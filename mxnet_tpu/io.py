"""Legacy data iterators.

Reference: ``python/mxnet/io/io.py`` (DataIter, DataBatch, NDArrayIter,
ResizeIter, PrefetchingIter) over the C++ iterator registry in ``src/io/``
(SURVEY.md §3.4).  The C++ threaded parser→batcher→prefetcher pipeline is
replaced by the Gluon DataLoader's thread-pool prefetch; these classes keep
the Module-era API surface.
"""
from __future__ import annotations

from collections import namedtuple

import numpy as _np

from .base import MXNetError
from .ndarray.ndarray import NDArray, array

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "ResizeIter",
           "PrefetchingIter", "ImageRecordIter"]


class DataDesc(namedtuple("DataDesc", ["name", "shape"])):
    def __new__(cls, name, shape, dtype=_np.float32, layout="NCHW"):
        ret = super().__new__(cls, name, shape)
        ret.dtype = dtype
        ret.layout = layout
        return ret


class DataBatch:
    def __init__(self, data, label=None, pad=None, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label


class DataIter:
    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self):
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self):
        raise NotImplementedError

    def getdata(self):
        raise NotImplementedError

    def getlabel(self):
        raise NotImplementedError

    def getindex(self):
        return None

    def getpad(self):
        raise NotImplementedError


def _init_data(data, allow_empty, default_name):
    if data is None:
        return []
    if isinstance(data, (NDArray, _np.ndarray)):
        data = [data]
    if isinstance(data, (list, tuple)):
        data = {f"{default_name}{'_' + str(i) if i else ''}": d
                for i, d in enumerate(data)} if len(data) > 1 else \
            ({default_name: data[0]} if data else {})
    out = []
    for k, v in data.items():
        if not isinstance(v, NDArray):
            v = array(_np.asarray(v))
        out.append((k, v))
    return out


class NDArrayIter(DataIter):
    """In-memory iterator (reference: mx.io.NDArrayIter)."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label"):
        super().__init__(batch_size)
        self.data = _init_data(data, False, data_name)
        self.label = _init_data(label, True, label_name)
        self.num_data = self.data[0][1].shape[0]
        self.cursor = -batch_size
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.last_batch_handle = last_batch_handle
        self.idx = _np.arange(self.num_data)
        if shuffle:
            _np.random.shuffle(self.idx)

    @property
    def provide_data(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.label]

    def reset(self):
        self.cursor = -self.batch_size
        if self.shuffle:
            _np.random.shuffle(self.idx)

    def iter_next(self):
        self.cursor += self.batch_size
        if self.last_batch_handle == "roll_over":
            return self.cursor < self.num_data
        if self.last_batch_handle == "discard":
            return self.cursor + self.batch_size <= self.num_data
        return self.cursor < self.num_data

    def _take(self, arrays):
        out = []
        for _, v in arrays:
            end = self.cursor + self.batch_size
            ids = self.idx[self.cursor:min(end, self.num_data)]
            batch = v.asnumpy()[ids]
            if len(ids) < self.batch_size:  # pad
                pad = self.batch_size - len(ids)
                batch = _np.concatenate([batch, batch[:pad]])
            out.append(array(batch))
        return out

    def getdata(self):
        return self._take(self.data)

    def getlabel(self):
        return self._take(self.label)

    def getpad(self):
        end = self.cursor + self.batch_size
        if end > self.num_data:
            return end - self.num_data
        return 0


class ResizeIter(DataIter):
    """Resize (truncate/loop) another iterator to size batches per epoch."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__(data_iter.batch_size)
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getpad(self):
        return self.current_batch.pad


class PrefetchingIter(DataIter):
    """Thread-prefetch wrapper (reference: mx.io.PrefetchingIter over
    dmlc::ThreadedIter)."""

    def __init__(self, iters, rename_data=None, rename_label=None):
        import threading
        import queue

        if not isinstance(iters, (list, tuple)):
            iters = [iters]
        if len(iters) != 1:
            raise MXNetError("PrefetchingIter here supports one base iter")
        self.iter = iters[0]
        super().__init__(self.iter.batch_size)
        self._queue = queue.Queue(maxsize=2)
        self._thread = None
        self._start()

    def _start(self):
        import threading

        def worker():
            while True:
                try:
                    batch = self.iter.next()
                except StopIteration:
                    self._queue.put(None)
                    return
                self._queue.put(batch)

        self._thread = threading.Thread(target=worker, daemon=True)
        self._thread.start()

    def reset(self):
        while self._thread.is_alive():
            try:
                if self._queue.get(timeout=0.1) is None:
                    break
            except Exception:
                break
        self.iter.reset()
        self._start()

    def next(self):
        batch = self._queue.get()
        if batch is None:
            raise StopIteration
        return batch

    def iter_next(self):
        raise NotImplementedError


class ImageRecordIter(DataIter):
    """Threaded image-record iterator (reference: src/io/iter_image_recordio_2.cc
    "ImageRecordIter" — shard reader → decode pool → batcher → prefetcher).

    TPU-native split: the C++ library (mxnet_tpu/native) owns file IO, record
    framing, num_parts/part_index sharding, epoch shuffling and prefetch;
    decode (PIL/numpy) and augmentation run here.  Supported record payloads:
    .npy-encoded arrays (recordio.pack_img default) and JPEG/PNG via PIL.
    """

    def __init__(self, path_imgrec, data_shape, batch_size, label_width=1,
                 shuffle=False, rand_crop=False, rand_mirror=False,
                 mean_r=0.0, mean_g=0.0, mean_b=0.0, std_r=1.0, std_g=1.0,
                 std_b=1.0, resize=-1, num_parts=1, part_index=0, seed=0,
                 round_batch=True, prefetch_buffer=4, data_name="data",
                 label_name="softmax_label", **kwargs):
        super().__init__(batch_size)
        from .native import NativeRecordReader
        from . import recordio as _rio

        self._rio = _rio
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self.rand_crop = rand_crop
        self.rand_mirror = rand_mirror
        self.resize = resize
        self.mean = _np.array([mean_r, mean_g, mean_b], dtype="float32")
        self.std = _np.array([std_r, std_g, std_b], dtype="float32")
        self.round_batch = round_batch
        self._rng = _np.random.RandomState(seed)
        self._reader = NativeRecordReader(
            path_imgrec, batch_size, num_parts=num_parts,
            part_index=part_index, shuffle=shuffle, seed=seed,
            queue_depth=prefetch_buffer)
        self._data_name = data_name
        self._label_name = label_name

    @property
    def provide_data(self):
        return [DataDesc(self._data_name,
                         (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        shape = ((self.batch_size,) if self.label_width == 1
                 else (self.batch_size, self.label_width))
        return [DataDesc(self._label_name, shape)]

    def reset(self):
        self._reader.reset()

    def _decode(self, payload):
        header, img = self._rio.unpack_img(payload)
        return self._augment(img), header.label

    def _augment(self, img):
        # img HWC uint8/float -> data_shape CHW float32
        c, h, w = self.data_shape
        if img.ndim == 2:
            img = img[:, :, None]
        # reconcile channel count with data_shape: gray->RGB replicate,
        # RGBA->drop alpha, RGB->gray luminance
        ic = img.shape[2]
        if ic != c:
            if ic == 1:
                img = _np.repeat(img, c, axis=2)
            elif ic == 4 and c == 3:
                img = img[:, :, :3]
            elif c == 1:
                img = img[:, :, :3].mean(axis=2, keepdims=True)
            else:
                raise MXNetError(
                    f"record has {ic} channels but data_shape wants {c}")
        if self.resize > 0:
            img = self._resize_short(img, self.resize)
        ih, iw = img.shape[:2]
        if self.rand_crop and ih >= h and iw >= w:
            y0 = self._rng.randint(0, ih - h + 1)
            x0 = self._rng.randint(0, iw - w + 1)
        else:
            y0 = max((ih - h) // 2, 0)
            x0 = max((iw - w) // 2, 0)
        img = img[y0:y0 + h, x0:x0 + w]
        if img.shape[0] != h or img.shape[1] != w:
            img = self._resize_exact(img, h, w)
        if self.rand_mirror and self._rng.rand() < 0.5:
            img = img[:, ::-1]
        data = img.astype("float32")
        nch = data.shape[2]
        data = (data - self.mean[:nch]) / self.std[:nch]
        return _np.transpose(data, (2, 0, 1))

    @staticmethod
    def _resize_short(img, size):
        from PIL import Image

        ih, iw = img.shape[:2]
        scale = size / min(ih, iw)
        nh, nw = int(round(ih * scale)), int(round(iw * scale))
        return _np.asarray(Image.fromarray(img.astype("uint8")).resize(
            (nw, nh), Image.BILINEAR))

    @staticmethod
    def _resize_exact(img, h, w):
        from PIL import Image

        return _np.asarray(Image.fromarray(img.astype("uint8")).resize(
            (w, h), Image.BILINEAR))

    def next(self):
        from .ndarray import array as _array

        payloads = self._reader.next_batch()
        if payloads is None:
            raise StopIteration
        imgs, labels = [], []
        for p in payloads:
            img, label = self._decode(p)
            imgs.append(img)
            labels.append(label)
        pad = self.batch_size - len(imgs)
        if pad > 0 and self.round_batch:
            # pad the tail batch with copies of the last record (reference
            # round_batch semantics); pad count lets callers mask them
            imgs.extend([imgs[-1]] * pad)
            labels.extend([labels[-1]] * pad)
        else:
            pad = 0
        data = _array(_np.stack(imgs))
        label = _array(_np.asarray(labels, dtype="float32"))
        return DataBatch(data=[data], label=[label], pad=pad)
