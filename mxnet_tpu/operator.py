"""User-defined operators: ``mx.operator.CustomOp`` / ``CustomOpProp`` /
``register`` and the ``mx.nd.Custom`` entry point.

Reference: ``python/mxnet/operator.py`` + ``src/operator/custom/custom.cc``
(SURVEY.md §3.2 custom-op row): users subclass CustomOp (imperative
forward/backward over NDArrays), describe it with a CustomOpProp
(arguments/outputs/shape/type inference), register it under an op_type
string, and call it as ``mx.nd.Custom(*data, op_type=...)``.

TPU-native execution model — two paths behind one API:

- **Eager** (concrete NDArray inputs): forward runs immediately as host
  Python, exactly like the reference's callback into the engine.  If
  autograd is recording, a tape node is created whose vjp is a callback
  into the user's ``backward`` — so ``.asnumpy()``/data-dependent Python in
  user code is fully supported, matching reference semantics.
- **Traced** (inside ``hybridize()``/``jit``): the op is staged as a
  ``jax.custom_vjp`` whose fwd/bwd run the user's methods over
  tracer-backed NDArrays.  User code must then be trace-compatible
  (NDArray math, no ``.asnumpy()``) — same restriction the reference's
  CachedOp imposes by bypassing custom ops' async callbacks.
"""
from __future__ import annotations

from .base import MXNetError

__all__ = ["CustomOp", "CustomOpProp", "register", "get_prop_registry"]

_PROP_REGISTRY = {}


class CustomOp:
    """Base class for user operator implementations."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError

    def assign(self, dst, req, src):
        """Write ``src`` into ``dst`` honoring the grad_req (reference:
        CustomOp::assign)."""
        if req == "null":
            return
        val = src._get() if hasattr(src, "_get") else src
        if req in ("write", "inplace"):
            dst._set(_coerce(val, dst))
        elif req == "add":
            dst._set(dst._get() + _coerce(val, dst))
        else:
            raise MXNetError(f"unknown req {req!r}")


def _coerce(val, dst):
    import jax.numpy as jnp

    v = jnp.asarray(val)
    return v.astype(dst.dtype) if str(v.dtype) != str(dst.dtype) else v


class CustomOpProp:
    """Describes a custom op: names, shapes, types, and operator factory."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def list_auxiliary_states(self):
        return []

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]] * len(self.list_outputs()), []

    def infer_type(self, in_type):
        t = in_type[0]
        return (in_type, [t] * len(self.list_outputs()),
                [t] * len(self.list_auxiliary_states()))

    def declare_backward_dependency(self, out_grad, in_data, out_data):
        deps = []
        if self.need_top_grad_:
            deps.extend(out_grad)
        deps.extend(in_data)
        deps.extend(out_data)
        return deps

    def create_operator(self, ctx, in_shapes, in_dtypes):
        return CustomOp()


def register(reg_name):
    """Register a CustomOpProp subclass under ``op_type=reg_name``
    (reference: mx.operator.register decorator)."""

    def _do(prop_cls):
        if not issubclass(prop_cls, CustomOpProp):
            raise MXNetError("register expects a CustomOpProp subclass")
        _PROP_REGISTRY[reg_name] = prop_cls
        return prop_cls

    return _do


def get_prop_registry():
    return dict(_PROP_REGISTRY)


# --------------------------------------------------------------------------
# the mx.nd.Custom entry point
# --------------------------------------------------------------------------
def _is_traced(vals):
    import jax

    return any(isinstance(v, jax.core.Tracer) for v in vals)


def custom(*inputs, op_type=None, **kwargs):
    """``mx.nd.Custom(*inputs, op_type='name', **prop_kwargs)``."""
    from . import autograd as _ag
    from .ndarray.ndarray import NDArray

    if op_type is None:
        raise MXNetError("Custom requires op_type=")
    prop_cls = _PROP_REGISTRY.get(op_type)
    if prop_cls is None:
        raise MXNetError(f"custom op {op_type!r} is not registered "
                         f"(known: {sorted(_PROP_REGISTRY)})")
    # the reference passes prop kwargs as strings through the C boundary;
    # here they arrive as-is
    prop = prop_cls(**kwargs)

    n_in = len(prop.list_arguments())
    n_aux = len(prop.list_auxiliary_states())
    n_out = len(prop.list_outputs())
    if len(inputs) != n_in + n_aux:
        raise MXNetError(
            f"custom op {op_type!r} expects {n_in} args + {n_aux} aux, "
            f"got {len(inputs)} inputs")
    in_nds = list(inputs[:n_in])
    aux_nds = list(inputs[n_in:])

    in_shapes = [tuple(a.shape) for a in in_nds]
    in_types = [_np_dtype(a) for a in in_nds]
    shapes = prop.infer_shape(in_shapes)
    out_shapes = list(shapes[1])
    types = prop.infer_type(in_types)
    out_types = list(types[1])

    ctx = in_nds[0].context if in_nds else None
    op = prop.create_operator(ctx, in_shapes, in_types)
    is_train = _ag.is_training()

    in_vals = [a._get() for a in in_nds]
    if _is_traced(in_vals + [a._get() for a in aux_nds]):
        return _custom_traced(op, prop, in_nds, aux_nds, out_shapes,
                              out_types, n_out, is_train, ctx)
    return _custom_eager(op, prop, in_nds, aux_nds, out_shapes, out_types,
                         n_out, is_train, ctx, op_type)


def _np_dtype(a):
    import numpy as np

    return np.dtype(str(a.dtype)) if not isinstance(a.dtype, np.dtype) \
        else a.dtype


def _alloc_outs(out_shapes, out_types, ctx):
    from .ndarray.ndarray import NDArray
    import jax.numpy as jnp

    return [NDArray._from_jax(jnp.zeros(s, dtype=t), ctx)
            for s, t in zip(out_shapes, out_types)]


def _custom_eager(op, prop, in_nds, aux_nds, out_shapes, out_types, n_out,
                  is_train, ctx, op_type):
    """Immediate host execution + manual tape node (callback backward)."""
    from . import autograd as _ag
    from .ndarray.ndarray import NDArray

    out_nds = _alloc_outs(out_shapes, out_types, ctx)
    req = ["write"] * n_out
    with _ag.pause():
        op.forward(is_train, req, in_nds, out_nds, aux_nds)

    recording = _ag.is_recording() and any(
        a._ag_entry is not None for a in in_nds)
    if recording:
        def backward_cb(out_grads):
            import jax.numpy as jnp

            in_grads = [NDArray._from_jax(jnp.zeros(a.shape, _np_dtype(a)),
                                          ctx) for a in in_nds]
            with _ag.pause():
                op.backward(["write"] * len(in_nds), out_grads, in_nds,
                            out_nds, in_grads, aux_nds)
            return list(in_grads) + [None] * len(aux_nds)

        _ag.record_callback_node(
            [a._ag_entry for a in in_nds] + [None] * len(aux_nds),
            out_nds, backward_cb, f"Custom:{op_type}", ctx)
    return out_nds[0] if n_out == 1 else tuple(out_nds)


def _custom_traced(op, prop, in_nds, aux_nds, out_shapes, out_types, n_out,
                   is_train, ctx):
    """Staged execution inside an enclosing jit trace: jax.custom_vjp whose
    fwd/bwd run the user's methods over tracer-backed NDArrays."""
    import jax
    from . import autograd as _ag
    from .ndarray.ndarray import NDArray

    n_in = len(in_nds)

    @jax.custom_vjp
    def fn(*vals):
        return _fwd(*vals)[0]

    def _fwd(*vals):
        ins = [NDArray._from_jax(v, ctx) for v in vals[:n_in]]
        auxs = [NDArray._from_jax(v, ctx) for v in vals[n_in:]]
        outs = _alloc_outs(out_shapes, out_types, ctx)
        with _ag.pause():
            op.forward(is_train, ["write"] * n_out, ins, outs, auxs)
        out_vals = tuple(o._get() for o in outs)
        return out_vals, (vals, out_vals)

    def _bwd(res, cots):
        in_vals, out_vals = res
        ins = [NDArray._from_jax(v, ctx) for v in in_vals[:n_in]]
        auxs = [NDArray._from_jax(v, ctx) for v in in_vals[n_in:]]
        outs = [NDArray._from_jax(v, ctx) for v in out_vals]
        out_grads = [NDArray._from_jax(c, ctx) for c in cots]
        import jax.numpy as jnp

        in_grads = [NDArray._from_jax(jnp.zeros(a.shape, _np_dtype(a)), ctx)
                    for a in ins]
        with _ag.pause():
            op.backward(["write"] * n_in, out_grads, ins, outs, in_grads,
                        auxs)
        return tuple(g._get() for g in in_grads) + \
            tuple(jnp.zeros(a.shape, _np_dtype(a)) for a in auxs)

    fn.defvjp(_fwd, _bwd)
    out_vals = fn(*[a._get() for a in in_nds + aux_nds])
    out_nds = [NDArray._from_jax(v, ctx) for v in out_vals]
    return out_nds[0] if n_out == 1 else tuple(out_nds)
