"""Unified runtime telemetry: metrics registry, step timeline, compile
tracing, and Prometheus/JSON exporters.

The reference stack's only visibility was a Chrome-trace profiler plus
ad-hoc counters (``dispatch_cache.stats()``, ``fault.stats()``).  This
module is the cross-cutting layer that makes a running job diagnosable:

- **Metrics registry**: process-wide, thread-safe Counter / Gauge /
  Histogram families with labels and exponential buckets.  Recording is
  always-on and cheap (one lock + dict update); nothing here sits on the
  per-op eager hot path — the dispatch cache and fault seams keep their
  own lock-striped counters and are *scraped* through collectors at
  export time instead of double-counting per call.
- **Step timeline**: ``step_begin()`` / ``phase(name)`` / ``step_end()``
  attribute each training step to phases (``data``, ``forward_backward``,
  ``optimizer``, ``collectives``, ``checkpoint``, ``other``).  Phases
  nest with *innermost-wins* attribution — the outer phase's clock pauses
  while an inner phase runs — so per-step phase durations always sum to
  the step's wall time.  Completed steps land in a bounded ring
  (``MXNET_TELEMETRY_TIMELINE_STEPS``, default 256) and, when the
  profiler is active, as ``step_phase`` spans in the Chrome trace.
- **Compile-event tracer**: every fresh ``jax.jit`` trace — a registry op
  (dispatch_cache miss), a hybridized block build, or a TrainStep — is
  recorded with its elapsed time and a *cause* (``new_op`` /
  ``new_shape`` / ``new_dtype`` / ``new_attrs`` / ``mode_change`` /
  ``recompile`` / ``trace_failure``), so retrace storms are diagnosable
  from the event stream instead of guessed from step-time jitter.
- **Exporters**: ``render_prometheus()`` (text exposition),
  ``snapshot()`` (JSON; also embedded in ``profiler.dump()`` otherData
  and ``bench.py`` extras), and an opt-in background HTTP endpoint
  (``MXNET_TELEMETRY_PORT`` or ``start_http_server(port)``) serving
  ``/metrics``, ``/snapshot``, and ``/healthz``.

Metric catalog (see README "Observability" for the full table): step
phases (``mxnet_step_phase_seconds``), compile events
(``mxnet_compile_events_total{kind,cause}``), dispatch cache
(``mxnet_dispatch_cache_*`` via collector), fault seams
(``mxnet_fault_seam_*_total{seam}`` via collector), DataLoader
(``mxnet_dataloader_batch_wait_seconds``, worker liveness), kvstore
traffic (``mxnet_kvstore_{push,pull}_bytes_total``), checkpoint
durations, and ``mxnet_recovery_restarts_total``.
"""
from __future__ import annotations

import json
import threading
import time
from collections import deque

from . import env as _env

__all__ = ["Counter", "Gauge", "Histogram", "counter", "gauge", "histogram",
           "exponential_buckets", "register_collector", "snapshot",
           "render_prometheus", "start_http_server", "stop_http_server",
           "register_http_route", "unregister_http_route",
           "step_begin", "step_end", "step_abort", "step_scope", "phase",
           "maybe_phase", "timeline", "compile_event", "compile_events",
           "goodput_note", "goodput_summary",
           "heartbeat", "last_heartbeat", "reset"]

_LOCK = threading.RLock()
_FAMILIES: dict = {}        # name -> _Family
_COLLECTORS: list = []      # zero-arg callables -> [family dict, ...]

# default duration buckets: 100µs .. ~13s, exponential
_TIME_BUCKETS = None  # filled after exponential_buckets is defined


def exponential_buckets(start, factor, count):
    """``count`` bucket upper bounds growing geometrically from ``start``
    (Prometheus-style; +Inf is implicit)."""
    out = []
    b = float(start)
    for _ in range(count):
        out.append(b)
        b *= factor
    return out


_TIME_BUCKETS = exponential_buckets(1e-4, 2.0, 18)


# --------------------------------------------------------------------------
# metric primitives
# --------------------------------------------------------------------------
class _Child:
    __slots__ = ("_value",)

    def __init__(self):
        self._value = 0.0


class Counter(_Child):
    """Monotonic counter (family child)."""

    def inc(self, amount=1.0):
        if amount < 0:
            raise ValueError("counters only go up")
        with _LOCK:
            self._value += amount

    @property
    def value(self):
        return self._value


class Gauge(_Child):
    """Settable value (family child)."""

    def set(self, value):
        with _LOCK:
            self._value = float(value)

    def inc(self, amount=1.0):
        with _LOCK:
            self._value += amount

    def dec(self, amount=1.0):
        with _LOCK:
            self._value -= amount

    @property
    def value(self):
        return self._value


class Histogram:
    """Histogram with cumulative-at-export buckets (family child)."""

    __slots__ = ("_buckets", "_counts", "_sum", "_count")

    def __init__(self, buckets=None):
        bs = sorted(float(b) for b in (buckets or _TIME_BUCKETS))
        self._buckets = bs
        self._counts = [0] * len(bs)
        self._sum = 0.0
        self._count = 0

    def observe(self, value):
        v = float(value)
        with _LOCK:
            self._sum += v
            self._count += 1
            for i, b in enumerate(self._buckets):
                if v <= b:
                    self._counts[i] += 1
                    break

    @property
    def count(self):
        return self._count

    @property
    def sum(self):
        return self._sum

    def cumulative(self):
        """[(upper_bound, cumulative_count), ...] ending with +Inf."""
        out = []
        acc = 0
        with _LOCK:
            for b, c in zip(self._buckets, self._counts):
                acc += c
                out.append((b, acc))
            out.append((float("inf"), self._count))
        return out


class _Family:
    """A named metric family with fixed label names; children per label
    value tuple.  Unlabeled families proxy their single ``()`` child."""

    def __init__(self, name, help, mtype, labelnames=(), buckets=None):
        self.name = name
        self.help = help
        self.type = mtype
        self.labelnames = tuple(labelnames)
        self._buckets = buckets
        self._children: dict = {}
        if not self.labelnames:
            self._children[()] = self._new_child()

    def _new_child(self):
        if self.type == "counter":
            return Counter()
        if self.type == "gauge":
            return Gauge()
        return Histogram(self._buckets)

    def labels(self, *values, **kw):
        if kw:
            if values:
                raise ValueError("pass labels positionally or by name")
            values = tuple(str(kw[n]) for n in self.labelnames)
        else:
            values = tuple(str(v) for v in values)
        if len(values) != len(self.labelnames):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, "
                f"got {values}")
        with _LOCK:
            child = self._children.get(values)
            if child is None:
                child = self._children[values] = self._new_child()
            return child

    # unlabeled convenience proxies
    def inc(self, amount=1.0):
        self._children[()].inc(amount)

    def set(self, value):
        self._children[()].set(value)

    def dec(self, amount=1.0):
        self._children[()].dec(amount)

    def observe(self, value):
        self._children[()].observe(value)

    @property
    def value(self):
        return self._children[()].value

    @property
    def count(self):
        return self._children[()].count

    @property
    def sum(self):
        return self._children[()].sum

    def cumulative(self):
        return self._children[()].cumulative()

    def remove(self, *values, **kw):
        """Drop one labeled child (stale-series cleanup — e.g. a
        re-published sharding plan's obsolete per-param rows; no-op when
        the label set was never created)."""
        if kw:
            if values:
                raise ValueError("pass labels positionally or by name")
            values = tuple(str(kw[n]) for n in self.labelnames)
        else:
            values = tuple(str(v) for v in values)
        with _LOCK:
            self._children.pop(values, None)

    def children(self):
        with _LOCK:
            return list(self._children.items())


def _get_or_create(name, help, mtype, labelnames=(), buckets=None):
    with _LOCK:
        fam = _FAMILIES.get(name)
        if fam is not None:
            if fam.type != mtype or fam.labelnames != tuple(labelnames):
                raise ValueError(
                    f"metric {name!r} re-registered with a different "
                    f"type/labels ({fam.type}{fam.labelnames} vs "
                    f"{mtype}{tuple(labelnames)})")
            return fam
        fam = _Family(name, help, mtype, labelnames, buckets)
        _FAMILIES[name] = fam
        return fam


def counter(name, help="", labelnames=()):
    """Get-or-create a Counter family."""
    return _get_or_create(name, help, "counter", labelnames)


def gauge(name, help="", labelnames=()):
    """Get-or-create a Gauge family."""
    return _get_or_create(name, help, "gauge", labelnames)


def histogram(name, help="", labelnames=(), buckets=None):
    """Get-or-create a Histogram family (default: exponential duration
    buckets 100µs..13s)."""
    return _get_or_create(name, help, "histogram", labelnames, buckets)


def register_collector(fn):
    """Register a zero-arg callable run at export time returning a list of
    ``{"name", "type", "help", "samples": [(labels_dict, value), ...]}``
    dicts — the scrape-time bridge for subsystems that keep their own
    counters (dispatch cache, fault seams) so their hot paths never pay a
    second lock."""
    with _LOCK:
        _COLLECTORS.append(fn)
    return fn


# --------------------------------------------------------------------------
# step timeline
# --------------------------------------------------------------------------
_TIMELINE_CAP = max(1, _env.get_int("MXNET_TELEMETRY_TIMELINE_STEPS", 256))
_STEPS: deque = deque(maxlen=_TIMELINE_CAP)
_CUR = None          # active step: {"step", "t0", "wall0", "phases", "stack"}
_STEP_SEQ = [0]

_PHASE_HIST = histogram(
    "mxnet_step_phase_seconds",
    "per-step time attributed to each phase (exclusive of nested phases)",
    labelnames=("phase",))
_STEP_HIST = histogram("mxnet_step_seconds", "training step wall time")
_STEPS_TOTAL = counter("mxnet_steps_total", "completed timeline steps")

# goodput ledger: wall time classified into what the job was DOING.
# "productive" accrues automatically from the step timeline (step wall
# minus any in-step checkpoint phase); the non-productive buckets are
# noted by the lifecycle/recovery seams that own them — checkpoint
# saves, run_with_recovery restart downtime, live resharding transfers,
# watchdog-diagnosed stalls, and numerical-integrity rewinds (time lost
# to wrong VALUES rather than lost processes; mxnet_tpu/guard.py).
# The ratio gauge is computed at export time by a collector so
# recording stays one counter add.
_GOODPUT = counter(
    "mxnet_goodput_seconds_total",
    "wall time by goodput bucket (productive = step wall minus in-step "
    "checkpoint time; checkpoint/restart/reshard/stall/rewind noted by "
    "their owning seams)", labelnames=("bucket",))


def goodput_note(bucket, seconds):
    """Charge ``seconds`` of wall time to a goodput ``bucket``
    (``checkpoint`` / ``restart`` / ``reshard`` / ``stall`` /
    ``rewind`` / caller-defined).  ``productive`` accrues automatically
    from the step timeline — loops never call this themselves."""
    if seconds > 0:
        _GOODPUT.labels(bucket=str(bucket)).inc(float(seconds))


def goodput_summary():
    """``{"buckets": {...seconds...}, "tracked_s", "productive_ratio"}``
    — productive wall time over everything the ledger has classified
    (``productive_ratio`` is None until anything was tracked)."""
    buckets = {}
    for values, child in _GOODPUT.children():
        buckets[values[0]] = child.value
    total = sum(buckets.values())
    prod = buckets.get("productive", 0.0)
    return {"buckets": buckets, "tracked_s": total,
            "productive_ratio": (prod / total) if total > 0 else None}


def _goodput_collector():
    s = goodput_summary()
    if s["productive_ratio"] is None:
        return []
    return [{"name": "mxnet_goodput_ratio", "type": "gauge",
             "help": "productive wall time over all ledger-classified "
                     "time (goodput)",
             "samples": [({}, s["productive_ratio"])]}]


def _chrome_span(name, t0, t1, cat):
    try:
        from . import profiler as _prof

        _prof._record_span(name, t0, t1, cat)
    except Exception:
        pass


def _flight_note(kind, **fields):
    """Context event into the flight-recorder ring (step boundaries,
    compile events) — lazy + failure-tolerant like ``_agg_tick``; a
    disabled recorder costs one module-dict lookup and a bool read."""
    try:
        from . import flight_recorder as _flight

        _flight.record_event(kind, **fields)
    except Exception:
        pass


# -- goodput SLO alerting (ROADMAP follow-on (d)) ---------------------------
# a WINDOW is one completed timeline step: at each step_end the DELTA
# of the goodput ledger since the previous step is classified, and
# MXNET_GOODPUT_SLO_WINDOWS consecutive windows below MXNET_GOODPUT_SLO
# fire one alert (lifecycle event + counter + flight-recorder entry).
# The alert re-arms only after a window back at/above the SLO, so a
# sustained degradation fires once, not every step.
_SLO_BREACHES = counter(
    "mxnet_goodput_slo_breaches_total",
    "goodput-SLO alerts: productive ratio below MXNET_GOODPUT_SLO for "
    "MXNET_GOODPUT_SLO_WINDOWS consecutive windows")
_SLO_STATE = {"last": None, "below": 0, "fired": False}


def _goodput_slo_tick():
    slo = _env.goodput_slo()
    if slo <= 0:
        return
    s = goodput_summary()
    cur = (s["tracked_s"], s["buckets"].get("productive", 0.0))
    last = _SLO_STATE["last"]
    _SLO_STATE["last"] = cur
    if last is None:
        return
    d_total = cur[0] - last[0]
    d_prod = cur[1] - last[1]
    if d_total <= 0:
        return          # nothing classified since the last boundary
    ratio = d_prod / d_total
    if ratio >= slo:
        _SLO_STATE["below"] = 0
        _SLO_STATE["fired"] = False
        return
    _SLO_STATE["below"] += 1
    if _SLO_STATE["fired"] or \
            _SLO_STATE["below"] < _env.goodput_slo_windows():
        return
    _SLO_STATE["fired"] = True
    _SLO_BREACHES.inc()
    try:
        from . import lifecycle as _lc

        _lc.note_goodput_slo_breach(ratio, slo, _SLO_STATE["below"])
    except Exception:   # alerting must never break a step boundary
        pass


# step heartbeat: monotonic timestamp of the last step-boundary activity
# (step_begin/step_end, or an explicit heartbeat() from a custom loop /
# lifecycle.check_stop).  The lifecycle watchdog reads it to enforce a
# per-step deadline; None = no step activity yet this process.
_HEARTBEAT = [None]


def heartbeat():
    """Mark step-boundary liveness for the stall watchdog
    (:mod:`mxnet_tpu.lifecycle`).  Cheap: one monotonic read + store."""
    _HEARTBEAT[0] = time.monotonic()


def last_heartbeat():
    """Monotonic time of the last heartbeat, or None."""
    return _HEARTBEAT[0]


def step_begin(step=None):
    """Open a timeline step.  An unfinished previous step is finalized
    first (robustness beats strictness in a training loop)."""
    global _CUR
    heartbeat()
    with _LOCK:
        if _CUR is not None:
            _finalize_locked(time.perf_counter())
        if step is None:
            step = _STEP_SEQ[0]
        step = int(step)
        _STEP_SEQ[0] = step + 1
        _CUR = {"step": step, "t0": time.perf_counter(),
                "wall0": time.time(), "phases": {}, "stack": []}
    # return the local, not _CUR["step"]: a concurrent step_end/abort may
    # have nulled _CUR the instant the lock dropped
    _flight_note("step", event="begin", step=step)
    return step


def _finalize_locked(now):
    """Complete the active step (lock held).  Returns the record."""
    global _CUR
    cur = _CUR
    _CUR = None
    if cur is None:
        return None
    stack = cur["stack"]
    if stack:
        # only the innermost frame has unclaimed elapsed time: every outer
        # frame was charged (and left paused) when its inner frame entered
        name, t = stack[-1]
        cur["phases"][name] = cur["phases"].get(name, 0.0) + (now - t)
        del stack[:]
    wall = now - cur["t0"]
    phases = cur["phases"]
    other = wall - sum(phases.values())
    if other > 1e-9:
        phases["other"] = other
    rec = {"step": cur["step"], "time": cur["wall0"],
           "wall_s": wall, "phases": dict(phases)}
    _STEPS.append(rec)
    for pname, dt in phases.items():
        _PHASE_HIST.labels(phase=pname).observe(dt)
    _STEP_HIST.observe(wall)
    _STEPS_TOTAL.inc()
    # goodput: a step is productive time EXCEPT what it spent inside a
    # checkpoint save (that phase is charged to the checkpoint bucket by
    # the save path itself — charging it here too would double-count)
    prod = wall - phases.get("checkpoint", 0.0)
    if prod > 0:
        _GOODPUT.labels(bucket="productive").inc(prod)
    _chrome_span(f"step {cur['step']}", cur["t0"], now, "step")
    return rec


def step_end():
    """Close the active step; returns its record (phase durations sum to
    the step wall time — unattributed time lands in ``other``)."""
    heartbeat()
    with _LOCK:
        rec = _finalize_locked(time.perf_counter())
    if rec is not None:
        _flight_note("step", event="end", step=rec["step"],
                     wall_s=rec["wall_s"])
    _goodput_slo_tick()
    _agg_tick()
    return rec


def _agg_tick():
    """Cross-rank aggregation stride hook: every completed step (and
    every ``lifecycle.check_stop``) advances the aggregator's tick
    counter; every ``MXNET_TELEMETRY_AGG_EVERY``-th tick publishes this
    rank's snapshot and (on rank 0) merges the peers'.  Pure host-side
    file IO — NEVER a device collective — so it is safe at any stride
    and cannot desync SPMD peers.  A disabled aggregator costs one
    module-dict lookup and an int check."""
    try:
        from . import telemetry_agg as _agg

        _agg.tick()
    except Exception:   # aggregation must never break a step boundary
        pass


def step_abort():
    """Discard the active step without recording (e.g. the data phase hit
    StopIteration — there is no step)."""
    global _CUR
    with _LOCK:
        _CUR = None


class _PhaseScope:
    __slots__ = ("name", "_t0")

    def __init__(self, name):
        self.name = name
        self._t0 = None

    def __enter__(self):
        now = time.perf_counter()
        self._t0 = now
        with _LOCK:
            cur = _CUR
            if cur is not None:
                stack = cur["stack"]
                if stack:
                    # pause the outer phase: charge it up to now
                    oname, ot = stack[-1]
                    cur["phases"][oname] = \
                        cur["phases"].get(oname, 0.0) + (now - ot)
                    stack[-1][1] = now
                stack.append([self.name, now])
        return self

    def __exit__(self, *exc):
        now = time.perf_counter()
        with _LOCK:
            cur = _CUR
            if cur is not None and cur["stack"] \
                    and cur["stack"][-1][0] == self.name:
                _, t = cur["stack"].pop()
                cur["phases"][self.name] = \
                    cur["phases"].get(self.name, 0.0) + (now - t)
                if cur["stack"]:
                    cur["stack"][-1][1] = now  # outer phase resumes
            elif cur is None:
                # phase outside a step: still observable in the histogram
                _PHASE_HIST.labels(phase=self.name).observe(now - self._t0)
        _chrome_span(f"phase:{self.name}", self._t0, now, "step_phase")
        return False


def phase(name):
    """Context manager attributing its (exclusive) duration to ``name`` in
    the active step; outside a step it records straight to the phase
    histogram."""
    return _PhaseScope(name)


class _NullScope:
    """Reusable no-op context for call sites with an opt-in telemetry flag
    (Trainer/Estimator): the disabled path pays one attribute read."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SCOPE = _NullScope()


def maybe_phase(enabled, name):
    """``phase(name)`` when ``enabled``, else a shared no-op scope."""
    return _PhaseScope(name) if enabled else _NULL_SCOPE


class _StepScope:
    def __init__(self, step):
        self._step = step

    def __enter__(self):
        return step_begin(self._step)

    def __exit__(self, *exc):
        step_end()
        return False


def step_scope(step=None):
    """``with telemetry.step_scope(): ...`` — begin/end a timeline step."""
    return _StepScope(step)


def timeline():
    """Completed step records, oldest first (bounded ring)."""
    with _LOCK:
        return [dict(r, phases=dict(r["phases"])) for r in _STEPS]


# --------------------------------------------------------------------------
# compile-event tracer
# --------------------------------------------------------------------------
_COMPILE_CAP = max(1, _env.get_int("MXNET_TELEMETRY_COMPILE_EVENTS", 512))
_COMPILE_EVENTS: deque = deque(maxlen=_COMPILE_CAP)

_COMPILES_TOTAL = counter(
    "mxnet_compile_events_total",
    "fresh jax.jit traces by kind (op/block/train_step) and cause",
    labelnames=("kind", "cause"))
_COMPILE_HIST = histogram(
    "mxnet_compile_seconds",
    "elapsed trace+compile (+first run for ops) per fresh jit",
    labelnames=("kind",))


def compile_event(kind, name, elapsed_s, cause, **extra):
    """Record one fresh jit trace.  ``kind``: ``op`` (dispatch cache miss),
    ``block`` (hybridized Gluon block build), ``train_step``,
    ``graph_pass`` (one graph-compiler pass application — ``extra``
    carries ``nodes_before``/``nodes_after``).  ``cause`` names why a
    new executable was needed (``new_op``/``new_shape``/``new_dtype``/
    ``new_attrs``/``mode_change``/``recompile``/``trace_failure``/...).
    Extra keyword fields land verbatim on the event record."""
    now = time.perf_counter()
    with _LOCK:
        _COMPILE_EVENTS.append(dict({"kind": kind, "name": name,
                                     "elapsed_s": float(elapsed_s),
                                     "cause": cause, "time": time.time()},
                                    **extra))
    _COMPILES_TOTAL.labels(kind=kind, cause=cause).inc()
    _COMPILE_HIST.labels(kind=kind).observe(elapsed_s)
    _flight_note("compile", name=str(name), compile_kind=str(kind),
                 cause=str(cause), elapsed_s=float(elapsed_s))
    _chrome_span(f"compile:{kind}:{name}", now - float(elapsed_s), now,
                 "compile")


def compile_events():
    """Recorded compile events, oldest first (bounded ring)."""
    with _LOCK:
        return [dict(e) for e in _COMPILE_EVENTS]


# --------------------------------------------------------------------------
# built-in collectors: dispatch cache + fault seams (scraped, not mirrored)
# --------------------------------------------------------------------------
def _dispatch_cache_collector():
    from .ndarray import dispatch_cache as _dc

    s = _dc.stats()
    def fam(name, mtype, help, value):
        return {"name": name, "type": mtype, "help": help,
                "samples": [({}, value)]}
    return [
        fam("mxnet_dispatch_cache_hits_total", "counter",
            "eager jit-cache hits", s["hits"]),
        fam("mxnet_dispatch_cache_misses_total", "counter",
            "eager jit-cache misses (fresh compiles)", s["misses"]),
        fam("mxnet_dispatch_cache_evictions_total", "counter",
            "eager jit-cache LRU evictions", s["evictions"]),
        fam("mxnet_dispatch_cache_bypasses_total", "counter",
            "eager jit-cache bypasses (unhashable/tracer/blocked)",
            s["bypasses"]),
        fam("mxnet_dispatch_cache_size", "gauge",
            "cached executables", s["size"]),
        fam("mxnet_dispatch_cache_capacity", "gauge",
            "executable LRU capacity", s["capacity"]),
        fam("mxnet_dispatch_cache_enabled", "gauge",
            "1 while the eager jit fast path is on", int(s["enabled"])),
    ]


def _fault_collector():
    from . import fault as _fault

    s = _fault.stats()
    out = []
    for metric, help in (("calls", "seam traversals"),
                         ("trips", "injected/observed seam failures"),
                         ("retries", "transient-error retries")):
        out.append({
            "name": f"mxnet_fault_seam_{metric}_total", "type": "counter",
            "help": help,
            "samples": [({"seam": seam}, c[metric])
                        for seam, c in sorted(s.items())]})
    return out


register_collector(_dispatch_cache_collector)
register_collector(_fault_collector)
register_collector(_goodput_collector)


# --------------------------------------------------------------------------
# exporters
# --------------------------------------------------------------------------
def _collected_families():
    with _LOCK:
        collectors = list(_COLLECTORS)
    out = []
    for fn in collectors:
        try:
            out.extend(fn())
        except Exception:   # a broken collector must not kill the scrape
            continue
    return out


def _escape_label(v):
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def _fmt_labels(labels):
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape_label(v)}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def _fmt_value(v):
    if v == float("inf"):
        return "+Inf"
    f = float(v)
    return repr(int(f)) if f == int(f) else repr(f)


def render_prometheus():
    """Prometheus text exposition (version 0.0.4) of every registered
    family plus collector output."""
    lines = []
    with _LOCK:
        families = list(_FAMILIES.values())
    for fam in families:
        lines.append(f"# HELP {fam.name} {fam.help}")
        lines.append(f"# TYPE {fam.name} {fam.type}")
        for values, child in fam.children():
            labels = dict(zip(fam.labelnames, values))
            if fam.type == "histogram":
                for le, cum in child.cumulative():
                    bl = dict(labels)
                    bl["le"] = _fmt_value(le)
                    lines.append(f"{fam.name}_bucket{_fmt_labels(bl)} {cum}")
                lines.append(f"{fam.name}_sum{_fmt_labels(labels)} "
                             f"{_fmt_value(child.sum)}")
                lines.append(f"{fam.name}_count{_fmt_labels(labels)} "
                             f"{child.count}")
            else:
                lines.append(f"{fam.name}{_fmt_labels(labels)} "
                             f"{_fmt_value(child.value)}")
    for fd in _collected_families():
        lines.append(f"# HELP {fd['name']} {fd.get('help', '')}")
        lines.append(f"# TYPE {fd['name']} {fd['type']}")
        for labels, value in fd["samples"]:
            lines.append(f"{fd['name']}{_fmt_labels(labels)} "
                         f"{_fmt_value(value)}")
    return "\n".join(lines) + "\n"


def snapshot():
    """JSON-able snapshot: every metric family (registered + collected),
    the step timeline, compile events, and aggregate summaries.  Embedded
    in ``profiler.dump()`` otherData and ``bench.py`` extras."""
    metrics = {}
    with _LOCK:
        families = list(_FAMILIES.values())
    for fam in families:
        samples = []
        for values, child in fam.children():
            labels = dict(zip(fam.labelnames, values))
            if fam.type == "histogram":
                samples.append({
                    "labels": labels,
                    "buckets": {_fmt_value(le): cum
                                for le, cum in child.cumulative()},
                    "sum": child.sum, "count": child.count})
            else:
                samples.append({"labels": labels, "value": child.value})
        metrics[fam.name] = {"type": fam.type, "help": fam.help,
                             "samples": samples}
    for fd in _collected_families():
        metrics[fd["name"]] = {
            "type": fd["type"], "help": fd.get("help", ""),
            "samples": [{"labels": labels, "value": value}
                        for labels, value in fd["samples"]]}
    steps = timeline()
    phase_totals: dict = {}
    for rec in steps:
        for pname, dt in rec["phases"].items():
            phase_totals[pname] = phase_totals.get(pname, 0.0) + dt
    events = compile_events()
    # totals come from the counter/histogram families, NOT the bounded
    # event ring: in a long retrace storm the ring keeps only the tail —
    # the diagnosis payload must not understate compile pressure exactly
    # when it is worst
    with _LOCK:
        n_compiles = sum(c.value
                         for _, c in _COMPILES_TOTAL.children())
        compile_s = sum(h.sum for _, h in _COMPILE_HIST.children())
    return {
        "time": time.time(),
        "metrics": metrics,
        "steps": steps,
        "step_phase_totals": phase_totals,
        "compile_events": events,
        "compile": {"count": int(n_compiles), "total_s": compile_s,
                    "events_kept": len(events)},
        "goodput": goodput_summary(),
        "graph": _graph_section(),
    }


def _graph_section():
    """Graph-compiler pipeline stats (pipeline runs, per-pass node
    deltas, fused-op count).  Import is lazy and failure-tolerant: the
    snapshot must work before (or without) the graph tier loading."""
    try:
        from .graph import stats_snapshot as _gs

        return _gs()
    except Exception:
        return {}


def reset():
    """Zero every registered family and clear the timeline + compile ring
    (test isolation; collectors' sources have their own reset_stats)."""
    global _CUR
    with _LOCK:
        for fam in _FAMILIES.values():
            for values in list(fam._children):
                fam._children[values] = fam._new_child()
            if not fam.labelnames:
                fam._children.setdefault((), fam._new_child())
        _STEPS.clear()
        _COMPILE_EVENTS.clear()
        _CUR = None
        _STEP_SEQ[0] = 0
        _HEARTBEAT[0] = None
        _SLO_STATE.update(last=None, below=0, fired=False)


# --------------------------------------------------------------------------
# HTTP endpoint (opt-in: MXNET_TELEMETRY_PORT or start_http_server)
# --------------------------------------------------------------------------
_HTTP_SERVER = None
_HTTP_THREAD = None
_HTTP_ROUTES: dict = {}   # path -> handler(method, path, query, body_bytes)


def register_http_route(path, handler):
    """Mount an application route on the telemetry endpoint.

    ``handler(method, path, query, body_bytes) -> (status, content_type,
    body_bytes[, headers_dict])`` is called for GET and POST requests
    whose path matches exactly; the optional 4th element carries extra
    response headers (the fleet router's 429 Retry-After rides it).
    This is how the serving plane (:mod:`mxnet_tpu.serving`)
    exposes its inference API beside ``/metrics`` — one 127.0.0.1 server
    per process, one port to scrape and to query.  Routes registered
    after the server started are live immediately (the handler resolves
    them per request).  Built-in paths (``/metrics``, ``/snapshot``,
    ``/healthz``) cannot be shadowed."""
    with _LOCK:
        _HTTP_ROUTES[path] = handler


def unregister_http_route(path):
    """Remove a mounted route (idempotent)."""
    with _LOCK:
        _HTTP_ROUTES.pop(path, None)


def _dispatch_route(method, path, query, body):
    with _LOCK:
        handler = _HTTP_ROUTES.get(path)
    if handler is None:
        return None
    try:
        return handler(method, path, query, body)
    except Exception as e:   # a broken app route must not kill the server
        return (500, "text/plain",
                f"route {path} failed: {e!r}\n".encode())


def start_http_server(port=None, addr="127.0.0.1"):
    """Serve ``/metrics`` (Prometheus text), ``/snapshot`` (JSON), and
    ``/healthz`` on a daemon thread.  ``port=0`` picks a free port; the
    bound port is on the returned server (``server_address[1]``).
    Idempotent: a second call returns the running server."""
    global _HTTP_SERVER, _HTTP_THREAD
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    if port is None:
        port = _env.get_int("MXNET_TELEMETRY_PORT", 0)

    class _Handler(BaseHTTPRequestHandler):
        def _reply(self, status, ctype, body, headers=None):
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, str(v))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            path, _, query = self.path.partition("?")
            if path in ("/metrics", "/"):
                self._reply(200, "text/plain; version=0.0.4; charset=utf-8",
                            render_prometheus().encode())
            elif path in ("/snapshot", "/json"):
                self._reply(200, "application/json",
                            json.dumps(snapshot()).encode())
            elif path == "/healthz":
                self._reply(200, "text/plain", b"ok\n")
            else:
                out = _dispatch_route("GET", path, query, b"")
                if out is None:
                    self.send_response(404)
                    self.end_headers()
                    return
                self._reply(*out)

        def do_POST(self):
            path, _, query = self.path.partition("?")
            length = int(self.headers.get("Content-Length") or 0)
            body = self.rfile.read(length) if length else b""
            out = _dispatch_route("POST", path, query, body)
            if out is None:
                self.send_response(404)
                self.end_headers()
                return
            self._reply(*out)

        def log_message(self, *a):   # no per-scrape stderr spam
            pass

    # check-and-create under one lock section: two racing callers must not
    # each bind a server (the loser's socket/thread would leak unreachable)
    with _LOCK:
        if _HTTP_SERVER is not None:
            return _HTTP_SERVER
        server = ThreadingHTTPServer((addr, int(port)), _Handler)
        server.daemon_threads = True
        thread = threading.Thread(target=server.serve_forever,
                                  name="mxnet-telemetry-http", daemon=True)
        thread.start()
        _HTTP_SERVER, _HTTP_THREAD = server, thread
        return server


def stop_http_server():
    """Shut the background endpoint down (idempotent)."""
    global _HTTP_SERVER, _HTTP_THREAD
    with _LOCK:
        server, thread = _HTTP_SERVER, _HTTP_THREAD
        _HTTP_SERVER = _HTTP_THREAD = None
    if server is not None:
        server.shutdown()
        server.server_close()
    if thread is not None:
        thread.join(timeout=5)
