"""mx.rnn — the legacy symbol-level RNN cell API.

Reference: ``python/mxnet/rnn/rnn_cell.py`` (BaseRNNCell/RNNCell/LSTMCell/
GRUCell/SequentialRNNCell + unroll — the API the BucketingModule language
-model examples are written against; SURVEY.md §3.2 RNN row).  The Gluon
cells (`mx.gluon.rnn`) are the imperative successors; these stage Symbol
graphs so `mx.mod.BucketingModule` scripts keep working.

TPU note: an unrolled cell graph jits into one XLA program per bucket
length — the same compiled-once-per-bucket behavior the reference's
BucketingModule executors had.
"""
from __future__ import annotations

from ..base import MXNetError
from .. import initializer as _init
from .. import symbol as _sym

__all__ = ["BaseRNNCell", "RNNCell", "LSTMCell", "GRUCell",
           "SequentialRNNCell", "FusedRNNCell", "BucketSentenceIter"]


class BaseRNNCell:
    """Abstract RNN cell over Symbols (reference: rnn_cell.BaseRNNCell)."""

    def __init__(self, prefix=""):
        self._prefix = prefix
        self._params = {}
        self._counter = 0

    def _get_param(self, name, init=None):
        full = self._prefix + name
        if full not in self._params:
            self._params[full] = _sym.var(full, init=init)
        return self._params[full]

    @property
    def params(self):
        return dict(self._params)

    @property
    def state_info(self):
        raise NotImplementedError

    def __call__(self, inputs, states):
        raise NotImplementedError

    def reset(self):
        self._counter = 0

    def begin_state(self, func=None, **kwargs):
        """Zero initial states, shaped off the data symbol at unroll time.

        The reference builds ``sym.zeros`` with deferred shapes; here the
        states are materialized inside :meth:`unroll` from the first input
        (``zeros_like``-style), so ``begin_state()`` returns placeholders
        that unroll recognizes."""
        return [None] * len(self.state_info)

    def _zero_states(self, in_sym):
        # zeros_like -> slice -> tile: pure shape plumbing, so inf/NaN in
        # the data cannot poison the initial state (sum(x)*0 would)
        z = _sym.slice_axis(_sym.zeros_like(in_sym), axis=-1, begin=0, end=1)
        return [_sym.tile(z, reps=(1, info["num_hidden"]))
                for info in self.state_info]

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        """Unroll ``length`` steps (reference: BaseRNNCell.unroll).

        inputs: a Symbol of shape (N, T, C) for layout 'NTC' (or (T, N, C)
        for 'TNC'), or a list of per-step symbols."""
        self.reset()
        if isinstance(inputs, (list, tuple)):
            if len(inputs) != length:
                raise MXNetError(f"unroll: got {len(inputs)} input symbols "
                                 f"for length {length}")
            seq = list(inputs)
        else:
            axis = 1 if layout == "NTC" else 0
            seq = [_sym.squeeze(
                _sym.slice_axis(inputs, axis=axis, begin=t, end=t + 1),
                axis=axis) for t in range(length)]
        states = begin_state
        if states is None or any(s is None for s in states):
            states = self._zero_states(seq[0])
        outputs = []
        for t in range(length):
            out, states = self(seq[t], states)
            outputs.append(out)
        if merge_outputs:
            # stack on the T axis of the requested layout: axis 1 for NTC,
            # axis 0 for TNC (reference: BaseRNNCell.unroll's
            # layout.find('T') axis selection)
            t_axis = 1 if layout == "NTC" else 0
            outputs = _sym.Concat(
                *[_sym.expand_dims(o, axis=t_axis) for o in outputs],
                dim=t_axis)
        return outputs, states


class RNNCell(BaseRNNCell):
    """Vanilla tanh/relu cell (reference: rnn_cell.RNNCell)."""

    def __init__(self, num_hidden, activation="tanh", prefix="rnn_"):
        super().__init__(prefix)
        self._num_hidden = num_hidden
        self._activation = activation

    @property
    def state_info(self):
        return [{"num_hidden": self._num_hidden}]

    def __call__(self, inputs, states):
        name = f"{self._prefix}t{self._counter}_"
        i2h = _sym.FullyConnected(inputs, self._get_param("i2h_weight"),
                                  self._get_param("i2h_bias"),
                                  num_hidden=self._num_hidden,
                                  name=name + "i2h")
        h2h = _sym.FullyConnected(states[0], self._get_param("h2h_weight"),
                                  self._get_param("h2h_bias"),
                                  num_hidden=self._num_hidden,
                                  name=name + "h2h")
        out = _sym.Activation(i2h + h2h, act_type=self._activation,
                              name=name + "out")
        self._counter += 1
        return out, [out]


class LSTMCell(BaseRNNCell):
    """LSTM (reference: rnn_cell.LSTMCell — gate order i, f, c, o)."""

    def __init__(self, num_hidden, prefix="lstm_", forget_bias=1.0):
        super().__init__(prefix)
        self._num_hidden = num_hidden
        self._forget_bias = forget_bias

    @property
    def state_info(self):
        return [{"num_hidden": self._num_hidden},
                {"num_hidden": self._num_hidden}]

    def __call__(self, inputs, states):
        name = f"{self._prefix}t{self._counter}_"
        nh = self._num_hidden
        # forget_bias is baked into the i2h_bias initializer (reference:
        # LSTMBiasInit parameterization) — NOT added in the forward pass,
        # so reference-trained .params load without a gate shift
        i2h = _sym.FullyConnected(
            inputs, self._get_param("i2h_weight"),
            self._get_param("i2h_bias",
                            init=_init.LSTMBias(self._forget_bias)),
            num_hidden=nh * 4, name=name + "i2h")
        h2h = _sym.FullyConnected(states[0], self._get_param("h2h_weight"),
                                  self._get_param("h2h_bias"),
                                  num_hidden=nh * 4, name=name + "h2h")
        gates = i2h + h2h
        sliced = _sym.SliceChannel(gates, num_outputs=4, axis=1,
                                   name=name + "slice")
        in_gate = _sym.Activation(sliced[0], act_type="sigmoid")
        forget_gate = _sym.Activation(sliced[1], act_type="sigmoid")
        in_trans = _sym.Activation(sliced[2], act_type="tanh")
        out_gate = _sym.Activation(sliced[3], act_type="sigmoid")
        next_c = forget_gate * states[1] + in_gate * in_trans
        next_h = out_gate * _sym.Activation(next_c, act_type="tanh")
        self._counter += 1
        return next_h, [next_h, next_c]


class GRUCell(BaseRNNCell):
    """GRU (reference: rnn_cell.GRUCell — gate order r, z, n)."""

    def __init__(self, num_hidden, prefix="gru_"):
        super().__init__(prefix)
        self._num_hidden = num_hidden

    @property
    def state_info(self):
        return [{"num_hidden": self._num_hidden}]

    def __call__(self, inputs, states):
        name = f"{self._prefix}t{self._counter}_"
        nh = self._num_hidden
        i2h = _sym.FullyConnected(inputs, self._get_param("i2h_weight"),
                                  self._get_param("i2h_bias"),
                                  num_hidden=nh * 3, name=name + "i2h")
        h2h = _sym.FullyConnected(states[0], self._get_param("h2h_weight"),
                                  self._get_param("h2h_bias"),
                                  num_hidden=nh * 3, name=name + "h2h")
        i2h_s = _sym.SliceChannel(i2h, num_outputs=3, axis=1,
                                  name=name + "i2h_slice")
        h2h_s = _sym.SliceChannel(h2h, num_outputs=3, axis=1,
                                  name=name + "h2h_slice")
        reset = _sym.Activation(i2h_s[0] + h2h_s[0], act_type="sigmoid")
        update = _sym.Activation(i2h_s[1] + h2h_s[1], act_type="sigmoid")
        cand = _sym.Activation(i2h_s[2] + reset * h2h_s[2], act_type="tanh")
        next_h = update * states[0] + (1.0 - update) * cand
        self._counter += 1
        return next_h, [next_h]


class SequentialRNNCell(BaseRNNCell):
    """Stacked cells (reference: rnn_cell.SequentialRNNCell)."""

    def __init__(self):
        super().__init__("")
        self._cells = []

    def add(self, cell):
        self._cells.append(cell)
        return self

    @property
    def params(self):
        out = {}
        for c in self._cells:
            out.update(c.params)
        return out

    @property
    def state_info(self):
        return [i for c in self._cells for i in c.state_info]

    def reset(self):
        for c in self._cells:
            c.reset()

    def __call__(self, inputs, states):
        next_states = []
        p = 0
        for cell in self._cells:
            n = len(cell.state_info)
            inputs, s = cell(inputs, states[p:p + n])
            next_states.extend(s)
            p += n
        return inputs, next_states


class BucketSentenceIter:
    """Bucketed sentence iterator (reference: python/mxnet/rnn/io.py
    BucketSentenceIter — pads each sentence to its bucket length and yields
    DataBatch with ``bucket_key`` for BucketingModule).

    sentences: list of lists of int token ids.
    """

    def __init__(self, sentences, batch_size, buckets=None, invalid_label=-1,
                 data_name="data", label_name="softmax_label", dtype="float32",
                 layout="NT"):
        self.layout = layout
        import numpy as np

        if buckets is None:
            lens = np.bincount([len(s) for s in sentences])
            buckets = [i for i, n in enumerate(lens)
                       if n >= batch_size and i > 0]
            if not buckets:
                buckets = [max(len(s) for s in sentences)]
        self.buckets = sorted(buckets)
        self.batch_size = batch_size
        self.invalid_label = invalid_label
        self.data_name = data_name
        self.label_name = label_name
        self.dtype = dtype
        self.data = [[] for _ in self.buckets]
        ndiscard = 0
        for s in sentences:
            buck = next((i for i, b in enumerate(self.buckets)
                         if b >= len(s)), None)
            if buck is None:
                ndiscard += 1
                continue
            row = np.full((self.buckets[buck],), invalid_label, dtype=dtype)
            row[:len(s)] = s
            self.data[buck].append(row)
        self.data = [np.asarray(rows, dtype=dtype) if rows else
                     np.zeros((0, b), dtype=dtype)
                     for rows, b in zip(self.data, self.buckets)]
        self.ndiscard = ndiscard
        self.default_bucket_key = max(self.buckets)
        shape = (batch_size, self.default_bucket_key) if layout == "NT" \
            else (self.default_bucket_key, batch_size)
        self.provide_data = [(data_name, shape)]
        self.provide_label = [(label_name, shape)]
        self.reset()

    def reset(self):
        import numpy as np

        self._idx = [(i, j) for i, rows in enumerate(self.data)
                     for j in range(0, len(rows) - self.batch_size + 1,
                                    self.batch_size)]
        np.random.shuffle(self._idx)
        for rows in self.data:
            np.random.shuffle(rows)
        self._cur = 0

    def __iter__(self):
        return self

    def __next__(self):
        return self.next()

    def next(self):
        from ..io import DataBatch
        from ..ndarray import array
        import numpy as np

        if self._cur >= len(self._idx):
            raise StopIteration
        i, j = self._idx[self._cur]
        self._cur += 1
        d = self.data[i][j:j + self.batch_size]
        # label = data shifted one step left (next-token prediction),
        # trailing slot filled with invalid_label (reference semantics)
        lab = np.full_like(d, self.invalid_label)
        lab[:, :-1] = d[:, 1:]
        if self.layout == "TN":
            d, lab = d.T, lab.T
        return DataBatch(data=[array(d)], label=[array(lab)],
                         bucket_key=self.buckets[i],
                         provide_data=[(self.data_name, d.shape)],
                         provide_label=[(self.label_name, lab.shape)])


class FusedRNNCell(BaseRNNCell):
    """Fused multi-layer RNN over the flat parameter vector (reference:
    rnn_cell.FusedRNNCell -> sym.RNN, src/operator/rnn.cc).  unroll()
    stages ONE RNN node — on TPU that is one XLA program with the i2h
    GEMMs hoisted out of the scan."""

    def __init__(self, num_hidden, num_layers=1, mode="lstm",
                 bidirectional=False, dropout=0.0, get_next_state=False,
                 prefix=None):
        super().__init__(prefix if prefix is not None else f"{mode}_")
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._dropout = dropout
        self._get_next_state = get_next_state

    @property
    def state_info(self):
        n = self._num_layers * (2 if self._bidirectional else 1)
        infos = [{"num_hidden": self._num_hidden, "layers": n}]
        if self._mode == "lstm":
            infos.append({"num_hidden": self._num_hidden, "layers": n})
        return infos

    def __call__(self, inputs, states):
        raise MXNetError("FusedRNNCell cannot step one timestep at a "
                         "time; use unroll() (reference behavior)")

    def _zero_fused_states(self, data_tnc):
        """(nl*nd, N, nh) zero-state symbols shaped off the data — staged
        explicitly so the op's state slots never become free trainable
        variables (reference starts fused RNNs from zeros)."""
        n = self._num_layers * (2 if self._bidirectional else 1)
        z = _sym.slice_axis(_sym.zeros_like(data_tnc), axis=0, begin=0,
                            end=1)                       # (1, N, C)
        z = _sym.slice_axis(z, axis=-1, begin=0, end=1)  # (1, N, 1)
        z = _sym.tile(z, reps=(n, 1, self._num_hidden))
        return [z] * len(self.state_info)

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        if isinstance(inputs, (list, tuple)):
            if len(inputs) != length:
                raise MXNetError(f"unroll: got {len(inputs)} input symbols "
                                 f"for length {length}")
            inputs = _sym.Concat(
                *[_sym.expand_dims(s, axis=1) for s in inputs], dim=1)
            layout = "NTC"
        data = inputs if layout == "TNC" else \
            _sym.swapaxes(inputs, dim1=0, dim2=1)
        if begin_state is None or all(s is None for s in begin_state):
            # None / the base begin_state() placeholder list = zero states
            begin_state = self._zero_fused_states(data)
        elif any(s is None for s in begin_state):
            raise MXNetError("begin_state mixes symbols and None; pass a "
                             "full list of state symbols (or None/"
                             "begin_state() for zeros)")
        args = [data, self._get_param("parameters")] + list(begin_state)
        out = _sym.RNN(*args, state_size=self._num_hidden,
                       num_layers=self._num_layers, mode=self._mode,
                       bidirectional=self._bidirectional, p=self._dropout,
                       state_outputs=self._get_next_state,
                       name=self._prefix + "rnn")
        if self._get_next_state:
            states = [out[i] for i in range(1, 3 if self._mode == "lstm"
                                            else 2)]
            out = out[0]
        else:
            states = []
        if layout == "NTC":
            out = _sym.swapaxes(out, dim1=0, dim2=1)
        if not merge_outputs:
            axis = 1 if layout == "NTC" else 0
            out = [_sym.squeeze(
                _sym.slice_axis(out, axis=axis, begin=t, end=t + 1),
                axis=axis) for t in range(length)]
        return out, states
