"""Profiler: per-op stats + Chrome trace over jax.profiler.

Reference: ``python/mxnet/profiler.py`` + ``src/profiler/`` (SURVEY.md
§6.1): Chrome-trace event file, per-op aggregate statistics table
(``dumps()``), user scopes/markers/counters.  TPU mapping:

- ``start()/stop()`` also drive ``jax.profiler`` traces (XLA per-HLO-op
  attribution, open in TensorBoard/Perfetto) — the on-device truth.
- Python-level op events come from the ``invoke`` seam: when
  ``profile_imperative`` (or profile_all) is set, each imperative op is
  timed with a sync, exactly the trade the reference's profiler makes
  (honest per-op wall time requires serializing the async engine).
- ``dump()`` writes a standard Chrome ``traceEvents`` JSON (op spans,
  markers as instant events, counters as counter events);
  ``dumps()`` returns the aggregate per-op summary table.
"""
from __future__ import annotations

import json
import os
import threading
import time

from .base import MXNetError

__all__ = ["set_config", "start", "stop", "dump", "dumps", "pause", "resume",
           "Task", "Frame", "Marker", "Counter", "Domain", "Scope"]

_CONFIG = {"filename": "profile.json", "profile_all": False,
           "profile_imperative": False, "dir": None, "jax_trace": True,
           "continuous_dump": False}
_ACTIVE = False
_PAUSED = False
_LOCK = threading.Lock()
_EVENTS = []   # chrome trace events
_AGG = {}      # opname -> [count, total_s, min_s, max_s]
_T0 = None
_DUMPED_ONCE = False  # continuous_dump: later dumps merge into the file


def set_config(profile_all=False, profile_symbolic=False,
               profile_imperative=False, profile_memory=False,
               profile_api=False, filename="profile.json",
               continuous_dump=False, jax_trace=True, **kwargs):
    global _DUMPED_ONCE
    _CONFIG.update(profile_all=profile_all, filename=filename,
                   profile_imperative=profile_imperative or profile_all,
                   jax_trace=jax_trace,
                   continuous_dump=bool(continuous_dump))
    _CONFIG["dir"] = os.path.dirname(os.path.abspath(filename)) or "."
    _DUMPED_ONCE = False


def _record_op(opname, t0, t1):
    with _LOCK:
        _EVENTS.append({"name": opname, "ph": "X", "pid": 0,
                        "tid": threading.get_ident() % 1000,
                        "ts": (t0 - _T0) * 1e6, "dur": (t1 - t0) * 1e6,
                        "cat": "operator"})
        agg = _AGG.get(opname)
        dt = t1 - t0
        if agg is None:
            _AGG[opname] = [1, dt, dt, dt]
        else:
            agg[0] += 1
            agg[1] += dt
            agg[2] = min(agg[2], dt)
            agg[3] = max(agg[3], dt)


def _instant(name, cat):
    if _T0 is None or not _ACTIVE or _PAUSED:
        return
    with _LOCK:
        _EVENTS.append({"name": name, "ph": "i", "pid": 0, "s": "g",
                        "tid": threading.get_ident() % 1000,
                        "ts": (time.perf_counter() - _T0) * 1e6, "cat": cat})


def _record_span(name, t0, t1, cat="step_phase", tid=1000, args=None):
    """Telemetry hook: merge a step-phase / compile / request span into
    the Chrome trace (its own tid row so phases don't interleave with op
    events).  ``t0``/``t1`` are perf_counter values — the same clock as
    ``_T0``; ``args`` (JSON-able dict) lands on the event verbatim (the
    serving request tracer carries trace ids/outcomes through it)."""
    if _T0 is None or not _ACTIVE or _PAUSED:
        return
    ev = {"name": name, "ph": "X", "pid": 0, "tid": tid,
          "ts": (t0 - _T0) * 1e6, "dur": (t1 - t0) * 1e6,
          "cat": cat}
    if args:
        ev["args"] = dict(args)
    with _LOCK:
        _EVENTS.append(ev)


def _counter(name, value):
    if _T0 is None or not _ACTIVE or _PAUSED:
        return
    with _LOCK:
        _EVENTS.append({"name": name, "ph": "C", "pid": 0,
                        "ts": (time.perf_counter() - _T0) * 1e6,
                        "args": {name: value}})


def start():
    global _ACTIVE, _T0, _PAUSED, _DUMPED_ONCE
    from .ndarray.ndarray import _PROFILE

    _T0 = time.perf_counter()
    _PAUSED = False
    _DUMPED_ONCE = False  # a new session never merges into an old file
    if _CONFIG.get("jax_trace", True):
        import jax

        logdir = _CONFIG.get("dir") or "."
        jax.profiler.start_trace(os.path.join(logdir, "jax_trace"))
    if _CONFIG.get("profile_imperative") or _CONFIG.get("profile_all"):
        _PROFILE["record"] = _record_op
        _PROFILE["on"] = True
    _ACTIVE = True


def stop():
    global _ACTIVE
    from .ndarray.ndarray import _PROFILE

    if not _ACTIVE:
        return
    _PROFILE["on"] = False
    _PROFILE["record"] = None
    if _CONFIG.get("jax_trace", True):
        import jax

        jax.profiler.stop_trace()
    _ACTIVE = False


def pause():
    global _PAUSED
    from .ndarray.ndarray import _PROFILE

    _PAUSED = True
    _PROFILE["on"] = False


def resume():
    global _PAUSED
    from .ndarray.ndarray import _PROFILE

    if not _ACTIVE:  # resume without a prior start() is a no-op
        return
    _PAUSED = False
    if _CONFIG.get("profile_imperative") or _CONFIG.get("profile_all"):
        _PROFILE["record"] = _record_op
        _PROFILE["on"] = True


def dump(finished=True, profile_process="worker", drain=None):
    """Write the Chrome traceEvents file (open in chrome://tracing /
    Perfetto; the XLA-level trace lives in jax_trace/ for TensorBoard).

    ``drain=True`` removes the written events from the in-memory buffer
    so a later dump never re-emits them.  Under
    ``set_config(continuous_dump=True)`` draining is implied (the file IS
    the buffer then — ``drain=False`` is ignored) and successive
    ``dump()`` calls MERGE the drained increments into the existing trace
    file, so periodic dumping from a long-running job yields one growing,
    duplicate-free trace."""
    global _DUMPED_ONCE
    from . import fault as _fault
    from . import telemetry as _telemetry
    from .ndarray import dispatch_cache as _dc

    if _CONFIG["continuous_dump"]:
        # the merge base is "everything drained so far"; leaving events
        # undrained while merging would re-emit them on the next dump
        drain = True
    elif drain is None:
        drain = False
    dstats = _dc.stats()
    with _LOCK:
        events = list(_EVENTS)
        if drain:
            _EVENTS.clear()
    if _CONFIG["continuous_dump"] and _DUMPED_ONCE and \
            os.path.exists(_CONFIG["filename"]):
        try:
            with open(_CONFIG["filename"]) as f:
                prior = json.load(f).get("traceEvents", [])
            events = prior + events
        except (OSError, ValueError):
            pass  # unreadable prior dump: write this increment standalone
    with open(_CONFIG["filename"], "w") as f:
        json.dump({"traceEvents": events,
                   "displayTimeUnit": "ms",
                   "otherData": {
                       "xla_trace": "see jax_trace/ (TensorBoard)",
                       "eager_dispatch_cache": {
                           k: dstats[k] for k in
                           ("enabled", "hits", "misses", "evictions",
                            "bypasses", "size", "capacity")},
                       "fault_seams": _fault.stats(),
                       "telemetry": _telemetry.snapshot()}}, f)
    _DUMPED_ONCE = True
    return _CONFIG["filename"]


def dumps(reset=False):
    """Aggregate per-op statistics table (reference: profiler.dumps), with
    the eager dispatch-cache hit/miss per op (ndarray/dispatch_cache.py)
    appended so the jit fast path's behavior shows up next to the timings.

    The Jit columns are the dispatch cache's own cumulative counters (all
    invokes since mx.nd.reset_dispatch_stats(), profiling on or off) — they
    are NOT bounded by the Count column, which only accumulates while
    profiling is active, and ``reset=True`` does not clear them."""
    from .ndarray import dispatch_cache as _dc

    dstats = _dc.stats()
    per_op = dstats["per_op"]
    with _LOCK:
        rows = [(name, a[0], a[1] * 1e3, a[2] * 1e3, a[3] * 1e3,
                 a[1] / a[0] * 1e3) for name, a in sorted(_AGG.items())]
        if reset:
            _AGG.clear()
            _EVENTS.clear()
    lines = ["Profile Statistics:",
             f"{'Name':<32}{'Total Count':>12}{'Total(ms)':>12}"
             f"{'Min(ms)':>10}{'Max(ms)':>10}{'Avg(ms)':>10}"
             f"{'JitHit':>8}{'JitMiss':>8}"]
    for name, cnt, tot, mn, mx, avg in rows:
        hm = per_op.get(name)
        hit, miss = (hm["hits"], hm["misses"]) if hm else (0, 0)
        lines.append(f"{name:<32}{cnt:>12}{tot:>12.3f}{mn:>10.3f}"
                     f"{mx:>10.3f}{avg:>10.3f}{hit:>8}{miss:>8}")
    lines.append(
        f"Eager dispatch cache: enabled={dstats['enabled']} "
        f"hits={dstats['hits']} misses={dstats['misses']} "
        f"evictions={dstats['evictions']} bypasses={dstats['bypasses']} "
        f"size={dstats['size']}/{dstats['capacity']} "
        "(cumulative since reset_dispatch_stats; not scoped to profiling)")
    # failure-domain counters (mxnet_tpu.fault): which seams saw traffic,
    # injected/observed trips, and transient-error retries — cumulative
    # since fault.reset_stats(), like the dispatch-cache counters above
    from . import fault as _fault

    fstats = _fault.stats()
    lines.append(f"Fault seams:{'':<20}{'Calls':>12}{'Trips':>10}"
                 f"{'Retries':>10}")
    for seam in _fault.SEAMS:
        c = fstats[seam]
        lines.append(f"  {seam:<30}{c['calls']:>12}{c['trips']:>10}"
                     f"{c['retries']:>10}")
    return "\n".join(lines)


class Domain:
    def __init__(self, name):
        self.name = name


class _Scope:
    def __init__(self, name):
        self.name = name
        self._ctx = None
        self._t0 = None

    def start(self):
        import jax

        self._ctx = jax.profiler.TraceAnnotation(self.name)
        self._ctx.__enter__()
        self._t0 = time.perf_counter()

    def stop(self):
        if self._ctx is not None:
            self._ctx.__exit__(None, None, None)
            self._ctx = None
        if self._t0 is not None and _T0 is not None and _ACTIVE \
                and not _PAUSED:
            _record_op(f"scope:{self.name}", self._t0, time.perf_counter())
        self._t0 = None

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *a):
        self.stop()
        return False


class Task(_Scope):
    def __init__(self, domain=None, name="task"):
        super().__init__(name)


class Frame(_Scope):
    def __init__(self, domain=None, name="frame"):
        super().__init__(name)


class Marker:
    """Instant event in the trace (reference: profiler.Marker.mark)."""

    def __init__(self, domain=None, name="marker"):
        self.name = name

    def mark(self, scope="process"):
        _instant(self.name, "marker")


class Counter:
    """Named counter recorded into the trace (reference: profiler.Counter)."""

    def __init__(self, domain=None, name="counter", value=0):
        self.name = name
        self.value = value
        _counter(self.name, value)

    def set_value(self, value):
        self.value = value
        _counter(self.name, value)

    def increment(self, delta=1):
        self.set_value(self.value + delta)

    def decrement(self, delta=1):
        self.set_value(self.value - delta)

    def __iadd__(self, delta):
        self.increment(delta)
        return self

    def __isub__(self, delta):
        self.decrement(delta)
        return self


Scope = _Scope
