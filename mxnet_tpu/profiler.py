"""Profiler shim over jax.profiler.

Reference: ``python/mxnet/profiler.py`` + ``src/profiler/`` (operator-level
Chrome-trace profiler — SURVEY.md §6.1).  TPU mapping: set_config/start/stop
drive ``jax.profiler`` traces viewable in TensorBoard/Perfetto (per-HLO-op
attribution replaces per-engine-op events); user scopes map to
``jax.profiler.TraceAnnotation`` / named scopes.
"""
from __future__ import annotations

import os
import time
from contextlib import contextmanager

from .base import MXNetError

__all__ = ["set_config", "start", "stop", "dump", "dumps", "pause", "resume",
           "Task", "Frame", "Marker", "Counter", "Domain", "Scope"]

_CONFIG = {"filename": "profile.json", "profile_all": False, "dir": None}
_ACTIVE = False


def set_config(profile_all=False, profile_symbolic=False,
               profile_imperative=False, profile_memory=False,
               profile_api=False, filename="profile.json",
               continuous_dump=False, **kwargs):
    _CONFIG.update(profile_all=profile_all, filename=filename)
    _CONFIG["dir"] = os.path.dirname(os.path.abspath(filename)) or "."


def start():
    global _ACTIVE
    import jax

    logdir = _CONFIG.get("dir") or "."
    jax.profiler.start_trace(os.path.join(logdir, "jax_trace"))
    _ACTIVE = True


def stop():
    global _ACTIVE
    import jax

    if _ACTIVE:
        jax.profiler.stop_trace()
        _ACTIVE = False


def pause():
    stop()


def resume():
    start()


def dump(finished=True, profile_process="worker"):
    """The jax trace is written at stop(); this records the pointer file."""
    with open(_CONFIG["filename"], "w") as f:
        f.write('{"note": "trace written by jax.profiler; open the '
                'jax_trace/ directory in TensorBoard or Perfetto"}\n')


def dumps(reset=False):
    return "<profile data in jax_trace/; open with TensorBoard>"


class Domain:
    def __init__(self, name):
        self.name = name


class _Scope:
    def __init__(self, name):
        self.name = name
        self._ctx = None

    def start(self):
        import jax

        self._ctx = jax.profiler.TraceAnnotation(self.name)
        self._ctx.__enter__()

    def stop(self):
        if self._ctx is not None:
            self._ctx.__exit__(None, None, None)
            self._ctx = None

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *a):
        self.stop()
        return False


class Task(_Scope):
    def __init__(self, domain=None, name="task"):
        super().__init__(name)


class Frame(_Scope):
    def __init__(self, domain=None, name="frame"):
        super().__init__(name)


class Marker:
    def __init__(self, domain=None, name="marker"):
        self.name = name

    def mark(self, scope="process"):
        pass


class Counter:
    def __init__(self, domain=None, name="counter", value=0):
        self.name = name
        self.value = value

    def set_value(self, value):
        self.value = value

    def increment(self, delta=1):
        self.value += delta

    def decrement(self, delta=1):
        self.value -= delta


Scope = _Scope
