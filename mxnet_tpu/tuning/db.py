"""Persistent per-signature tuning DB: search winners on disk, keyed
like compile-cache entries.

The persistence half of the TVM loop (PAPERS.md, arXiv:1802.04799):
an offline ``bench.py --tune`` run measures candidates and publishes
the winner; every later process — same program, same plan, same
device kind, same jax — replays it with **zero search trials**.  The
on-disk discipline is ``compile_cache.py``'s, byte for byte in spirit:

Key = sha256 over:

- the knob name,
- the workload signature (a repr-stable tuple — aval signatures,
  model/graph identity; ``None`` = the knob's global winner),
- the governing :class:`~mxnet_tpu.parallel.planner.ShardingPlan`
  digest (a re-planned mesh must never replay the old winner),
- the device kind (a winner tuned on CPU must not steer a TPU),
- the jax/jaxlib fingerprint + this module's format version (an
  upgraded runtime silently starts cold).

Entry format: one file per key, ``<keyhash>.tune`` = a JSON header
line (payload sha256, size, fingerprint, creation time) + a JSON
payload ``{"knob", "value", "score", "default_score", "trials",
"unit"}``.  Written atomically (tmp + fsync + rename), verified on
read: **a corrupt, truncated, or version-mismatched entry is a silent
miss, never a crash** — the warm path just runs the default and the
next ``--tune`` overwrites it.
"""
from __future__ import annotations

import hashlib
import json
import logging
import os
import tempfile
import time

from .. import env as _env
from .. import telemetry as _telemetry

__all__ = ["TuningDB", "default_db", "resolve_db", "device_kind"]

_LOGGER = logging.getLogger(__name__)

# bump when the entry payload shape or the winner semantics change:
# old entries silently miss instead of steering with stale meaning
_FORMAT_VERSION = 1

_DB_HITS = _telemetry.counter(
    "mxnet_tuning_db_hits_total",
    "tuned winners served from the persistent tuning DB (each one is "
    "a knob search that did NOT happen)")
_DB_MISSES = _telemetry.counter(
    "mxnet_tuning_db_misses_total",
    "tuning-DB lookups that found no usable entry (unset, corrupt, "
    "version-mismatched, or out-of-grid)")
_DB_STORES = _telemetry.counter(
    "mxnet_tuning_db_stores_total",
    "search winners published into the persistent tuning DB")


def _fingerprint():
    import jax
    import jaxlib

    return f"jax={jax.__version__};jaxlib={jaxlib.__version__}" \
           f";fmt={_FORMAT_VERSION}"


def device_kind():
    """The device kind a winner is valid for.  Prefers an ALREADY
    chosen backend (never forces backend init just to name it:
    pre-backend resolve calls fall back to the platform request, so a
    CPU process and a TPU process still key apart)."""
    try:
        import jax

        devs = jax.devices()
        if devs:
            return str(getattr(devs[0], "device_kind", None)
                       or devs[0].platform)
    except Exception:
        pass
    return str(os.environ.get("JAX_PLATFORMS", "unknown").split(",")[0]
               or "unknown")


_DEFAULT = None
_DEFAULT_DIR = None


def default_db():
    """The session-default DB from ``MXNET_TUNE_DB_DIR`` (None when
    unset — without a directory there is nothing to replay)."""
    global _DEFAULT, _DEFAULT_DIR
    d = _env.tune_db_dir()
    if not d:
        return None
    if _DEFAULT is None or _DEFAULT_DIR != d:
        _DEFAULT = TuningDB(d)
        _DEFAULT_DIR = d
    return _DEFAULT


def resolve_db(explicit):
    """The DB a consumer should use: explicit wins, else the session
    default, else None."""
    return explicit if explicit is not None else default_db()


class TuningDB:
    """One on-disk winner directory (content-addressed, atomic-publish,
    sha256-verified — the compile-cache discipline)."""

    def __init__(self, directory, logger=None):
        self.directory = directory
        self.logger = logger or _LOGGER

    # -- keys --------------------------------------------------------------
    def key(self, knob_name, signature=None, plan_digest=None,
            device=None):
        """sha256 key for one winner — knob + workload signature + plan
        digest + device kind + jax fingerprint."""
        doc = repr((str(knob_name), signature if signature is not None
                    else "global", plan_digest or "none",
                    device or device_kind(), _fingerprint()))
        return hashlib.sha256(doc.encode()).hexdigest()

    def _path(self, key):
        return os.path.join(self.directory, f"{key}.tune")

    # -- entries -----------------------------------------------------------
    def get(self, key):
        """The verified winner doc for ``key``, or None.  Every failure
        mode — missing file, torn header, truncated payload, checksum
        mismatch, fingerprint drift, non-dict payload — is a SILENT
        miss: the warm path runs the default instead."""
        path = self._path(key)
        try:
            with open(path, "rb") as f:
                header = json.loads(f.readline())
                payload = f.read()
        except (OSError, ValueError):
            _DB_MISSES.inc()
            return None
        try:
            ok = (header.get("fingerprint") == _fingerprint()
                  and header.get("size") == len(payload)
                  and header.get("sha256") ==
                  hashlib.sha256(payload).hexdigest())
        except Exception:
            ok = False
        doc = None
        if ok:
            try:
                doc = json.loads(payload)
            except ValueError:
                doc = None
        if not isinstance(doc, dict) or "value" not in doc:
            _DB_MISSES.inc()
            self.logger.warning(
                "tuning DB entry %s failed verification; treating as a "
                "miss (the next --tune run will overwrite it)", path)
            return None
        _DB_HITS.inc()
        return doc

    def put(self, key, doc):
        """Atomically publish a winner doc (tmp + fsync + rename;
        concurrent tuners converge on a complete file, a crash
        mid-write leaves no visible entry).  Returns False on OSError —
        the DB is an accelerator, not a dependency."""
        payload = json.dumps(doc, sort_keys=True).encode()
        header = {"sha256": hashlib.sha256(payload).hexdigest(),
                  "size": len(payload),
                  "fingerprint": _fingerprint(),
                  "time": time.time()}
        try:
            os.makedirs(self.directory, exist_ok=True)
            fd, tmp = tempfile.mkstemp(dir=self.directory,
                                       prefix=".tmp_tune_")
        except OSError as e:
            self.logger.warning("tuning DB store failed: %r", e)
            return False
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(json.dumps(header).encode() + b"\n")
                f.write(payload)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self._path(key))
        except OSError as e:
            self.logger.warning("tuning DB store failed: %r", e)
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return False
        _DB_STORES.inc()
        return True

    # -- winner sugar ------------------------------------------------------
    def get_winner(self, knob, signature=None, plan_digest=None):
        """The stored winner VALUE for ``knob`` (a :class:`Knob`), or
        None.  Falls back from the exact signature to the knob's global
        winner, validates against the declared grid (a stale entry from
        an older grid degrades to a miss), and parses through the
        knob's type."""
        for sig in ((signature, plan_digest), (None, None)) \
                if signature is not None or plan_digest is not None \
                else ((None, None),):
            doc = self.get(self.key(knob.name, sig[0], sig[1]))
            if doc is None:
                continue
            value = knob.parse(doc.get("value"))
            if knob.validate(value):
                return value
            self.logger.warning(
                "tuning DB winner %r for knob %s is outside the "
                "declared grid %r; ignoring it", value, knob.name,
                knob.grid)
        return None

    def put_winner(self, knob, value, *, signature=None,
                   plan_digest=None, score=None, default_score=None,
                   trials=None, unit=None, publish_global=True):
        """Publish a search winner (see :meth:`get_winner` for the
        lookup side).  ``value`` is stored as a string so int/str knobs
        round-trip the same way env vars do.  With ``publish_global``
        (the default) a signature-keyed winner is ALSO published under
        the knob's global key — resolve sites without signature context
        (e.g. ``bucket_cap_bytes``) replay through the global fallback."""
        doc = {"format": _FORMAT_VERSION, "knob": knob.name,
               "value": str(value)}
        if score is not None:
            doc["score"] = float(score)
        if default_score is not None:
            doc["default_score"] = float(default_score)
        if trials is not None:
            doc["trials"] = int(trials)
        if unit:
            doc["unit"] = str(unit)
        ok = self.put(self.key(knob.name, signature, plan_digest), doc)
        if ok and publish_global and (signature is not None
                                      or plan_digest is not None):
            ok = self.put(self.key(knob.name, None, None),
                          dict(doc, signature=repr(signature)))
        return ok

    def stats(self):
        """Entry count + bytes on disk (observability helper)."""
        n, total = 0, 0
        try:
            for name in os.listdir(self.directory):
                if name.endswith(".tune"):
                    n += 1
                    total += os.path.getsize(
                        os.path.join(self.directory, name))
        except OSError:
            pass
        return {"entries": n, "bytes": total,
                "directory": self.directory}
