"""Search-based autotuning tier: one resolve funnel over every
performance knob, a grid + successive-halving search driver, and a
persistent per-signature winner DB.

The TVM loop (PAPERS.md, arXiv:1802.04799) split across the repo's
existing layers:

- **template** — :mod:`.knobs`: the declarative registry of tunables
  (name, type, legal grid, subsumed env var, scorer family);
- **search** — :mod:`.search`: grid + successive halving with a
  deterministic candidate schedule, scored by the live PR 14 gauges
  (step time / MFU for training arms, tokens/s + p99 TTFT for
  serving);
- **persistence** — :mod:`.db`: winners on disk, keyed like
  compile-cache entries (signature + plan digest + device kind + jax
  fingerprint), sha256-verified, atomic publish, corrupt = silent
  miss.

Every consumer — ``TrainStep``/kvstore bucketing, the graph
``PassPipeline``, flash-attention tiles, the prefetcher, the
``ServingEngine`` — resolves its value through ONE funnel::

    value = tuning.resolve("allreduce_bucket_mb", signature=sig)

Precedence, strictly: an active **search trial** override (only ever
present inside ``bench.py --tune``) > an **explicit env pin** (the
operator said so — recorded as ``pinned``, never overridden) > a
**stored winner** (only when ``MXNET_TUNE=1``: the warm path replays,
it never explores) > the **default**.  With ``MXNET_TUNE`` unset the
funnel never touches the DB, so default-config trajectories stay
bit-identical to a build without this tier.

Telemetry: ``mxnet_tuning_trials_total{knob}`` (search measurements),
``mxnet_tuning_db_{hits,misses,stores}_total`` (DB traffic), and
``mxnet_tuning_chosen_value{knob}`` (the numeric value each knob
resolved to, by source precedence — string-grid knobs export their
grid index).
"""
from __future__ import annotations

import contextlib
import os
import threading

from .. import env as _env
from .. import telemetry as _telemetry
from . import db as _dbmod
from .db import TuningDB, default_db, device_kind, resolve_db
from .knobs import Knob, all_knobs, get_knob, knob_names, register_knob
from .search import schedule, successive_halving, tune_knob

__all__ = ["Knob", "TuningDB", "all_knobs", "default_db",
           "device_kind", "effective_config", "enabled", "get_knob",
           "knob_names", "register_knob", "reset", "resolve",
           "resolve_db", "resolve_info", "schedule",
           "successive_halving", "trial_override", "tune_knob"]

_CHOSEN = _telemetry.gauge(
    "mxnet_tuning_chosen_value",
    "the value each knob resolved to through the tuning funnel "
    "(string-grid knobs export their grid index; env pins and tuned "
    "winners both land here — the source rides the bench stamp)",
    labelnames=("knob",))

_LOCK = threading.Lock()
# name -> value, set only inside a search trial (bench.py --tune);
# consulted first by resolve() so trials measure the candidate without
# mutating the process environment
_TRIAL: dict = {}
# (name, signature, plan_digest, db_dir) -> winner value; the warm
# path's per-process memo so steady-state resolve() costs a dict probe,
# not a file read + sha256 per step
_WINNERS: dict = {}


def enabled():
    """Whether the warm replay path may consult the DB
    (``MXNET_TUNE``, default off — online exploration NEVER happens
    here regardless; only ``bench.py --tune`` searches)."""
    return _env.tune_enabled()


@contextlib.contextmanager
def trial_override(name, value):
    """Apply a candidate value for the duration of one search trial.
    Every consumer read site sees it through :func:`resolve`; nothing
    escapes the ``with`` — a crashed trial cannot poison the process
    (no env mutation, restore is unconditional)."""
    knob = get_knob(name)
    if knob.apply is not None:
        knob.apply(value)
    with _LOCK:
        prev = _TRIAL.get(name, _TRIAL)
        _TRIAL[name] = value
    try:
        yield value
    finally:
        with _LOCK:
            if prev is _TRIAL:
                _TRIAL.pop(name, None)
            else:
                _TRIAL[name] = prev
        if knob.apply is not None:
            knob.apply(None)


def _gauge_value(knob, value):
    if isinstance(value, (int, float)) and not isinstance(value, bool):
        return float(value)
    try:
        return float(knob.grid.index(value))
    except ValueError:
        return -1.0


def resolve_info(name, signature=None, plan_digest=None, db=None):
    """``(value, source)`` for one knob — source is ``trial``, ``env``
    (pinned), ``tuned``, or ``default``.  See the module docstring for
    the precedence contract."""
    knob = get_knob(name)
    with _LOCK:
        if name in _TRIAL:
            return _TRIAL[name], "trial"
    raw = os.environ.get(knob.env_var)
    if raw not in (None, ""):
        value = knob.parse(raw)
        _CHOSEN.labels(knob=name).set(_gauge_value(knob, value))
        return value, "env"
    if enabled():
        d = resolve_db(db)
        if d is not None:
            memo = (name, signature, plan_digest, d.directory)
            with _LOCK:
                if memo in _WINNERS:
                    return _WINNERS[memo], "tuned"
            value = d.get_winner(knob, signature, plan_digest)
            if value is not None:
                with _LOCK:
                    _WINNERS[memo] = value
                _CHOSEN.labels(knob=name).set(_gauge_value(knob, value))
                return value, "tuned"
    return knob.default, "default"


def resolve(name, signature=None, plan_digest=None, db=None):
    """The value a consumer should use for ``name`` — the one funnel
    every read site goes through (see ``resolve_info`` for the
    provenance-carrying variant the bench stamps use)."""
    return resolve_info(name, signature, plan_digest, db)[0]


def effective_config(names=None, signature=None, plan_digest=None):
    """``{knob: {"value", "source"}}`` for every (or the named) knobs —
    the configuration stamp ``bench.py`` records in each result block
    so A/B arms can never silently run different configs."""
    out = {}
    for name in (names if names is not None else knob_names()):
        value, source = resolve_info(name, signature, plan_digest)
        out[name] = {"value": value, "source": source}
    return out


def reset():
    """Drop trial overrides + the winner memo (test isolation; the
    on-disk DB is untouched)."""
    global _WINNERS
    with _LOCK:
        _TRIAL.clear()
        _WINNERS = {}
    _dbmod._DEFAULT = None
    _dbmod._DEFAULT_DIR = None
