"""Declarative registry of tunable performance knobs.

The TVM blueprint (PAPERS.md, arXiv:1802.04799) starts from a schedule
*template* — a declared space of legal configurations — and only then
searches it.  This module is that template layer for the runtime's
hand-picked performance constants: every tunable registers its name,
value type, legal grid, the ``MXNET_*`` env var it subsumes, and which
live gauge family scores it (training arms: step time / MFU; serving
arms: tokens/s + p99 TTFT).

The registry is **ordered and closed**: knobs register at import in
source order and :func:`all_knobs` walks them in that order, so two
processes enumerating the search space visit candidates identically —
the same determinism contract bucket assignment already carries
(parallel/bucketing.py).

A knob does NOT read its env var here beyond parsing: precedence
(trial > env pin > tuned winner > default) lives in
``tuning.resolve`` — this module only says what exists and what is
legal.
"""
from __future__ import annotations

__all__ = ["Knob", "register_knob", "get_knob", "all_knobs",
           "knob_names"]


class Knob:
    """One tunable dimension: identity, legality, and how to apply it.

    ``grid`` is the declared legal candidate list, in search order
    (deterministic across processes — never derived from a dict or a
    hash).  ``default`` must be a member of the value space but need
    not sit in the grid; the search driver always prepends it so the
    baseline is measured under the same budget as every candidate.
    ``kind`` routes the knob to a scorer family: ``training`` (step
    time / MFU) or ``serving`` (tokens/s + p99 TTFT).
    """

    __slots__ = ("name", "env_var", "type", "default", "grid", "kind",
                 "description", "apply")

    def __init__(self, name, env_var, type, default, grid, kind,
                 description, apply=None):
        self.name = str(name)
        self.env_var = str(env_var)
        self.type = type
        self.default = default
        self.grid = tuple(grid)
        self.kind = str(kind)
        self.description = str(description)
        # apply hook: how a SEARCH TRIAL takes effect.  The default
        # (None) routes through tuning's trial-override table, which
        # every consumer read site consults via tuning.resolve — no
        # env mutation, so a crashed search never leaves a poisoned
        # process environment behind.
        self.apply = apply

    def parse(self, raw):
        """Parse an env-var/DB string into the knob's value type;
        garbage degrades to the default (the env.get_int contract —
        a typo'd override must never crash a step)."""
        if raw is None:
            return self.default
        if self.type is str:
            return str(raw)
        try:
            return self.type(raw)
        except (TypeError, ValueError):
            import warnings

            warnings.warn(
                f"{self.env_var}={raw!r} is not a valid "
                f"{self.type.__name__} for knob {self.name!r}; using "
                f"default {self.default!r}", stacklevel=2)
            return self.default

    def validate(self, value):
        """Whether ``value`` is inside the declared legal space (grid
        member or the default).  The warm path checks this before
        applying a DB winner: a stale entry from an older grid must
        degrade to the default, never apply an illegal value."""
        return value == self.default or value in self.grid

    def __repr__(self):  # pragma: no cover - debugging aid
        return (f"Knob({self.name!r}, env={self.env_var}, "
                f"default={self.default!r}, grid={self.grid!r}, "
                f"kind={self.kind})")


_REGISTRY: dict = {}      # name -> Knob, insertion-ordered


def register_knob(knob):
    """Add a knob to the registry (idempotent per name: re-registering
    the same name replaces — module reloads in tests)."""
    _REGISTRY[knob.name] = knob
    return knob


def get_knob(name):
    """The registered :class:`Knob`, or raise KeyError with the legal
    names (a typo'd knob name must fail loudly — unlike a typo'd VALUE,
    which degrades)."""
    try:
        return _REGISTRY[name]
    except KeyError:
        raise KeyError(
            f"unknown tuning knob {name!r}; registered: "
            f"{sorted(_REGISTRY)}") from None


def all_knobs():
    """Every registered knob, in registration (= search) order."""
    return list(_REGISTRY.values())


def knob_names():
    return list(_REGISTRY)


# --------------------------------------------------------------------------
# the initial population: the hand-picked constants the ROADMAP names
# as the first search dimensions.  Grids stay small on purpose — grid +
# successive halving is exhaustive over them, and every candidate costs
# a real measurement.
# --------------------------------------------------------------------------
register_knob(Knob(
    "allreduce_bucket_mb", "MXNET_ALLREDUCE_BUCKET_MB", int, 32,
    (0, 1, 4, 8, 16, 32, 64, 128), "training",
    "fused-allreduce gradient-bucket cap in MiB (0 = per-key "
    "collectives; parallel/bucketing.py)"))
register_knob(Knob(
    "graph_fuse_cap", "MXNET_GRAPH_FUSE_CAP", int, 16,
    (0, 4, 8, 16, 32, 64), "training",
    "max ops per fused elementwise chain (< 2 disables the pass; "
    "graph/passes.py)"))
register_knob(Knob(
    "flash_block_q", "MXNET_FLASH_BLOCK_Q", int, 128,
    (128, 256, 512), "training",
    "flash-attention forward q tile (must divide the padded sequence; "
    "ops/flash_attention.py)"))
register_knob(Knob(
    "flash_block_kv", "MXNET_FLASH_BLOCK_KV", int, 128,
    (128, 256, 512), "training",
    "flash-attention forward kv tile (ops/flash_attention.py)"))
register_knob(Knob(
    "prefetch_buffer", "MXNET_PREFETCH_BUFFER", int, 2,
    (0, 1, 2, 4, 8), "training",
    "device-prefetch queue depth (0 = serial staging; "
    "gluon/data/prefetcher.py)"))
register_knob(Knob(
    "serving_batch_buckets", "MXNET_SERVING_BATCH_BUCKETS", str,
    "1,2,4,8",
    ("1,2,4,8", "1,4,8", "1,2,4,8,16"), "serving",
    "decode batch-size buckets the serving engine AOT-compiles "
    "(serving/engine.py)"))
register_knob(Knob(
    "serving_prefill_buckets", "MXNET_SERVING_PREFILL_BUCKETS", str,
    "32,64,128",
    ("32,64,128", "16,32,64,128", "64,128", "32,128"), "serving",
    "prompt-length prefill buckets (prompts right-pad up; "
    "serving/engine.py)"))
register_knob(Knob(
    "serving_page_size", "MXNET_SERVING_PAGE_SIZE", int, 16,
    (8, 16, 32), "serving",
    "tokens per KV-cache page (serving/kvcache.py)"))
