"""Knob-space search driver: grid + successive halving, scored by the
live gauges, deterministic candidate order.

The measurement loop of the TVM blueprint (PAPERS.md): enumerate the
declared grid, measure every candidate at a small budget, keep the
better half, double the budget, repeat — so the cheap rungs prune the
obviously-bad region and only the contenders pay a full-budget
measurement (early stopping by construction).

Determinism contract: the candidate SCHEDULE — which values run, in
which order, at which budget — is a pure function of the knob's
declared grid and the rung parameters.  Two processes tuning the same
knob walk identical schedules (ties in a rung break by grid position,
never by dict/hash order); only the measured scores, and therefore
the winner, reflect the machine.  ``ci/runtest.sh tuning`` asserts the
schedule's cross-process identity.

Scores are "lower is better" throughout (seconds per step for
training arms).  Serving arms measure tokens/s + p99 TTFT — callers
fold those into one ascending score (e.g. negative tokens/s plus a
TTFT penalty) so one driver serves both gauge families.
"""
from __future__ import annotations

from .. import telemetry as _telemetry
from . import knobs as _knobs

__all__ = ["schedule", "successive_halving", "tune_knob"]

_TRIALS = _telemetry.counter(
    "mxnet_tuning_trials_total",
    "search-trial measurements executed by the tuning driver "
    "(a warm replay of a stored winner performs zero)",
    labelnames=("knob",))


def schedule(knob, rungs=2, budget0=2, eta=2):
    """The deterministic rung schedule for ``knob``: a list of
    ``(budget, n_candidates)`` pairs, BEFORE any measurement.  Rung 0
    holds the default + the full grid (deduplicated, grid order);
    each later rung keeps the better half (ceil) at ``eta``× the
    budget.  Pure — this is the cross-process identical part."""
    if isinstance(knob, str):
        knob = _knobs.get_knob(knob)
    seen = []
    for v in (knob.default,) + knob.grid:
        if v not in seen:
            seen.append(v)
    out = []
    n = len(seen)
    budget = max(1, int(budget0))
    for _ in range(max(1, int(rungs))):
        out.append((budget, n))
        if n <= 1:
            break
        n = (n + 1) // 2
        budget *= max(2, int(eta))
    return {"candidates": seen, "rungs": out}


def successive_halving(knob, measure, rungs=2, budget0=2, eta=2,
                       log=None):
    """Run the rung schedule: ``measure(value, budget) -> score``
    (ascending = better).  Returns ``(results, trials)`` where
    ``results`` is the final rung's ``[(score, value), ...]`` sorted
    ascending (ties by grid position) and ``trials`` counts every
    measurement made.  A candidate whose measurement raises is dropped
    from the rung (scored ``inf``) — one pathological config must not
    kill the whole search."""
    if isinstance(knob, str):
        knob = _knobs.get_knob(knob)
    plan = schedule(knob, rungs=rungs, budget0=budget0, eta=eta)
    order = {v: i for i, v in enumerate(plan["candidates"])}
    survivors = list(plan["candidates"])
    trials = 0
    scored = []
    for budget, keep in plan["rungs"]:
        survivors = survivors[:keep]
        scored = []
        for value in survivors:         # deterministic order
            try:
                score = float(measure(value, budget))
            except Exception as e:
                if log is not None:
                    log(f"tuning trial {knob.name}={value!r} failed: "
                        f"{e!r}")
                score = float("inf")
            trials += 1
            _TRIALS.labels(knob=knob.name).inc()
            scored.append((score, value))
        scored.sort(key=lambda sv: (sv[0], order[sv[1]]))
        survivors = [v for _, v in scored]
    return scored, trials


def tune_knob(knob, measure, db=None, signature=None, plan_digest=None,
              rungs=2, budget0=2, eta=2, unit="s", log=None):
    """Search one knob and (when it wins cleanly) persist the winner.

    Returns a report dict: winner, per-candidate final-rung scores,
    the default's measured score, the best-vs-default delta, and the
    trial count.  An env-pinned knob is NOT searched — explicit
    overrides always win and the report records the pin instead
    (``tuning.resolve`` will keep honoring the pin regardless of any
    DB entry, so searching under it would measure a lie).
    """
    import os

    if isinstance(knob, str):
        knob = _knobs.get_knob(knob)
    raw = os.environ.get(knob.env_var)
    if raw not in (None, ""):
        return {"knob": knob.name, "pinned": knob.parse(raw),
                "source": "env", "trials": 0,
                "detail": f"{knob.env_var} is set; explicit overrides "
                          "always win — not searched"}
    from . import trial_override

    def _measure(value, budget):
        with trial_override(knob.name, value):
            return measure(value, budget)

    results, trials = successive_halving(
        knob, _measure, rungs=rungs, budget0=budget0, eta=eta, log=log)
    best_score, best_value = results[0]
    # the default's score from the FINAL rung when it survived there,
    # else a dedicated full-budget measurement — deltas must compare
    # equal budgets
    default_score = None
    for score, value in results:
        if value == knob.default:
            default_score = score
            break
    if default_score is None:
        final_budget = schedule(knob, rungs=rungs, budget0=budget0,
                                eta=eta)["rungs"][-1][0]
        default_score = float(_measure(knob.default, final_budget))
        trials += 1
        _TRIALS.labels(knob=knob.name).inc()
    report = {
        "knob": knob.name, "unit": unit, "trials": trials,
        "winner": best_value, "winner_score": best_score,
        "default": knob.default, "default_score": default_score,
        "delta_pct": round((default_score - best_score)
                           / default_score * 100.0, 2)
        if default_score else 0.0,
        "final_rung": [{"value": v, "score": s} for s, v in results],
    }
    if db is not None and best_score != float("inf"):
        report["stored"] = bool(db.put_winner(
            knob, best_value, signature=signature,
            plan_digest=plan_digest, score=best_score,
            default_score=default_score, trials=trials, unit=unit))
    return report
