"""Online MFU accounting: per-executable FLOPs meet the wall clock.

The bench rounds compute MFU offline, once per round, from analytic
FLOP formulas.  This module makes utilization a *live* metric: every
compiled executable's FLOP count is captured ONCE at compile time from
XLA's own cost model (``lower(...).compile().cost_analysis()`` — the
TrainStep AOT path, the serving prefill/decode/sample grid, and
compile-cache warm loads, which carry the count in the cache entry so a
warm start never re-derives it), and every steady-state dispatch does
nothing but a host-side float add into a trailing window.  From the
window and a per-device peak-FLOPs registry two gauges fall out:

- ``mxnet_model_flops_utilization`` — dispatched FLOPs over
  ``elapsed × peak × device_count`` for the trailing window.  The gauge
  is created LAZILY: when ``cost_analysis`` is unavailable (platform
  quirk, warm load without a recorded count) or the device peak is
  unknown (non-TPU backend, no ``MXNET_DEVICE_PEAK_FLOPS`` override),
  the gauge is simply **absent** — never present-but-wrong.
- ``mxnet_executable_flops_total{kind}`` — raw dispatched FLOPs by
  consumer kind (``train_step`` / ``serving_prefill`` /
  ``serving_decode`` / ``serving_sample``), always on.

Hot-path contract: :func:`account_flops` never touches a device array —
no host syncs, no traces; ``flops_of`` runs only inside the (already
cold) compile paths.  FLOP counts from ``cost_analysis`` are for the
whole (global) program, so utilization divides by the GLOBAL device
count — every SPMD rank computes the same number, which is what the
cross-rank aggregation (``telemetry_agg``) expects to see agree.
"""
from __future__ import annotations

import threading
import time
from collections import deque

from . import env as _env
from . import telemetry as _telemetry

__all__ = ["device_peak_flops", "flops_of", "account_flops",
           "utilization", "window_stats", "reset"]

# bf16 peak FLOP/s per chip by device_kind substring (the same table
# bench.py's offline MFU uses; MXNET_DEVICE_PEAK_FLOPS overrides)
_PEAKS = (("v5 lite", 197e12), ("v5e", 197e12), ("v5p", 459e12),
          ("v6", 918e12), ("v4", 275e12), ("v3", 123e12), ("v2", 45e12))

_LOCK = threading.Lock()
_WINDOW: deque = deque(maxlen=512)    # (perf_counter t, flops)
_WINDOW_SUM = [0.0]                   # running sum (no O(window) scans)
_MFU_GAUGE = None                     # created lazily on first valid util
_DEVICES = [None]                     # cached global device count
_KIND_PEAK = [False]                  # cached device-kind table lookup

_FLOPS_TOTAL = _telemetry.counter(
    "mxnet_executable_flops_total",
    "FLOPs dispatched, from compile-time cost_analysis, by consumer",
    labelnames=("kind",))


def device_peak_flops():
    """Per-device peak FLOP/s: the ``MXNET_DEVICE_PEAK_FLOPS`` override
    when set, else the TPU device-kind table, else None (unknown — the
    MFU gauge stays absent rather than guessing a CPU peak).  The env
    var is re-read every call (the bench A/B flips it mid-process); the
    device-kind table lookup is resolved once and cached — this runs on
    every account_flops, so it must stay one env read + one list
    read."""
    override = _env.device_peak_flops_override()
    if override > 0:
        return override
    if _KIND_PEAK[0] is False:
        peak = None
        try:
            import jax

            kind = jax.devices()[0].device_kind.lower()
            for sub, p in _PEAKS:
                if sub in kind:
                    peak = p
                    break
        except Exception:
            peak = None
        _KIND_PEAK[0] = peak
    return _KIND_PEAK[0]


def _device_count():
    if _DEVICES[0] is None:
        try:
            import jax

            _DEVICES[0] = max(1, jax.device_count())
        except Exception:
            _DEVICES[0] = 1
    return _DEVICES[0]


def flops_of(compiled):
    """FLOP count of a compiled executable from XLA's cost model, or
    None when unavailable (the graceful-fallback contract: an absent
    count means an absent gauge, never a wrong one).  Accepts both
    cost_analysis shapes across jax versions (dict or list-of-dict)."""
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        v = float(cost.get("flops", 0.0))
        return v if v > 0 else None
    except Exception:
        return None


def account_flops(flops, kind="train_step"):
    """Record one dispatched executable's FLOPs (host-side only: a
    float add + deque append + gauge arithmetic — ZERO device work).
    Called with the compile-time count on every TrainStep call and
    every serving prefill/decode step; a None/0 count is a no-op."""
    if not flops:
        return
    now = time.perf_counter()
    _FLOPS_TOTAL.labels(kind=kind).inc(float(flops))
    with _LOCK:
        if len(_WINDOW) == _WINDOW.maxlen:
            # about to evict the oldest entry: keep the running sum
            # exact so utilization never scans the window
            _WINDOW_SUM[0] -= _WINDOW[0][1]
        _WINDOW.append((now, float(flops)))
        _WINDOW_SUM[0] += float(flops)
    _update_gauge(now)


def utilization(now=None):
    """Model FLOPs utilization over the trailing window: dispatched
    FLOPs / (elapsed × peak × global device count).  None when the peak
    is unknown or fewer than two events are in the window."""
    peak = device_peak_flops()
    if not peak:
        return None
    if now is None:
        now = time.perf_counter()
    with _LOCK:
        if len(_WINDOW) < 2:
            return None
        t0 = _WINDOW[0][0]
        total = _WINDOW_SUM[0]
    dt = now - t0
    if dt <= 0:
        return None
    return total / (dt * peak * _device_count())


def _update_gauge(now):
    global _MFU_GAUGE
    util = utilization(now)
    if util is None:
        return
    if _MFU_GAUGE is None:
        # lazy registration IS the fallback contract: with no usable
        # FLOPs source or peak the family never exists, so a scrape
        # sees "no data" instead of a fabricated 0.0
        _MFU_GAUGE = _telemetry.gauge(
            "mxnet_model_flops_utilization",
            "dispatched FLOPs over elapsed x peak x device count "
            "(trailing window; absent when FLOPs/peak are unknown)")
    _MFU_GAUGE.set(util)


def window_stats():
    """Diagnostics: ``{"events", "flops", "span_s", "peak",
    "devices"}`` for the trailing window (bench/teldump context)."""
    now = time.perf_counter()
    with _LOCK:
        events = len(_WINDOW)
        total = _WINDOW_SUM[0]
        span = (now - _WINDOW[0][0]) if _WINDOW else 0.0
    return {"events": events, "flops": total, "span_s": span,
            "peak": device_peak_flops(), "devices": _device_count()}


def reset():
    """Clear the accounting window (test isolation / bench A-B arms).
    The lazily-created gauge family, once registered, stays registered
    (telemetry families are process-wide); its value re-zeros through
    ``telemetry.reset()``."""
    with _LOCK:
        _WINDOW.clear()
        _WINDOW_SUM[0] = 0.0
    _DEVICES[0] = None
    _KIND_PEAK[0] = False
