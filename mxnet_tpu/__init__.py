"""mxnet_tpu — a TPU-native deep learning framework with MXNet's API surface.

A ground-up rebuild of Apache MXNet 1.x's capabilities (reference:
ddlee96/incubator-mxnet, surveyed in SURVEY.md) designed TPU-first:

- the C++ dependency engine is replaced by JAX/XLA async dispatch;
- the ~1000-op C++/CUDA zoo is a single registry of pure jax functions that
  XLA fuses and tiles onto the MXU (plus Pallas kernels for flash attention);
- ``hybridize()`` stages Gluon models into ``jax.jit`` computations instead
  of NNVM graphs;
- KVStore data-parallelism is XLA collectives over the ICI/DCN mesh
  (``dist_tpu_sync``) instead of ps-lite/NCCL.

Typical use — identical to the reference's surface:

    import mxnet_tpu as mx
    from mxnet_tpu import gluon, nd, autograd

    ctx = mx.tpu()
    net = gluon.model_zoo.vision.resnet50_v1()
    net.initialize(ctx=ctx)
    net.hybridize(static_alloc=True)
    trainer = gluon.Trainer(net.collect_params(), 'sgd', {'learning_rate': 0.1})
    with autograd.record():
        loss = loss_fn(net(x), y)
    loss.backward()
    trainer.step(batch_size)
"""
from __future__ import annotations

__version__ = "0.1.0"

from .base import MXNetError
from . import engine

engine._init_from_env()

from .context import Context, cpu, gpu, tpu, cpu_pinned, current_context, num_gpus, num_tpus
from . import context
from . import base
from . import autograd
from . import random
from . import ndarray
from . import ndarray as nd
from . import initializer
from . import initializer as init
from . import optimizer
from . import lr_scheduler
from . import metric
from . import kvstore
from . import kvstore as kv
from . import symbol
from . import symbol as sym
from .executor import Executor
from . import module
from . import module as mod
from . import rnn
from . import operator
from . import model
from . import gluon
from . import io
from . import recordio
from . import image
from . import profiler
from . import checkpoint
from . import visualization
from . import visualization as viz
from . import util
from .util import test_utils
from . import runtime
from . import callback
from . import monitor
from . import graph
from . import subgraph
from . import numpy as np  # mx.np — NumPy-compatible namespace
from . import numpy_extension as npx
from . import env
from . import fault
from . import telemetry
from . import flight_recorder
from . import lifecycle
from . import tuning

env.apply_env()
from . import parallel
from . import contrib

from .ndarray import NDArray
