"""Device contexts: ``mx.cpu()``, ``mx.tpu()``, ``mx.gpu()``.

Reference: ``python/mxnet/context.py`` (class Context, mx.cpu()/mx.gpu(),
num_gpus) — SURVEY.md §3.5 "Misc frontend": this is *the thing mx.tpu()
extends* per the north star.  Here a Context is a thin, hashable handle that
resolves to a concrete ``jax.Device``.

Design notes (TPU-first):
- ``tpu`` maps to the JAX accelerator backend (platform "tpu", or the
  experimental "axon" tunnel platform used in this environment).
- ``gpu`` is accepted for script compatibility and resolves to the
  accelerator as well ("GluonCV scripts run unmodified by swapping
  mx.gpu() -> mx.tpu()" — we go one better and make the swap optional).
- ``cpu_pinned``/``cpu_shared`` degenerate to cpu: XLA manages host staging
  buffers itself, so the reference's pinned/shm storage managers
  (src/storage/) have no TPU-side analog.
"""
from __future__ import annotations

import threading

from .base import MXNetError

__all__ = ["Context", "cpu", "gpu", "tpu", "cpu_pinned", "current_context", "num_gpus", "num_tpus"]


def _jax():
    import jax

    return jax


class Context:
    """Device context. Hashable, comparable; ``with ctx:`` sets the default.

    Reference: python/mxnet/context.py class Context.
    """

    devtype2str = {1: "cpu", 2: "gpu", 3: "cpu_pinned", 4: "cpu_shared", 5: "tpu"}
    devstr2type = {v: k for k, v in devtype2str.items()}
    _default_ctx = threading.local()

    def __init__(self, device_type, device_id=0):
        if isinstance(device_type, Context):
            self.device_typeid = device_type.device_typeid
            self.device_id = device_type.device_id
        else:
            if device_type not in self.devstr2type:
                raise MXNetError(f"unknown device type {device_type!r}")
            self.device_typeid = self.devstr2type[device_type]
            self.device_id = device_id
        self._old_ctx = None

    @property
    def device_type(self):
        return self.devtype2str[self.device_typeid]

    # -- jax resolution ----------------------------------------------------
    @property
    def device(self):
        """Concrete jax.Device this context resolves to.

        In a multi-process (jax.distributed) job, contexts index the
        *process-local* devices — the reference's ctx numbering is likewise
        per-worker (each ps-lite worker sees only its own GPUs)."""
        jax = _jax()
        if self.device_type in ("cpu", "cpu_pinned", "cpu_shared"):
            devs = [d for d in jax.local_devices() if d.platform == "cpu"] \
                or jax.devices("cpu")
        else:  # tpu / gpu -> accelerator backend
            devs = _accelerator_devices()
            if not devs:
                raise MXNetError(
                    f"Context {self} requested but no accelerator devices are "
                    "visible to JAX; use mx.cpu() or set JAX_PLATFORMS."
                )
        if self.device_id >= len(devs):
            raise MXNetError(
                f"{self}: device_id out of range (have {len(devs)} devices)"
            )
        return devs[self.device_id]

    # -- default-context management ---------------------------------------
    @classmethod
    def _current(cls):
        if not hasattr(cls._default_ctx, "value"):
            cls._default_ctx.value = Context("cpu", 0)
        return cls._default_ctx.value

    def __enter__(self):
        self._old_ctx = Context._current()
        Context._default_ctx.value = self
        return self

    def __exit__(self, *exc):
        Context._default_ctx.value = self._old_ctx
        return False

    def __eq__(self, other):
        return (
            isinstance(other, Context)
            and self.device_typeid == other.device_typeid
            and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((self.device_typeid, self.device_id))

    def __repr__(self):
        return f"{self.device_type}({self.device_id})"

    def __str__(self):
        return self.__repr__()


def _accelerator_devices():
    """Process-local non-cpu jax devices (tpu, or the axon tunnel platform)."""
    jax = _jax()
    local = [d for d in jax.local_devices() if d.platform != "cpu"]
    if local:
        return local
    return [d for d in jax.devices() if d.platform != "cpu"]


def cpu(device_id=0):
    return Context("cpu", device_id)


def cpu_pinned(device_id=0):
    return Context("cpu_pinned", device_id)


def tpu(device_id=0):
    return Context("tpu", device_id)


def gpu(device_id=0):
    """Compatibility alias: resolves to the accelerator backend (see module
    docstring). Falls back at *resolution* time, not here."""
    return Context("gpu", device_id)


def num_gpus():
    return len(_accelerator_devices())


def num_tpus():
    return len(_accelerator_devices())


def current_context():
    return Context._current()
