"""Cross-rank telemetry aggregation: one merged, rank-labeled view of a
multi-process job, with per-phase straggler skew.

Layer 3 of the runtime introspection plane (ISSUE 14).  Every rank has
had a complete ``telemetry.snapshot()`` since PR 3 — but each one is an
island: rank 7 being 40 ms slower in its ``data`` phase every step is
invisible until it becomes a watchdog stall.  This module merges the
per-rank snapshots into

- **rank-labeled families** — every metric family from every rank, its
  samples carrying a ``rank`` label, in one document; and
- **per-phase skew histograms** — ``mxnet_rank_step_skew_seconds``
  observes, per phase, ``max - min`` of the per-rank durations at the
  newest step every rank has reported, so a straggler is a visible
  distribution long before it wedges the mesh.

Transport contract — **never a device collective**: ranks exchange
snapshots through atomically-published JSON files in a shared directory
(``MXNET_TELEMETRY_AGG_DIR``).  The publish rides the existing uniform
step boundary (``telemetry.step_end`` and ``lifecycle.check_stop``
both tick the stride counter) purely because that is where a
consistent per-step cut exists — the IO is host-side, so a rank
publishing late or not at all degrades the merge, never the job
(MXT001/003 have nothing to taint).  Every
``MXNET_TELEMETRY_AGG_EVERY``-th tick a rank rewrites its own
``rank<N>.json``; rank 0 additionally merges whatever peer files exist
and serves the result at the ``/agg`` route beside ``/metrics``.

:func:`merge_snapshots` itself is a pure, deterministic function of its
inputs (CI asserts two merges of the same snapshots are identical), so
``tools/teldump`` can re-merge offline from the same files.

Two extensions (ISSUE 15):

- ``MXNET_TELEMETRY_AGG_TRANSPORT=kv`` rides the jax.distributed KV
  store instead of a shared filesystem (pods without one) — snapshot
  gather only; the publish/merge semantics are identical.
- :func:`merge_blackboxes` merges the flight recorder's per-rank
  ``blackbox.rank<N>.json`` crash dumps
  (:mod:`mxnet_tpu.flight_recorder`) and emits a **blame verdict** —
  which collective the mesh wedged in, at which sequence number, and
  which rank fell out of program order.  Black-box dumps are ALWAYS
  file-based regardless of the snapshot transport: they are written
  while the distributed runtime is presumed dead.
"""
from __future__ import annotations

import json
import os
import re
import tempfile
import threading
import time

from . import env as _env
from . import telemetry as _telemetry

__all__ = ["merge_snapshots", "skew_from_snapshots", "configure",
           "tick", "publish", "publish_kv", "read_kv", "merge_dir",
           "read_dir", "merged", "read_blackboxes", "merge_blackboxes",
           "reset"]

_SKEW_HIST = _telemetry.histogram(
    "mxnet_rank_step_skew_seconds",
    "per-phase max-min spread of step-phase durations across ranks at "
    "the newest common step (straggler visibility)",
    labelnames=("phase",),
    buckets=[1e-4, 3e-4, 1e-3, 3e-3, 1e-2, 3e-2, 0.1, 0.3, 1.0, 3.0,
             10.0])
_MERGES = _telemetry.counter(
    "mxnet_telemetry_agg_merges_total",
    "cross-rank snapshot merges performed by this process")
_AGG_RANKS = _telemetry.gauge(
    "mxnet_telemetry_agg_ranks",
    "ranks present in the latest cross-rank merge")
_LEDGER_SKEW = _telemetry.gauge(
    "mxnet_collective_ledger_skew",
    "max-min spread of the per-rank collective-ledger positions at "
    "the latest cross-rank merge (a growing spread is the pre-hang "
    "signature: some rank stopped issuing collectives)")
_LEDGER_SKEW_ALERTS = _telemetry.counter(
    "mxnet_ledger_skew_alerts_total",
    "ledger-skew pre-hang alerts: cross-rank position divergence "
    "above MXNET_LEDGER_SKEW_THRESHOLD for MXNET_LEDGER_SKEW_WINDOWS "
    "consecutive aggregation merges")

# episode state for the pre-hang alert — the goodput-SLO discipline
# (telemetry._goodput_slo_tick): N consecutive above-threshold merges
# fire ONE alert; a merge back below the threshold re-arms it
_SKEW_ALERT_STATE = {"above": 0, "fired": False}

_LOCK = threading.Lock()
_STATE = {
    "configured": False,
    "dir": None,
    "every": 0,
    "rank": 0,
    "world": 1,
    "ticks": 0,
    "merged": None,      # latest merged doc (aggregating rank only)
    "route": False,
    "warned": False,
    # snapshot-gather transport: "file" (shared dir) or "kv" (the
    # jax.distributed KV store — pods without a shared filesystem,
    # ROADMAP follow-on (b)).  Black-box dumps are ALWAYS file-based:
    # they are written while the distributed runtime is presumed dead.
    "transport": "file",
    "kv_client": None,   # injected client (tests) or resolved lazily
    "kv_warned": False,
}

_KV_PREFIX = "mxnet_tpu/telemetry_agg/rank"

_RANK_FILE = re.compile(r"^rank(\d+)\.json$")


# --------------------------------------------------------------------------
# pure merge (deterministic: same snapshots in -> same document out)
# --------------------------------------------------------------------------
def merge_snapshots(snaps):
    """Merge ``{rank: telemetry-snapshot}`` into one document.

    Deterministic and pure: ranks are processed in sorted order, no
    clock reads feed the payload (the newest input snapshot's ``time``
    is carried through), so merging the same inputs twice yields the
    same document — the property teldump's offline re-merge and the CI
    determinism assertion rely on.

    Output shape::

        {"time", "ranks": [...], "metrics": {name: {type, help,
         samples: [{labels: {..., "rank": "0"}, ...}]}},
         "skew": {"step": N|None, "phases": {phase: max-min}},
         "per_rank": {rank: {steps, last_step, compile_count,
                             goodput_ratio}}}
    """
    snaps = {int(r): s for r, s in dict(snaps).items()}
    ranks = sorted(snaps)
    metrics: dict = {}
    per_rank: dict = {}
    for rank in ranks:
        snap = snaps[rank]
        for name, fam in sorted((snap.get("metrics") or {}).items()):
            out = metrics.setdefault(
                name, {"type": fam.get("type"),
                       "help": fam.get("help", ""), "samples": []})
            for sample in fam.get("samples", ()):
                labeled = dict(sample)
                labels = dict(labeled.get("labels") or {})
                labels["rank"] = str(rank)
                labeled["labels"] = labels
                out["samples"].append(labeled)
        steps = snap.get("steps") or []
        per_rank[rank] = {
            "steps": len(steps),
            "last_step": steps[-1]["step"] if steps else None,
            "compile_count": (snap.get("compile") or {}).get("count"),
            "goodput_ratio": (snap.get("goodput") or {}).get(
                "productive_ratio"),
        }
    step, phases = skew_from_snapshots(snaps)
    return {
        "time": max((s.get("time") or 0) for s in snaps.values())
        if snaps else 0,
        "ranks": ranks,
        "metrics": metrics,
        "skew": {"step": step, "phases": phases},
        "per_rank": per_rank,
    }


def skew_from_snapshots(snaps):
    """``(step, {phase: max-min seconds})`` at the newest step EVERY
    rank has a timeline record for (``(None, {})`` when there is no
    common step — e.g. a rank that has not completed a step yet)."""
    per_rank_steps = {}
    for rank, snap in snaps.items():
        per_rank_steps[rank] = {rec["step"]: rec
                                for rec in (snap.get("steps") or [])}
    if not per_rank_steps or any(not d for d in per_rank_steps.values()):
        return None, {}
    common = set.intersection(*(set(d) for d in per_rank_steps.values()))
    if not common:
        return None, {}
    step = max(common)
    phases: dict = {}
    names = set()
    for d in per_rank_steps.values():
        names.update(d[step]["phases"])
    for name in sorted(names):
        vals = [d[step]["phases"].get(name, 0.0)
                for d in per_rank_steps.values()]
        phases[name] = max(vals) - min(vals)
    return step, phases


# --------------------------------------------------------------------------
# ledger-position skew: the pre-hang alert (flight-recorder follow-on)
# --------------------------------------------------------------------------
def _ledger_positions(doc):
    """``{rank: position}`` from a merged doc's rank-labeled
    ``mxnet_collective_ledger_position`` samples (a rank without the
    gauge — recorder off — is simply absent)."""
    fam = (doc.get("metrics") or {}).get(
        "mxnet_collective_ledger_position") or {}
    out = {}
    for sample in fam.get("samples", ()):
        r = (sample.get("labels") or {}).get("rank")
        try:
            out[int(r)] = float(sample.get("value"))
        except (TypeError, ValueError):
            continue
    return out


def _ledger_skew_tick(doc):
    """One aggregation-merge window of the pre-hang alert: when the
    cross-rank ledger-position spread stays above
    ``MXNET_LEDGER_SKEW_THRESHOLD`` for
    ``MXNET_LEDGER_SKEW_WINDOWS`` consecutive merges, fire ONE
    lifecycle alert naming the lagging rank(s); re-arm only after a
    merge back below the threshold — a sustained divergence pages
    once, not every merge.  The goodput-SLO hook pattern, moved one
    layer down: this fires while every rank is still alive, BEFORE
    the watchdog/black-box machinery has a corpse to blame."""
    threshold = _env.ledger_skew_threshold()
    if threshold <= 0:
        return
    positions = _ledger_positions(doc)
    if len(positions) < 2:
        return          # nothing to diverge from
    skew = int(max(positions.values()) - min(positions.values()))
    _LEDGER_SKEW.set(skew)
    if skew < threshold:
        _SKEW_ALERT_STATE["above"] = 0
        _SKEW_ALERT_STATE["fired"] = False
        return
    _SKEW_ALERT_STATE["above"] += 1
    if _SKEW_ALERT_STATE["fired"] or \
            _SKEW_ALERT_STATE["above"] < _env.ledger_skew_windows():
        return
    _SKEW_ALERT_STATE["fired"] = True
    _LEDGER_SKEW_ALERTS.inc()
    low = min(positions.values())
    laggards = sorted(r for r, p in positions.items() if p == low)
    try:
        from . import lifecycle as _lc

        _lc.note_ledger_skew(skew, threshold,
                             _SKEW_ALERT_STATE["above"], laggards)
    except Exception:   # alerting must never break a merge
        pass


# --------------------------------------------------------------------------
# black-box merge + blame (the flight-recorder half of this module)
# --------------------------------------------------------------------------
_BLACKBOX_FILE = re.compile(r"^blackbox\.rank(\d+)\.json$")


def read_blackboxes(directory):
    """``{rank: blackbox-doc}`` from every readable
    ``blackbox.rank<N>.json`` in the directory.  A torn/garbage file is
    skipped — each rank dumped alone while dying, so the merge is
    best-effort by construction."""
    boxes = {}
    try:
        names = os.listdir(directory)
    except OSError:
        return boxes
    for name in sorted(names):
        m = _BLACKBOX_FILE.match(name)
        if not m:
            continue
        try:
            with open(os.path.join(directory, name)) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        if not isinstance(doc, dict) or "events" not in doc:
            continue
        boxes[int(m.group(1))] = doc
    return boxes


def _ledger_of(doc):
    """``{seq: collective-entry}`` from one black-box doc (ring order;
    a wrapped ring keeps only the tail — the newest window, which is
    the one that matters for blame)."""
    out = {}
    for e in doc.get("events") or ():
        if isinstance(e, dict) and e.get("kind") == "collective" \
                and isinstance(e.get("seq"), int):
            out[e["seq"]] = e
    return out


def _last_step_of(doc):
    """The newest training step this rank's ring mentions (the
    ``step`` context events telemetry.step_begin/step_end record), or
    None when the ring holds none — the step-alignment half of blame:
    seq numbers say WHERE in the collective program a rank stopped,
    the step events say how far the TRAINING LOOP got."""
    last = None
    for e in doc.get("events") or ():
        if isinstance(e, dict) and e.get("kind") == "step" \
                and isinstance(e.get("step"), int):
            if last is None or e["step"] > last:
                last = e["step"]
    return last


def _verdict(kind, detail, ranks=(), seq=None, tag=None, digest=None):
    return {"kind": kind, "detail": detail,
            "ranks": sorted(int(r) for r in ranks),
            "seq": seq, "tag": tag, "digest": digest}


def merge_blackboxes(boxes):
    """Merge ``{rank: blackbox-doc}`` into one report with a **blame
    verdict** — pure and deterministic (no clock reads; same boxes in →
    byte-identical document out, the property ``teldump blame``'s
    offline re-merge relies on).

    The ledgers align by the per-rank collective sequence number: the
    equal-call-count contract (parallel/collectives.py) means equal
    seq across ranks must carry equal tags.  Verdicts, in priority
    order:

    - ``numerical_divergence`` — the guard's quarantine evidence
      (``guard_checksum`` post-allreduce bucket digests /
      ``guard_canary`` recompute digests, bit-identical across ranks by
      construction) disagrees at a (step, key): silent data corruption
      or desync ON the named minority rank(s).  Checked first —
      explicit recorded evidence beats the inferred verdicts below.
    - ``desync`` — the first sequence number where ranks' tags diverge
      (a rank issued a different/extra collective); blamed ranks are
      the minority tag holders at that seq.
    - ``hang`` — the lagging rank(s): wedged *inside* their last
      entered collective (no exit stamp), failed in it (error stamp),
      or stopped *between* collectives (never entered the leaders'
      next seq).
    - ``all_wedged`` — every rank entered the SAME seq and none
      exited: the collective itself (interconnect, a dead device), not
      a lagging rank.
    - ``no_blame`` / ``single_rank`` / ``no_data`` — nothing to blame,
      one ring only, or no rings.
    """
    boxes = {int(r): d for r, d in dict(boxes).items()}
    ranks = sorted(boxes)
    ledgers = {r: _ledger_of(boxes[r]) for r in ranks}
    per_rank = {}
    for r in ranks:
        led = ledgers[r]
        last = led[max(led)] if led else None
        per_rank[r] = {
            "reason": boxes[r].get("reason"),
            "time": boxes[r].get("time"),
            "position": boxes[r].get("position"),
            "events": len(boxes[r].get("events") or ()),
            "last_seq": max(led) if led else 0,
            "first_seq": min(led) if led else 0,
            "last_tag": last.get("tag") if last else None,
            "last_exited": bool(last and "t1" in last
                                and "error" not in last),
            "last_error": (last or {}).get("error"),
            "last_step": _last_step_of(boxes[r]),
        }
    doc = {
        "format": 1,
        "ranks": ranks,
        "per_rank": per_rank,
        "time": max((boxes[r].get("time") or 0) for r in ranks)
        if ranks else 0,
    }
    verdict = _blame(ranks, ledgers, per_rank, boxes)
    # step alignment: when the blamed rank's ring carries step context
    # events, translate the seq-space verdict into loop-space too —
    # "rank 3 is 2 steps behind" reads at a glance what seq numbers
    # only imply.  Pure post-processing of per_rank, so the verdict
    # stays deterministic.
    steps = {r: per_rank[r]["last_step"] for r in ranks
             if per_rank[r]["last_step"] is not None}
    verdict["step_lag"] = None
    blamed_with_steps = sorted(r for r in verdict.get("ranks") or ()
                               if r in steps)
    if len(steps) > 1 and blamed_with_steps:
        lead = max(steps.values())
        b = min(blamed_with_steps, key=lambda r: (steps[r], r))
        lag = int(lead - steps[b])
        if lag > 0:
            verdict["step_lag"] = lag
            verdict["detail"] += (
                f"; rank {b} is {lag} step(s) behind "
                f"(step {steps[b]} vs leaders' step {lead})")
    doc["verdict"] = verdict
    return doc


def _blame(ranks, ledgers, per_rank, boxes):
    if not ranks:
        return _verdict("no_data", "no black-box files to merge")
    # -- numerical divergence: guard checksum/canary digests disagree --
    # The quarantine tier (mxnet_tpu/guard.py) stamps digests of data
    # that is bit-identical across ranks by construction; a mismatch at
    # the same (step, key) is positive evidence of SDC/desync on the
    # minority rank — stronger than anything inferred from ledger
    # positions, so it is checked before every other verdict.
    if len(ranks) > 1:
        stamped: dict = {}
        for r in ranks:
            for e in boxes[r].get("events") or ():
                if not isinstance(e, dict):
                    continue
                if e.get("kind") == "guard_checksum":
                    k = (e.get("step"), str(e.get("key")))
                    d = e.get("crc")
                elif e.get("kind") == "guard_canary":
                    k = (e.get("step"), "__canary__")
                    d = e.get("digest")
                else:
                    continue
                stamped.setdefault(k, {})[r] = (d, e.get("seq"))
        for k in sorted(stamped, key=lambda kk: (kk[0] is None,
                                                 kk[0] or 0, kk[1])):
            per = stamped[k]
            if len(per) < 2:
                continue
            vals = {r: v[0] for r, v in per.items()}
            if len(set(vals.values())) <= 1:
                continue
            counts: dict = {}
            for d in vals.values():
                counts[d] = counts.get(d, 0) + 1
            majority = max(sorted(counts, key=repr),
                           key=lambda d: counts[d])
            blamed = sorted(r for r, d in vals.items() if d != majority)
            if len(set(counts.values())) == 1 and len(counts) > 1:
                blamed = sorted(vals)       # tie: every holder suspect
            step, key = k
            b0 = blamed[0]
            v = _verdict(
                "numerical_divergence",
                f"guard digest for {key!r} at step {step} diverges: " +
                ", ".join(f"rank {r}={vals[r]!r}"
                          for r in sorted(vals)) +
                " — the stamped payload is bit-identical across ranks "
                "by construction, so the minority rank(s) hold "
                "corrupted values (SDC or silent desync)",
                ranks=blamed, seq=per[b0][1], tag=key,
                digest=vals[b0])
            v["step"] = step
            return v
    # -- desync: first seq where tags diverge across any two ranks -----
    if len(ranks) > 1:
        shared = set()
        for r in ranks:
            shared |= set(ledgers[r])
        for seq in sorted(shared):
            tags = {r: ledgers[r][seq].get("tag")
                    for r in ranks if seq in ledgers[r]}
            if len(tags) < 2 or len(set(tags.values())) <= 1:
                continue
            counts: dict = {}
            for t in tags.values():
                counts[t] = counts.get(t, 0) + 1
            majority = max(sorted(counts), key=lambda t: counts[t])
            blamed = sorted(r for r, t in tags.items() if t != majority)
            if len(set(counts.values())) == 1 and len(counts) > 1:
                blamed = sorted(tags)       # tie: every holder suspect
            return _verdict(
                "desync",
                f"collective tags diverge at seq {seq}: " +
                ", ".join(f"rank {r}={tags[r]!r}"
                          for r in sorted(tags)) +
                " — a rank issued an extra/different collective "
                "(equal-call-count contract broken)",
                ranks=blamed, seq=seq,
                tag=ledgers[blamed[0]][seq].get("tag") if blamed else None,
                digest=ledgers[blamed[0]][seq].get("digest")
                if blamed else None)
    # -- hang: who lags, and where exactly -----------------------------
    max_seqs = {r: per_rank[r]["last_seq"] for r in ranks}
    lead = max(max_seqs.values())
    laggards = sorted(r for r in ranks if max_seqs[r] < lead)
    # a configured world larger than the dumps we have: a rank that
    # died without dumping is the primary suspect
    world = max((boxes[r].get("world") or 0) for r in ranks)
    missing = sorted(set(range(world)) - set(ranks)) if world > len(ranks) \
        else []
    if missing and not laggards:
        wedged = [r for r in ranks if not per_rank[r]["last_exited"]
                  and max_seqs[r] > 0]
        w = wedged[0] if wedged else None
        detail = (f"rank(s) {missing} wrote no black box"
                  + (f"; rank {w} is wedged in "
                     f"{per_rank[w]['last_tag']!r} seq {max_seqs[w]} "
                     f"waiting on them" if w is not None else ""))
        return _verdict(
            "hang", detail, ranks=missing,
            seq=max_seqs[w] if w is not None else None,
            tag=per_rank[w]["last_tag"] if w is not None else None,
            digest=ledgers[w][max_seqs[w]].get("digest")
            if w is not None else None)
    if laggards:
        low = min(max_seqs[r] for r in laggards)
        blamed = sorted(r for r in laggards if max_seqs[r] == low)
        b = blamed[0]
        led = ledgers[b]
        last = led.get(low)
        if last is not None and "error" in last:
            return _verdict(
                "hang",
                f"rank {b} failed inside {last.get('tag')!r} seq {low} "
                f"({last['error']}) and issued nothing after it",
                ranks=blamed, seq=low, tag=last.get("tag"),
                digest=last.get("digest"))
        if last is not None and "t1" not in last:
            return _verdict(
                "hang",
                f"rank {b} entered {last.get('tag')!r} seq {low} but "
                f"never exited (wedged inside the collective; leaders "
                f"reached seq {lead})",
                ranks=blamed, seq=low, tag=last.get("tag"),
                digest=last.get("digest"))
        # stopped BETWEEN collectives: blame the first seq it never
        # entered, tagged from any leading rank's ledger
        nxt = low + 1
        tag = digest = None
        for r in ranks:
            if nxt in ledgers[r]:
                tag = ledgers[r][nxt].get("tag")
                digest = ledgers[r][nxt].get("digest")
                break
        return _verdict(
            "hang",
            f"rank {b} never entered {tag!r} seq {nxt} (last completed "
            f"seq {low}; leaders reached seq {lead})",
            ranks=blamed, seq=nxt, tag=tag, digest=digest)
    # -- no laggards: same position everywhere --------------------------
    unexited = sorted(r for r in ranks
                      if not per_rank[r]["last_exited"] and lead > 0)
    if unexited and len(unexited) == len(ranks) and len(ranks) > 1:
        tag = per_rank[ranks[0]]["last_tag"]
        return _verdict(
            "all_wedged",
            f"every rank entered {tag!r} seq {lead} and none exited — "
            "the collective itself is wedged (interconnect / dead "
            "device), not a lagging rank",
            ranks=ranks, seq=lead, tag=tag,
            digest=ledgers[ranks[0]][lead].get("digest"))
    if unexited:
        b = unexited[0]
        alone = " (single ring — no peer ledger to compare)" \
            if len(ranks) == 1 else " while peers completed it"
        return _verdict(
            "hang",
            f"rank(s) {unexited} entered {per_rank[b]['last_tag']!r} "
            f"seq {lead} but never exited{alone}",
            ranks=unexited, seq=lead, tag=per_rank[b]["last_tag"],
            digest=ledgers[b][lead].get("digest"))
    if len(ranks) == 1:
        return _verdict(
            "single_rank",
            f"one ring only (rank {ranks[0]}, reason "
            f"{per_rank[ranks[0]]['reason']!r}) — nothing to align "
            "against", ranks=ranks,
            seq=lead or None, tag=per_rank[ranks[0]]["last_tag"])
    return _verdict(
        "no_blame",
        f"all {len(ranks)} ranks completed the same ledger position "
        f"(seq {lead}) — no collective-order fault in the recorded "
        "window", ranks=[])


# --------------------------------------------------------------------------
# the file-based gather
# --------------------------------------------------------------------------
def configure(directory=None, every=None, rank=None, world=None,
              transport=None, kv_client=None):
    """Configure (or reconfigure) the aggregator explicitly.  Defaults
    come from the env knobs / launcher vars; ``every=0`` disables.
    ``transport="kv"`` gathers snapshots through the jax.distributed
    KV store instead of the shared directory (``kv_client`` injects a
    client — tests; production resolves the live coordination-service
    client lazily)."""
    with _LOCK:
        _STATE["dir"] = directory if directory is not None \
            else _env.telemetry_agg_dir()
        _STATE["every"] = int(every if every is not None
                              else _env.telemetry_agg_every())
        _STATE["rank"] = int(rank if rank is not None else _launcher_rank())
        _STATE["world"] = int(world if world is not None
                              else _launcher_world())
        _STATE["transport"] = str(transport) if transport is not None \
            else _env.telemetry_agg_transport()
        _STATE["kv_client"] = kv_client
        _STATE["kv_warned"] = False
        _STATE["configured"] = True
        _STATE["ticks"] = 0
        if _STATE["every"] > 0 and not _STATE["dir"] \
                and _STATE["transport"] == "file" \
                and not _STATE["warned"]:
            _STATE["warned"] = True
            import warnings

            warnings.warn(
                "MXNET_TELEMETRY_AGG_EVERY is set but "
                "MXNET_TELEMETRY_AGG_DIR is not: cross-rank telemetry "
                "aggregation stays OFF (the ranks need a shared "
                "directory to publish into)", stacklevel=2)
    return dict(_STATE)


def _launcher_rank():
    # one shared implementation (env.launcher_rank) so this module's
    # rank label and the flight recorder's dump filename always agree
    return _env.launcher_rank()


def _launcher_world():
    return _env.launcher_world()


def tick():
    """One step-boundary tick (called by ``telemetry.step_end`` and
    ``lifecycle.check_stop``).  Disabled = one dict read + int check.
    Every ``every``-th tick: publish this rank's snapshot; on rank 0
    also merge the peers'.  Host-side IO only (file or KV RPC) —
    never a device collective."""
    with _LOCK:
        if not _STATE["configured"]:
            _configure_locked_from_env()
        transport = _STATE["transport"]
        if _STATE["every"] <= 0 or \
                (transport == "file" and not _STATE["dir"]):
            return None
        _STATE["ticks"] += 1
        if _STATE["ticks"] % _STATE["every"] != 0:
            return None
        rank = _STATE["rank"]
        world = _STATE["world"]
        directory = _STATE["dir"]
    if transport == "kv":
        client = _kv_client()
        if client is None:
            # no coordination service: fall back to the directory
            # gather when one is configured, else aggregation is off
            if not directory:
                return None
        else:
            publish_kv(client, rank)
            if rank == 0:
                doc = merge_snapshots(read_kv(client, world))
                _note_merge(doc)
                return doc
            return None
    publish(directory, rank)
    if rank == 0:
        doc = merge_dir(directory)
        with _LOCK:
            _STATE["merged"] = doc
            if not _STATE["route"]:
                _STATE["route"] = True
                _telemetry.register_http_route("/agg", _http_agg)
        return doc
    return None


def _note_merge(doc):
    """Shared bookkeeping for a completed rank-0 merge (either
    transport): cache it, feed the skew histogram, mount /agg."""
    _MERGES.inc()
    _AGG_RANKS.set(len(doc["ranks"]))
    for phase, skew in doc["skew"]["phases"].items():
        _SKEW_HIST.labels(phase=phase).observe(skew)
    _ledger_skew_tick(doc)
    with _LOCK:
        _STATE["merged"] = doc
        if not _STATE["route"]:
            _STATE["route"] = True
            _telemetry.register_http_route("/agg", _http_agg)


# --------------------------------------------------------------------------
# the KV-store gather (pods without a shared filesystem)
# --------------------------------------------------------------------------
def _kv_client():
    """The live jax.distributed coordination-service client (or the
    injected test client).  Resolving it must never initialize the
    backend: only an ALREADY-initialized distributed runtime has one —
    a missing client warns once and the transport degrades."""
    with _LOCK:
        if _STATE["kv_client"] is not None:
            return _STATE["kv_client"]
        warned = _STATE["kv_warned"]
    client = None
    try:
        from jax._src import distributed as _dist

        client = getattr(_dist.global_state, "client", None)
    except Exception:
        client = None
    if client is None and not warned:
        with _LOCK:
            _STATE["kv_warned"] = True
        import warnings

        warnings.warn(
            "MXNET_TELEMETRY_AGG_TRANSPORT=kv but no jax.distributed "
            "client is live (distributed.init not called?); falling "
            "back to the file gather"
            + ("" if _STATE["dir"] else " — and no "
               "MXNET_TELEMETRY_AGG_DIR either, so aggregation "
               "stays OFF"), stacklevel=2)
    return client


def publish_kv(client, rank):
    """Publish this rank's snapshot under ``…/rank<N>`` in the KV
    store (overwrite-tolerant: the newest publish wins, like the file
    rename)."""
    snap = _telemetry.snapshot()
    snap["rank"] = int(rank)
    payload = json.dumps(snap)
    key = f"{_KV_PREFIX}{int(rank)}"
    try:
        try:
            client.key_value_set(key, payload, allow_overwrite=True)
        except TypeError:           # older client: no overwrite kwarg
            try:
                client.key_value_delete(key)
            except Exception:
                pass
            client.key_value_set(key, payload)
        return True
    except Exception:
        # a failed publish degrades the merge, never the job — the
        # transport contract shared with the file gather
        return False


def read_kv(client, world):
    """``{rank: snapshot}`` for every rank with a published value —
    a missing/torn rank is skipped (best-effort merge, exactly like
    ``read_dir``)."""
    snaps = {}
    for r in range(max(1, int(world))):
        key = f"{_KV_PREFIX}{r}"
        val = None
        try:
            val = client.key_value_try_get(key)
        except AttributeError:      # older client: blocking get only
            try:
                val = client.blocking_key_value_get(key, 50)
            except Exception:
                val = None
        except Exception:
            val = None
        if not val:
            continue
        try:
            snaps[r] = json.loads(val)
        except ValueError:
            continue
    return snaps


def _configure_locked_from_env():
    _STATE["dir"] = _env.telemetry_agg_dir()
    _STATE["every"] = _env.telemetry_agg_every()
    _STATE["rank"] = _launcher_rank()
    _STATE["world"] = _launcher_world()
    _STATE["transport"] = _env.telemetry_agg_transport()
    _STATE["configured"] = True
    if _STATE["every"] > 0 and not _STATE["dir"] \
            and _STATE["transport"] == "file" and not _STATE["warned"]:
        # the production (env-only) path must warn about the half-set
        # config exactly like explicit configure() does — silence here
        # would leave the operator discovering a 404 at /agg instead
        _STATE["warned"] = True
        import warnings

        warnings.warn(
            "MXNET_TELEMETRY_AGG_EVERY is set but "
            "MXNET_TELEMETRY_AGG_DIR is not: cross-rank telemetry "
            "aggregation stays OFF (the ranks need a shared directory "
            "to publish into)", stacklevel=2)


def publish(directory, rank):
    """Atomically write this rank's current snapshot to
    ``rank<N>.json`` (tmp + rename — a reader never sees a torn file;
    the newest publish simply wins)."""
    os.makedirs(directory, exist_ok=True)
    snap = _telemetry.snapshot()
    snap["rank"] = int(rank)
    fd, tmp = tempfile.mkstemp(dir=directory, prefix=".tmp_agg_")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(snap, f)
        os.replace(tmp, os.path.join(directory, f"rank{int(rank)}.json"))
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False
    return True


def read_dir(directory, max_age_s=600.0):
    """``{rank: snapshot}`` from every readable ``rank*.json`` in the
    directory (a torn/missing peer file is skipped — the merge is
    best-effort by design).

    Staleness filter: a rank that left the job (elastic shrink,
    restart under a new world size) stops publishing but its file
    persists; without a filter it would pin a frozen rank into every
    merge forever — and once the live ranks' timeline rings advance
    past its last step, the skew histogram would silently stop finding
    a common step.  Snapshots more than ``max_age_s`` older than the
    NEWEST snapshot in the directory are dropped (measured against the
    newest file, not the wall clock, so an offline teldump re-merge of
    an old directory is deterministic and complete).  ``max_age_s <=
    0`` disables the filter."""
    snaps = {}
    try:
        names = os.listdir(directory)
    except OSError:
        return snaps
    for name in sorted(names):
        m = _RANK_FILE.match(name)
        if not m:
            continue
        try:
            with open(os.path.join(directory, name)) as f:
                snaps[int(m.group(1))] = json.load(f)
        except (OSError, ValueError):
            continue
    if snaps and max_age_s and max_age_s > 0:
        newest = max((s.get("time") or 0) for s in snaps.values())
        snaps = {r: s for r, s in snaps.items()
                 if (s.get("time") or 0) >= newest - max_age_s}
    return snaps


def merge_dir(directory):
    """Merge every rank file in ``directory`` and feed the straggler
    histogram (``mxnet_rank_step_skew_seconds``) with the per-phase
    skew at the newest common step.  Returns the merged doc."""
    snaps = read_dir(directory)
    doc = merge_snapshots(snaps)
    _MERGES.inc()
    _AGG_RANKS.set(len(doc["ranks"]))
    for phase, skew in doc["skew"]["phases"].items():
        _SKEW_HIST.labels(phase=phase).observe(skew)
    _ledger_skew_tick(doc)
    return doc


def merged():
    """The latest merged document on the aggregating rank (None before
    the first merge / on non-zero ranks)."""
    with _LOCK:
        return _STATE["merged"]


def _http_agg(method, path, query, body):
    doc = merged()
    if doc is None:
        return (404, "application/json",
                b'{"error": "no cross-rank merge yet"}')
    return 200, "application/json", json.dumps(doc).encode()


def reset():
    """Drop configuration + cached merge (test isolation)."""
    with _LOCK:
        _STATE.update(configured=False, dir=None, every=0, rank=0,
                      world=1, ticks=0, merged=None, warned=False,
                      transport="file", kv_client=None, kv_warned=False)
        _SKEW_ALERT_STATE.update(above=0, fired=False)
        if _STATE["route"]:
            _STATE["route"] = False
            _telemetry.unregister_http_route("/agg")
