"""Cross-rank telemetry aggregation: one merged, rank-labeled view of a
multi-process job, with per-phase straggler skew.

Layer 3 of the runtime introspection plane (ISSUE 14).  Every rank has
had a complete ``telemetry.snapshot()`` since PR 3 — but each one is an
island: rank 7 being 40 ms slower in its ``data`` phase every step is
invisible until it becomes a watchdog stall.  This module merges the
per-rank snapshots into

- **rank-labeled families** — every metric family from every rank, its
  samples carrying a ``rank`` label, in one document; and
- **per-phase skew histograms** — ``mxnet_rank_step_skew_seconds``
  observes, per phase, ``max - min`` of the per-rank durations at the
  newest step every rank has reported, so a straggler is a visible
  distribution long before it wedges the mesh.

Transport contract — **never a device collective**: ranks exchange
snapshots through atomically-published JSON files in a shared directory
(``MXNET_TELEMETRY_AGG_DIR``).  The publish rides the existing uniform
step boundary (``telemetry.step_end`` and ``lifecycle.check_stop``
both tick the stride counter) purely because that is where a
consistent per-step cut exists — the IO is host-side, so a rank
publishing late or not at all degrades the merge, never the job
(MXT001/003 have nothing to taint).  Every
``MXNET_TELEMETRY_AGG_EVERY``-th tick a rank rewrites its own
``rank<N>.json``; rank 0 additionally merges whatever peer files exist
and serves the result at the ``/agg`` route beside ``/metrics``.

:func:`merge_snapshots` itself is a pure, deterministic function of its
inputs (CI asserts two merges of the same snapshots are identical), so
``tools/teldump`` can re-merge offline from the same files.
"""
from __future__ import annotations

import json
import os
import re
import tempfile
import threading
import time

from . import env as _env
from . import telemetry as _telemetry

__all__ = ["merge_snapshots", "skew_from_snapshots", "configure",
           "tick", "publish", "merge_dir", "read_dir", "merged",
           "reset"]

_SKEW_HIST = _telemetry.histogram(
    "mxnet_rank_step_skew_seconds",
    "per-phase max-min spread of step-phase durations across ranks at "
    "the newest common step (straggler visibility)",
    labelnames=("phase",),
    buckets=[1e-4, 3e-4, 1e-3, 3e-3, 1e-2, 3e-2, 0.1, 0.3, 1.0, 3.0,
             10.0])
_MERGES = _telemetry.counter(
    "mxnet_telemetry_agg_merges_total",
    "cross-rank snapshot merges performed by this process")
_AGG_RANKS = _telemetry.gauge(
    "mxnet_telemetry_agg_ranks",
    "ranks present in the latest cross-rank merge")

_LOCK = threading.Lock()
_STATE = {
    "configured": False,
    "dir": None,
    "every": 0,
    "rank": 0,
    "world": 1,
    "ticks": 0,
    "merged": None,      # latest merged doc (aggregating rank only)
    "route": False,
    "warned": False,
}

_RANK_FILE = re.compile(r"^rank(\d+)\.json$")


# --------------------------------------------------------------------------
# pure merge (deterministic: same snapshots in -> same document out)
# --------------------------------------------------------------------------
def merge_snapshots(snaps):
    """Merge ``{rank: telemetry-snapshot}`` into one document.

    Deterministic and pure: ranks are processed in sorted order, no
    clock reads feed the payload (the newest input snapshot's ``time``
    is carried through), so merging the same inputs twice yields the
    same document — the property teldump's offline re-merge and the CI
    determinism assertion rely on.

    Output shape::

        {"time", "ranks": [...], "metrics": {name: {type, help,
         samples: [{labels: {..., "rank": "0"}, ...}]}},
         "skew": {"step": N|None, "phases": {phase: max-min}},
         "per_rank": {rank: {steps, last_step, compile_count,
                             goodput_ratio}}}
    """
    snaps = {int(r): s for r, s in dict(snaps).items()}
    ranks = sorted(snaps)
    metrics: dict = {}
    per_rank: dict = {}
    for rank in ranks:
        snap = snaps[rank]
        for name, fam in sorted((snap.get("metrics") or {}).items()):
            out = metrics.setdefault(
                name, {"type": fam.get("type"),
                       "help": fam.get("help", ""), "samples": []})
            for sample in fam.get("samples", ()):
                labeled = dict(sample)
                labels = dict(labeled.get("labels") or {})
                labels["rank"] = str(rank)
                labeled["labels"] = labels
                out["samples"].append(labeled)
        steps = snap.get("steps") or []
        per_rank[rank] = {
            "steps": len(steps),
            "last_step": steps[-1]["step"] if steps else None,
            "compile_count": (snap.get("compile") or {}).get("count"),
            "goodput_ratio": (snap.get("goodput") or {}).get(
                "productive_ratio"),
        }
    step, phases = skew_from_snapshots(snaps)
    return {
        "time": max((s.get("time") or 0) for s in snaps.values())
        if snaps else 0,
        "ranks": ranks,
        "metrics": metrics,
        "skew": {"step": step, "phases": phases},
        "per_rank": per_rank,
    }


def skew_from_snapshots(snaps):
    """``(step, {phase: max-min seconds})`` at the newest step EVERY
    rank has a timeline record for (``(None, {})`` when there is no
    common step — e.g. a rank that has not completed a step yet)."""
    per_rank_steps = {}
    for rank, snap in snaps.items():
        per_rank_steps[rank] = {rec["step"]: rec
                                for rec in (snap.get("steps") or [])}
    if not per_rank_steps or any(not d for d in per_rank_steps.values()):
        return None, {}
    common = set.intersection(*(set(d) for d in per_rank_steps.values()))
    if not common:
        return None, {}
    step = max(common)
    phases: dict = {}
    names = set()
    for d in per_rank_steps.values():
        names.update(d[step]["phases"])
    for name in sorted(names):
        vals = [d[step]["phases"].get(name, 0.0)
                for d in per_rank_steps.values()]
        phases[name] = max(vals) - min(vals)
    return step, phases


# --------------------------------------------------------------------------
# the file-based gather
# --------------------------------------------------------------------------
def configure(directory=None, every=None, rank=None, world=None):
    """Configure (or reconfigure) the aggregator explicitly.  Defaults
    come from the env knobs / launcher vars; ``every=0`` disables."""
    with _LOCK:
        _STATE["dir"] = directory if directory is not None \
            else _env.telemetry_agg_dir()
        _STATE["every"] = int(every if every is not None
                              else _env.telemetry_agg_every())
        _STATE["rank"] = int(rank if rank is not None else _launcher_rank())
        _STATE["world"] = int(world if world is not None
                              else _launcher_world())
        _STATE["configured"] = True
        _STATE["ticks"] = 0
        if _STATE["every"] > 0 and not _STATE["dir"] \
                and not _STATE["warned"]:
            _STATE["warned"] = True
            import warnings

            warnings.warn(
                "MXNET_TELEMETRY_AGG_EVERY is set but "
                "MXNET_TELEMETRY_AGG_DIR is not: cross-rank telemetry "
                "aggregation stays OFF (the ranks need a shared "
                "directory to publish into)", stacklevel=2)
    return dict(_STATE)


def _launcher_rank():
    # launcher env, NOT jax.process_index(): the tick must never force
    # backend init (the PR 2 checkpoint-primary-election precedent)
    for name in ("MXNET_WORKER_ID", "DMLC_WORKER_ID"):
        v = os.environ.get(name)
        if v:
            try:
                return int(v)
            except ValueError:
                pass
    return 0


def _launcher_world():
    for name in ("MXNET_NUM_WORKERS", "DMLC_NUM_WORKER"):
        v = os.environ.get(name)
        if v:
            try:
                return max(1, int(v))
            except ValueError:
                pass
    return 1


def tick():
    """One step-boundary tick (called by ``telemetry.step_end`` and
    ``lifecycle.check_stop``).  Disabled = one dict read + int check.
    Every ``every``-th tick: publish this rank's snapshot; on rank 0
    also merge the directory.  Host-side file IO only."""
    with _LOCK:
        if not _STATE["configured"]:
            _configure_locked_from_env()
        if _STATE["every"] <= 0 or not _STATE["dir"]:
            return None
        _STATE["ticks"] += 1
        if _STATE["ticks"] % _STATE["every"] != 0:
            return None
        rank = _STATE["rank"]
        directory = _STATE["dir"]
    publish(directory, rank)
    if rank == 0:
        doc = merge_dir(directory)
        with _LOCK:
            _STATE["merged"] = doc
            if not _STATE["route"]:
                _STATE["route"] = True
                _telemetry.register_http_route("/agg", _http_agg)
        return doc
    return None


def _configure_locked_from_env():
    _STATE["dir"] = _env.telemetry_agg_dir()
    _STATE["every"] = _env.telemetry_agg_every()
    _STATE["rank"] = _launcher_rank()
    _STATE["world"] = _launcher_world()
    _STATE["configured"] = True
    if _STATE["every"] > 0 and not _STATE["dir"] and not _STATE["warned"]:
        # the production (env-only) path must warn about the half-set
        # config exactly like explicit configure() does — silence here
        # would leave the operator discovering a 404 at /agg instead
        _STATE["warned"] = True
        import warnings

        warnings.warn(
            "MXNET_TELEMETRY_AGG_EVERY is set but "
            "MXNET_TELEMETRY_AGG_DIR is not: cross-rank telemetry "
            "aggregation stays OFF (the ranks need a shared directory "
            "to publish into)", stacklevel=2)


def publish(directory, rank):
    """Atomically write this rank's current snapshot to
    ``rank<N>.json`` (tmp + rename — a reader never sees a torn file;
    the newest publish simply wins)."""
    os.makedirs(directory, exist_ok=True)
    snap = _telemetry.snapshot()
    snap["rank"] = int(rank)
    fd, tmp = tempfile.mkstemp(dir=directory, prefix=".tmp_agg_")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(snap, f)
        os.replace(tmp, os.path.join(directory, f"rank{int(rank)}.json"))
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False
    return True


def read_dir(directory, max_age_s=600.0):
    """``{rank: snapshot}`` from every readable ``rank*.json`` in the
    directory (a torn/missing peer file is skipped — the merge is
    best-effort by design).

    Staleness filter: a rank that left the job (elastic shrink,
    restart under a new world size) stops publishing but its file
    persists; without a filter it would pin a frozen rank into every
    merge forever — and once the live ranks' timeline rings advance
    past its last step, the skew histogram would silently stop finding
    a common step.  Snapshots more than ``max_age_s`` older than the
    NEWEST snapshot in the directory are dropped (measured against the
    newest file, not the wall clock, so an offline teldump re-merge of
    an old directory is deterministic and complete).  ``max_age_s <=
    0`` disables the filter."""
    snaps = {}
    try:
        names = os.listdir(directory)
    except OSError:
        return snaps
    for name in sorted(names):
        m = _RANK_FILE.match(name)
        if not m:
            continue
        try:
            with open(os.path.join(directory, name)) as f:
                snaps[int(m.group(1))] = json.load(f)
        except (OSError, ValueError):
            continue
    if snaps and max_age_s and max_age_s > 0:
        newest = max((s.get("time") or 0) for s in snaps.values())
        snaps = {r: s for r, s in snaps.items()
                 if (s.get("time") or 0) >= newest - max_age_s}
    return snaps


def merge_dir(directory):
    """Merge every rank file in ``directory`` and feed the straggler
    histogram (``mxnet_rank_step_skew_seconds``) with the per-phase
    skew at the newest common step.  Returns the merged doc."""
    snaps = read_dir(directory)
    doc = merge_snapshots(snaps)
    _MERGES.inc()
    _AGG_RANKS.set(len(doc["ranks"]))
    for phase, skew in doc["skew"]["phases"].items():
        _SKEW_HIST.labels(phase=phase).observe(skew)
    return doc


def merged():
    """The latest merged document on the aggregating rank (None before
    the first merge / on non-zero ranks)."""
    with _LOCK:
        return _STATE["merged"]


def _http_agg(method, path, query, body):
    doc = merged()
    if doc is None:
        return (404, "application/json",
                b'{"error": "no cross-rank merge yet"}')
    return 200, "application/json", json.dumps(doc).encode()


def reset():
    """Drop configuration + cached merge (test isolation)."""
    with _LOCK:
        _STATE.update(configured=False, dir=None, every=0, rank=0,
                      world=1, ticks=0, merged=None, warned=False)
        if _STATE["route"]:
            _STATE["route"] = False
            _telemetry.unregister_http_route("/agg")
