"""mx.sym.contrib — contrib operators on the symbolic frontend.

Reference: ``python/mxnet/symbol/contrib.py`` (the contrib namespace is
code-generated there from the same op registry as ``mx.nd.contrib``,
SURVEY.md §6.6).  Every registered ``_contrib_*`` op is exposed under its
short name.
"""
from __future__ import annotations

import sys as _sys

from ..ops.registry import OP_TABLE
from .symbol import _make_symbol_function


def _bind_contrib_ops():
    mod = _sys.modules[__name__]
    for name, od in OP_TABLE.items():
        if name.startswith("_contrib_"):
            short = name[len("_contrib_"):]
            if not hasattr(mod, short):
                setattr(mod, short, _make_symbol_function(od))


_bind_contrib_ops()
