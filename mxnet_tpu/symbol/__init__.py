"""mx.sym — the symbolic frontend (reference: python/mxnet/symbol/).

The op surface is code-generated from the same registry that drives
``mx.nd.*`` (one op table → both frontends, SURVEY.md §6.6)."""
from __future__ import annotations

from .symbol import (Symbol, var, Variable, Group, load, load_json, constant,
                     evaluate, populate_namespace)

populate_namespace(globals())

# sub-namespace (reference: python/mxnet/symbol/contrib.py)
from . import contrib  # noqa: E402,F401

zeros = globals().get("zeros")
ones = globals().get("ones")
