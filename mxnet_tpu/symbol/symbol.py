"""Symbol: the staged-graph frontend (``mx.sym``).

Reference: ``python/mxnet/symbol/symbol.py`` (~3k lines over the NNVM graph
IR, SURVEY.md §3.5) — graph construction, composition, ``infer_shape``,
``bind``/``simple_bind`` → Executor, JSON save/load, ``group2ctx``.

TPU-native design: a Symbol is a lightweight Python DAG over the SAME op
table that drives ``mx.nd.*`` (ops/registry.py) — there is no second kernel
surface.  Executing a symbol interprets the DAG with the pure jax op
functions inside ``jax.jit``, so XLA owns scheduling, fusion and memory
planning (replacing the reference's nnvm passes: PlanMemory, inplace-addto,
pointwise fusion).  ``infer_shape`` is ``jax.eval_shape`` over the same
interpreter — one definition of every op's shape semantics, not two.

JSON serialization mirrors the nnvm format (``nodes``/``arg_nodes``/
``heads``, reference ``nnvm/src/pass/saveload_json.cc``) so graphs survive
round-trips and ``SymbolBlock``/``Module.load_checkpoint`` interop works.
"""
from __future__ import annotations

import ast
import json
import threading

import numpy as _np

from ..base import MXNetError
from ..ops.registry import OP_TABLE, get_op

__all__ = ["Symbol", "var", "Variable", "Group", "load", "load_json"]

# ops whose outputs write back into an aux-state input during training
# (input index -> output index); reference: stateful FCompute mutating aux.
# Aux classification derives from these slots (Symbol._aux_var_ids), like
# the reference's per-op ListAuxiliaryStates — never from name suffixes.
_STATE_OPS = {"BatchNorm": ((3, 1), (4, 2))}

# parameter inputs auto-created as variables when omitted at call sites —
# mx.sym.FullyConnected(data, num_hidden=10) materializes fc0_weight/fc0_bias
# (reference: nnvm op ListInputNames + Symbol::Compose auto-var creation)
_OP_PARAM_VARS = {
    "FullyConnected": lambda a: ["weight"] + ([] if a.get("no_bias") else ["bias"]),
    "Convolution": lambda a: ["weight"] + ([] if a.get("no_bias") else ["bias"]),
    "Deconvolution": lambda a: ["weight"] + ([] if a.get("no_bias", True) else ["bias"]),
    "BatchNorm": lambda a: ["gamma", "beta", "moving_mean", "moving_var"],
    "Embedding": lambda a: ["weight"],
    "LayerNorm": lambda a: ["gamma", "beta"],
    "GroupNorm": lambda a: ["gamma", "beta"],
    "InstanceNorm": lambda a: ["gamma", "beta"],
    "RNN": lambda a: ["parameters", "state"] + (
        ["state_cell"] if str(a.get("mode", "lstm")) == "lstm" else []),
}


# fused/derived ops inheriting a base op's param-shape rules (extended by
# mxnet_tpu.subgraph for its fused nodes)
_OP_SHAPE_HINT_ALIASES = {}


def _param_shape_hints(op, attrs, data_shape):
    """Backward shape inference for auto-created parameter variables
    (reference: each op's FInferShape fills unknown input shapes; jax
    eval_shape is forward-only so the common param-bearing ops get explicit
    hints here)."""
    op = _OP_SHAPE_HINT_ALIASES.get(op, op)
    a = attrs
    if op == "FullyConnected":
        nh = int(a["num_hidden"])
        in_units = (int(_np.prod(data_shape[1:])) if a.get("flatten", True)
                    else data_shape[-1])
        return {"weight": (nh, in_units), "bias": (nh,)}
    if op in ("Convolution", "Deconvolution"):
        k = a["kernel"]
        k = (k,) if isinstance(k, int) else tuple(k)
        nf = int(a["num_filter"])
        g = int(a.get("num_group", 1))
        c = data_shape[1]
        if op == "Convolution":
            return {"weight": (nf, c // g) + k, "bias": (nf,)}
        return {"weight": (c, nf // g) + k, "bias": (nf,)}
    if op == "BatchNorm":
        c = data_shape[a.get("axis", 1)]
        return {k: (c,) for k in ("gamma", "beta", "moving_mean", "moving_var",
                                  "running_mean", "running_var")}
    if op == "Embedding":
        return {"weight": (int(a["input_dim"]), int(a["output_dim"]))}
    if op in ("LayerNorm", "GroupNorm", "InstanceNorm"):
        ax = a.get("axis", -1) if op == "LayerNorm" else 1
        c = data_shape[ax]
        return {"gamma": (c,), "beta": (c,)}
    if op == "RNN":
        from ..ops.nn import rnn_param_size

        nh = int(a["state_size"])
        nl = int(a.get("num_layers", 1))
        bi = _attr_true(a.get("bidirectional"))
        ndir = 2 if bi else 1
        t, n, c = data_shape  # TNC layout
        total = rnn_param_size(str(a.get("mode", "lstm")), c, nh, nl, bi)
        return {"parameters": (total,), "state": (nl * ndir, n, nh),
                "state_cell": (nl * ndir, n, nh)}
    return {}


# label-var shape back-inference for the legacy loss heads (reference: each
# output op's FInferShape derives the label shape from the data shape)
_LABEL_SHAPE_FROM_DATA = {
    "SoftmaxOutput": lambda ds: tuple(ds[:-1]),
    "LinearRegressionOutput": lambda ds: tuple(ds),
    "LogisticRegressionOutput": lambda ds: tuple(ds),
    "MAERegressionOutput": lambda ds: tuple(ds),
}


# arity resolution for nout='dynamic' ops when building graphs without shapes
_DYNAMIC_NOUT = {
    "split": lambda attrs, nin: int(attrs.get("num_outputs", 1)),
    "SliceChannel": lambda attrs, nin: int(attrs.get("num_outputs", 1)),
    "slice_channel": lambda attrs, nin: int(attrs.get("num_outputs", 1)),
    "topk": lambda attrs, nin: 2 if attrs.get("ret_typ") == "both" else 1,
    "amp_multicast": lambda attrs, nin: nin,
}


def _attr_true(v):
    """Symbol attrs may arrive as python bools or JSON strings."""
    return v in (True, "True", "true", "1", 1)


def _proposal_nout(attrs, nin):
    return 2 if _attr_true(attrs.get("output_score")) else 1


for _k in ("_contrib_Proposal", "Proposal", "proposal"):
    _DYNAMIC_NOUT[_k] = _proposal_nout


def _rnn_nout(attrs, nin):
    if not _attr_true(attrs.get("state_outputs")):
        return 1
    return 3 if str(attrs.get("mode", "lstm")) == "lstm" else 2


for _k in ("RNN", "rnn"):
    _DYNAMIC_NOUT[_k] = _rnn_nout


class _NameManager(threading.local):
    def __init__(self):
        self.counters = {}

    def get(self, hint):
        hint = hint.lower()
        n = self.counters.get(hint, 0)
        self.counters[hint] = n + 1
        return f"{hint}{n}"


_NAMER = _NameManager()


class _Node:
    """One graph node: an op application or a variable (op=None)."""

    __slots__ = ("op", "name", "attrs", "inputs", "nout", "value")

    def __init__(self, op, name, attrs=None, inputs=(), nout=1, value=None):
        self.op = op              # op name (str) | None for variable/constant
        self.name = name
        self.attrs = dict(attrs or {})
        self.inputs = list(inputs)  # [(Node, out_index)]
        self.nout = nout
        self.value = value        # constants only: a numpy array

    @property
    def is_var(self):
        return self.op is None and self.value is None

    @property
    def is_const(self):
        return self.op is None and self.value is not None


def _resolve_nout(opname, attrs, nin):
    od = get_op(opname)
    if od.nout == "dynamic":
        fn = _DYNAMIC_NOUT.get(opname)
        if fn is None:
            raise MXNetError(
                f"op {opname!r} has dynamic arity; cannot stage symbolically")
        return fn(attrs, nin)
    return od.nout


def _topo(heads):
    """Topological order of all nodes reachable from head (node, idx) pairs."""
    order, seen = [], set()

    def visit(node):
        if id(node) in seen:
            return
        seen.add(id(node))
        for inp, _ in node.inputs:
            visit(inp)
        order.append(node)

    for node, _ in heads:
        visit(node)
    return order


class Symbol:
    """A symbolic multi-output handle onto the staged graph."""

    __slots__ = ("_heads",)

    def __init__(self, heads):
        self._heads = list(heads)   # [(node, out_index)]

    # ------------------------------------------------------------------
    # introspection
    # ------------------------------------------------------------------
    @property
    def name(self):
        if len(self._heads) == 1:
            return self._heads[0][0].name
        return None

    def list_outputs(self):
        outs = []
        for node, idx in self._heads:
            if node.nout == 1:
                outs.append(f"{node.name}_output" if node.op else node.name)
            else:
                outs.append(f"{node.name}_output{idx}")
        return outs

    @staticmethod
    def _aux_var_ids(nodes):
        """Variables feeding an aux-state input slot of a state op
        (reference: per-op ListAuxiliaryStates — classification by graph
        position, so a parameter whose NAME merely ends in running_mean is
        never misfiled; VERDICT r3 weak #11)."""
        aux = set()
        for n in nodes:
            for in_idx, _ in _STATE_OPS.get(n.op, ()):
                if in_idx < len(n.inputs):
                    inp, _ = n.inputs[in_idx]
                    if inp.is_var:
                        aux.add(id(inp))
        return aux

    def list_arguments(self):
        nodes = _topo(self._heads)
        aux = self._aux_var_ids(nodes)
        return [n.name for n in nodes if n.is_var and id(n) not in aux]

    def list_auxiliary_states(self):
        nodes = _topo(self._heads)
        aux = self._aux_var_ids(nodes)
        return [n.name for n in nodes if n.is_var and id(n) in aux]

    def list_inputs(self):
        return [n.name for n in _topo(self._heads) if n.is_var]

    def attr(self, key):
        if len(self._heads) == 1:
            v = self._heads[0][0].attrs.get(key)
            return None if v is None else str(v)
        return None

    def attr_dict(self):
        out = {}
        for n in _topo(self._heads):
            if n.attrs:
                out[n.name] = {k: str(v) for k, v in n.attrs.items()}
        return out

    def _set_attr(self, **kwargs):
        for node, _ in self._heads:
            node.attrs.update(kwargs)

    def optimize_for(self, backend, args=None, aux=None, ctx=None, **kwargs):
        """Apply a registered subgraph backend's partitioning passes
        (reference: Symbol.optimize_for over src/operator/subgraph/).
        args/aux/ctx are accepted for signature parity; passes here run
        shape-oblivious."""
        from .. import subgraph

        return subgraph.optimize_for(self, backend, **kwargs)

    def get_internals(self):
        nodes = _topo(self._heads)
        heads = []
        for n in nodes:
            for i in range(n.nout):
                heads.append((n, i))
        return Symbol(heads)

    def get_children(self):
        kids = []
        for node, _ in self._heads:
            kids.extend(node.inputs)
        return Symbol(kids) if kids else None

    def __getitem__(self, index):
        if isinstance(index, str):
            matches = [i for i, name in enumerate(self.list_outputs())
                       if name == index or name.rsplit("_output", 1)[0] == index]
            if not matches:
                raise MXNetError(f"no output named {index!r}")
            return Symbol([self._heads[matches[0]]])
        if isinstance(index, slice):
            return Symbol(self._heads[index])
        return Symbol([self._heads[index]])

    def __len__(self):
        return len(self._heads)

    def __iter__(self):
        return (Symbol([h]) for h in self._heads)

    def __repr__(self):
        name = self.name
        return f"<Symbol {name if name else 'Grouped'}>"

    def __copy__(self):
        return self.__class__(self._heads)

    def __deepcopy__(self, memo):
        return load_json(self.tojson())

    # ------------------------------------------------------------------
    # composition (reference: Symbol.__call__ / Compose)
    # ------------------------------------------------------------------
    def __call__(self, *args, **kwargs):
        if args:
            raise MXNetError("compose only supports keyword arguments "
                             "(name=symbol)")
        subst = {}
        for k, v in kwargs.items():
            if not isinstance(v, Symbol) or len(v._heads) != 1:
                raise MXNetError("compose values must be single-output Symbols")
            subst[k] = v._heads[0]
        return Symbol([_substitute(h, subst, {}) for h in self._heads])

    # ------------------------------------------------------------------
    # shape/type inference (jax.eval_shape over the interpreter)
    # ------------------------------------------------------------------
    def infer_shape(self, *args, **kwargs):
        try:
            return self._infer_shape_impl(False, *args, **kwargs)
        except MXNetError:
            raise
        except Exception as e:
            raise MXNetError(f"infer_shape failed: {e}") from e

    def infer_shape_partial(self, *args, **kwargs):
        return self._infer_shape_impl(True, *args, **kwargs)

    def _infer_shape_impl(self, partial, *args, **kwargs):
        import jax

        arg_names = self.list_arguments()
        aux_names = self.list_auxiliary_states()
        known = {}
        if args:
            for name, shape in zip(arg_names, args):
                if shape is not None:
                    known[name] = tuple(shape)
        known.update({k: tuple(v) for k, v in kwargs.items() if v is not None})

        # propagate shapes node-by-node in topo order
        shapes = dict(known)
        nodes = _topo(self._heads)
        for n in nodes:
            if n.is_const:
                shapes[n.name] = tuple(n.value.shape)
            elif n.is_var and n.name not in shapes:
                declared = n.attrs.get("__shape__")
                if declared is not None:
                    shapes[n.name] = tuple(declared)
        progressed = True
        while progressed:
            progressed = False
            for n in nodes:
                if n.op is None:
                    continue
                key = id(n)
                if key in shapes:
                    continue
                # label shapes back-infer from the data input for the legacy
                # loss-output ops (reference: their FInferShape does this, so
                # predict-time binds need no label_shapes)
                if n.op in _LABEL_SHAPE_FROM_DATA and len(n.inputs) >= 2:
                    d0, lab = n.inputs[0][0], n.inputs[1][0]
                    ds = (shapes.get(d0.name) if d0.op is None
                          else shapes.get((id(d0), n.inputs[0][1])))
                    if ds is not None and lab.op is None \
                            and lab.name not in shapes:
                        shapes[lab.name] = _LABEL_SHAPE_FROM_DATA[n.op](ds)
                        progressed = True
                # backward-infer auto-created param-var shapes from data shape
                if n.op in _OP_PARAM_VARS and n.inputs:
                    d0 = n.inputs[0][0]
                    ds = (shapes.get(d0.name) if d0.op is None
                          else shapes.get((id(d0), n.inputs[0][1])))
                    if ds is not None:
                        hints = _param_shape_hints(n.op, _clean_attrs(n.attrs), ds)
                        for inp, _ in n.inputs[1:]:
                            if inp.op is None and inp.name not in shapes:
                                for pname, shp in hints.items():
                                    if (inp.name == pname
                                            or inp.name.endswith("_" + pname)
                                            or inp.name.endswith("." + pname)):
                                        shapes[inp.name] = shp
                                        progressed = True
                                        break
                in_shapes = []
                ok = True
                for inp, idx in n.inputs:
                    if inp.op is None:
                        s = shapes.get(inp.name)
                    else:
                        s = shapes.get((id(inp), idx))
                    if s is None:
                        ok = False
                        break
                    in_shapes.append(s)
                if not ok:
                    continue
                od = get_op(n.op)
                structs = [jax.ShapeDtypeStruct(s, _np.float32)
                           for s in in_shapes]
                if od.needs_rng:
                    structs = [jax.ShapeDtypeStruct((2,), _np.uint32)] + structs
                try:
                    out = jax.eval_shape(
                        lambda *a: od.fn(*a, **_clean_attrs(n.attrs)), *structs)
                except Exception as e:
                    if partial:
                        continue
                    raise MXNetError(
                        f"shape inference failed at node {n.name} ({n.op}): {e}"
                    ) from e
                outs = out if isinstance(out, (tuple, list)) else (out,)
                for i, o in enumerate(outs):
                    shapes[(id(n), i)] = tuple(o.shape)
                shapes[key] = True
                progressed = True

        def get_shape(n, idx=0):
            if n.op is None:
                return shapes.get(n.name)
            return shapes.get((id(n), idx))

        arg_shapes = [shapes.get(nm) for nm in arg_names]
        aux_shapes = [shapes.get(nm) for nm in aux_names]
        out_shapes = [get_shape(n, i) for n, i in self._heads]
        if not partial and any(s is None for s in arg_shapes + out_shapes):
            # back-infer variable shapes is not supported (jax is forward
            # only); the reference could back-propagate shapes — callers that
            # need it must provide all input shapes
            missing = [nm for nm, s in zip(arg_names, arg_shapes) if s is None]
            if missing:
                return None, None, None
        return arg_shapes, out_shapes, aux_shapes

    def infer_type(self, *args, **kwargs):
        # everything defaults to float32 unless a dtype attr says otherwise
        arg_types = [_np.float32] * len(self.list_arguments())
        out_types = [_np.float32] * len(self._heads)
        aux_types = [_np.float32] * len(self.list_auxiliary_states())
        return arg_types, out_types, aux_types

    # ------------------------------------------------------------------
    # serialization (nnvm JSON schema)
    # ------------------------------------------------------------------
    def tojson(self):
        nodes = _topo(self._heads)
        nid = {id(n): i for i, n in enumerate(nodes)}
        jnodes, arg_nodes = [], []
        for i, n in enumerate(nodes):
            entry = {"op": n.op if n.op else "null", "name": n.name,
                     "inputs": [[nid[id(inp)], idx, 0] for inp, idx in n.inputs]}
            attrs = {k: _attr_str(v) for k, v in n.attrs.items()}
            if n.is_const:
                attrs["__value__"] = json.dumps(n.value.tolist())
                attrs["__dtype__"] = str(n.value.dtype)
                attrs["__const__"] = "1"
            if n.op and n.nout != 1:
                attrs["__nout__"] = str(n.nout)
            if attrs:
                entry["attrs"] = attrs
            if n.op is None:
                arg_nodes.append(i)
            jnodes.append(entry)
        heads = [[nid[id(n)], idx, 0] for n, idx in self._heads]
        return json.dumps({"nodes": jnodes, "arg_nodes": arg_nodes,
                           "heads": heads,
                           "attrs": {"mxnet_version": ["int", 10600],
                                     "framework": ["str", "mxnet_tpu"]}},
                          indent=2)

    def save(self, fname):
        with open(fname, "w") as f:
            f.write(self.tojson())

    # ------------------------------------------------------------------
    # evaluation / binding
    # ------------------------------------------------------------------
    def eval(self, ctx=None, **kwargs):
        from ..ndarray import NDArray

        args = {k: v for k, v in kwargs.items()}
        ex = self.bind(ctx, args)
        return ex.forward()

    def bind(self, ctx=None, args=None, args_grad=None, grad_req="write",
             aux_states=None, group2ctx=None, shared_exec=None):
        from ..executor import Executor

        return Executor(self, ctx, args=args, args_grad=args_grad,
                        grad_req=grad_req, aux_states=aux_states)

    def simple_bind(self, ctx=None, grad_req="write", type_dict=None,
                    shared_arg_names=None, shared_exec=None,
                    shared_buffer=None, **kwargs):
        from ..executor import Executor
        from ..ndarray import zeros

        arg_shapes, _, aux_shapes = self.infer_shape(**kwargs)
        if arg_shapes is None:
            raise MXNetError("simple_bind needs enough shapes to infer all "
                             f"arguments; got {kwargs}")
        arg_names = self.list_arguments()
        aux_names = self.list_auxiliary_states()
        args = {}
        shared = shared_exec.arg_dict if shared_exec is not None else None
        for name, shape in zip(arg_names, arg_shapes):
            if shared is not None and name in shared and name not in kwargs:
                args[name] = shared[name]
            else:
                args[name] = zeros(shape, ctx=ctx)
        aux = {}
        shared_aux = shared_exec.aux_dict if shared_exec is not None else None
        for name, shape in zip(aux_names, aux_shapes):
            if shared_aux is not None and name in shared_aux:
                aux[name] = shared_aux[name]
            else:
                aux[name] = zeros(shape, ctx=ctx)
        args_grad = None
        if grad_req != "null":
            args_grad = {n: zeros(s, ctx=ctx)
                         for n, s in zip(arg_names, arg_shapes)}
        return Executor(self, ctx, args=args, args_grad=args_grad,
                        grad_req=grad_req, aux_states=aux)

    # ------------------------------------------------------------------
    # operator sugar (mirrors NDArray's)
    # ------------------------------------------------------------------
    def _binary(self, op, other, reverse=False):
        if isinstance(other, Symbol):
            a, b = (other, self) if reverse else (self, other)
            return _sym_invoke(op, [a, b], {})
        attrs = {"scalar": float(other), "reverse": reverse}
        return _sym_invoke(op + "_scalar", [self], attrs)

    def __add__(self, o):
        return self._binary("broadcast_add", o)

    def __radd__(self, o):
        return self._binary("broadcast_add", o, reverse=True)

    def __sub__(self, o):
        return self._binary("broadcast_sub", o)

    def __rsub__(self, o):
        return self._binary("broadcast_sub", o, reverse=True)

    def __mul__(self, o):
        return self._binary("broadcast_mul", o)

    def __rmul__(self, o):
        return self._binary("broadcast_mul", o, reverse=True)

    def __truediv__(self, o):
        return self._binary("broadcast_div", o)

    def __rtruediv__(self, o):
        return self._binary("broadcast_div", o, reverse=True)

    def __pow__(self, o):
        return self._binary("broadcast_power", o)

    def __neg__(self):
        return _sym_invoke("negative", [self], {})

    def __getstate__(self):
        return {"json": self.tojson()}

    def __setstate__(self, state):
        self._heads = load_json(state["json"])._heads

    def reshape(self, shape):
        return _sym_invoke("reshape", [self], {"shape": shape})

    def transpose(self, axes=None):
        return _sym_invoke("transpose", [self], {"axes": axes})


def _encode_slices(v):
    """slice objects (from _slice_key indexing nodes) are not literals —
    encode them as tagged tuples so JSON attrs round-trip."""
    if isinstance(v, slice):
        return ("__slice__", v.start, v.stop, v.step)
    if isinstance(v, tuple):
        return tuple(_encode_slices(x) for x in v)
    if isinstance(v, list):
        return [_encode_slices(x) for x in v]
    return v


def _decode_slices(v):
    if isinstance(v, tuple):
        if len(v) == 4 and v[0] == "__slice__":
            return slice(v[1], v[2], v[3])
        return tuple(_decode_slices(x) for x in v)
    if isinstance(v, list):
        return [_decode_slices(x) for x in v]
    return v


def _attr_str(v):
    return repr(_encode_slices(v)) if not isinstance(v, str) else v


def _parse_attr(s):
    try:
        return _decode_slices(ast.literal_eval(s))
    except (ValueError, SyntaxError):
        return s


def _clean_attrs(attrs):
    return {k: v for k, v in attrs.items() if not k.startswith("__")}


def _substitute(head, subst, memo):
    node, idx = head
    if node.is_var and node.name in subst:
        return subst[node.name]
    if id(node) in memo:
        return (memo[id(node)], idx)
    if node.op is None:
        memo[id(node)] = node
        return (node, idx)
    new = _Node(node.op, node.name, node.attrs,
                [_substitute(h, subst, memo) for h in node.inputs],
                nout=node.nout, value=node.value)
    memo[id(node)] = new
    return (new, idx)


# --------------------------------------------------------------------------
# construction API
# --------------------------------------------------------------------------
def var(name, attr=None, shape=None, dtype=None, init=None, stype=None,
        lr_mult=None, wd_mult=None, **kwargs):
    """Create a symbolic variable (reference: mx.sym.Variable)."""
    attrs = dict(attr or {})
    if shape is not None:
        attrs["__shape__"] = tuple(shape)
    if dtype is not None:
        attrs["__dtype__"] = str(dtype)
    if init is not None:
        # per-variable initializer override; honored by Initializer.__call__
        # via InitDesc.attrs (reference: sym.var(init=...) semantics)
        attrs["__init__"] = init if isinstance(init, str) else init.dumps()
    attrs.update(kwargs)
    return Symbol([(_Node(None, name, attrs), 0)])


Variable = var


def constant(value, name=None):
    value = _np.asarray(value)
    name = name or _NAMER.get("_const")
    return Symbol([(_Node(None, name, {}, value=value), 0)])


def Group(symbols):
    heads = []
    for s in symbols:
        heads.extend(s._heads)
    return Symbol(heads)


def load(fname):
    with open(fname) as f:
        return load_json(f.read())


def load_json(json_str):
    data = json.loads(json_str)
    nodes = []
    for entry in data["nodes"]:
        attrs = {k: _parse_attr(v) for k, v in entry.get("attrs", {}).items()}
        op = entry["op"]
        if op == "null":
            if attrs.pop("__const__", None):
                value = _np.asarray(json.loads(attrs.pop("__value__")),
                                    dtype=attrs.pop("__dtype__", "float32"))
                nodes.append(_Node(None, entry["name"], attrs, value=value))
            else:
                nodes.append(_Node(None, entry["name"], attrs))
        else:
            inputs = [(nodes[nid], idx) for nid, idx, _ in entry["inputs"]]
            nout = int(attrs.pop("__nout__", 0)) or _resolve_nout(
                op, attrs, len(inputs))
            nodes.append(_Node(op, entry["name"], attrs, inputs, nout=nout))
    heads = [(nodes[nid], idx) for nid, idx, _ in data["heads"]]
    return Symbol(heads)


# --------------------------------------------------------------------------
# symbolic invoke — builds a graph node (the staged twin of ndarray.invoke)
# --------------------------------------------------------------------------
def _sym_invoke(opname, inputs, attrs, name=None):
    od = get_op(opname)
    attrs = {k: v for k, v in attrs.items()
             if v is not None or k in ("axis", "a_min", "a_max")}
    in_heads = []
    for a in inputs:
        if a is None:
            continue
        if isinstance(a, Symbol):
            if len(a._heads) != 1:
                raise MXNetError(
                    f"op {opname}: grouped symbol cannot be an input")
            in_heads.append(a._heads[0])
        else:
            in_heads.append(constant(a)._heads[0])
    name = name or _NAMER.get(od.name)
    # auto-create parameter variables for the param-bearing layer ops
    pv = _OP_PARAM_VARS.get(od.name)
    if pv is not None:
        wanted = pv(attrs)
        have = len(in_heads) - 1  # first input is data
        for pname in wanted[max(have, 0):]:
            in_heads.append((_Node(None, f"{name}_{pname}", {}), 0))
    nout = _resolve_nout(od.name, attrs, len(in_heads))
    node = _Node(od.name, name, attrs, in_heads, nout=nout)
    if nout == 1 or od.name in _STATE_OPS:
        # state ops (BatchNorm) expose only the primary output as the
        # chainable head — the extra outputs are running-stat updates the
        # interpreter writes back into aux states (reference: symbolic
        # BatchNorm is single-output; moving stats are aux mutations)
        return Symbol([(node, 0)])
    return Symbol([(node, i) for i in range(nout)])


# --------------------------------------------------------------------------
# interpreter — evaluate head values given a feed dict of input values
# --------------------------------------------------------------------------
def evaluate(heads, feed, rng_key=None, training=False, collect_state=False):
    """Evaluate graph heads with the registered pure jax op functions.

    feed: dict name -> jax array for every variable (args + aux).
    Returns (outputs, state_updates) where state_updates maps an aux var name
    to its new value (BatchNorm moving stats under training).
    """
    import jax

    vals = {}            # (id(node), idx) -> jax value
    state_updates = {}
    nodes = _topo(heads)
    key_iter = [rng_key]

    def next_key():
        if key_iter[0] is None:
            # inference path with training-only random ops (Dropout in eval
            # mode consumes a key but ignores it) — a fixed key is sound
            key_iter[0] = jax.random.PRNGKey(0)
        key_iter[0], sub = jax.random.split(key_iter[0])
        return sub

    for n in nodes:
        if n.op is None:
            if n.is_const:
                vals[(id(n), 0)] = n.value
            else:
                if n.name not in feed:
                    raise MXNetError(f"unbound variable {n.name!r}")
                vals[(id(n), 0)] = feed[n.name]
            continue
        od = get_op(n.op)
        in_vals = [vals[(id(inp), idx)] for inp, idx in n.inputs]
        attrs = _clean_attrs(n.attrs)
        if training and n.op in ("BatchNorm", "Dropout", "RNN"):
            attrs["training"] = True
        if od.needs_rng:
            in_vals = [next_key()] + in_vals
        from ..ndarray.ndarray import _AMP

        if _AMP["on"]:
            # same mixed-precision cast policy as the imperative invoke path
            # (contrib.amp): without this, SymbolBlock/Executor graphs would
            # silently run full-precision under amp.init()/TrainStep(dtype=…)
            fn = _AMP["wrap"](od, lambda *a, _f=od.fn, _at=attrs: _f(*a, **_at))
            out = fn(*in_vals)
        else:
            out = od.fn(*in_vals, **attrs)
        outs = out if isinstance(out, (tuple, list)) else (out,)
        for i, v in enumerate(outs):
            vals[(id(n), i)] = v
        if collect_state and training and n.op in _STATE_OPS:
            for in_idx, out_idx in _STATE_OPS[n.op]:
                if in_idx < len(n.inputs):
                    aux_node = n.inputs[in_idx][0]
                    if aux_node.op is None:
                        state_updates[aux_node.name] = outs[out_idx]
    outputs = [vals[(id(n), i)] for n, i in heads]
    return outputs, state_updates


# --------------------------------------------------------------------------
# symbolic tracing of imperative code (the HybridBlock.export seam)
# --------------------------------------------------------------------------
class SymbolTracer:
    """An NDArray-shaped proxy carrying a graph head + concrete aval.

    Reference: hybridize's first-call trace passes Symbol proxies into
    hybrid_forward (SURVEY.md §4.6).  Here imperative ``forward`` code runs
    unmodified: ndarray.invoke diverts to graph building when it sees these."""

    __slots__ = ("_symhead", "_aval", "context")

    def __init__(self, head, aval, ctx=None):
        self._symhead = head            # (node, idx)
        self._aval = aval               # jax.ShapeDtypeStruct
        self.context = ctx

    @property
    def shape(self):
        return tuple(self._aval.shape)

    @property
    def dtype(self):
        return self._aval.dtype

    @property
    def ndim(self):
        return len(self._aval.shape)

    @property
    def size(self):
        n = 1
        for s in self._aval.shape:
            n *= s
        return n

    def _get(self):
        raise MXNetError(
            "cannot read a value during symbolic export tracing — "
            "remove asnumpy()/asscalar()/item() calls from forward()")

    def asnumpy(self):
        self._get()

    # arithmetic mirrors NDArray's operator sugar, through trace_invoke
    def _binary(self, op, other, reverse=False):
        from ..ndarray.ndarray import NDArray

        if isinstance(other, (SymbolTracer, NDArray)):
            args = [other, self] if reverse else [self, other]
            return trace_invoke(op, args, {})
        return trace_invoke(op + "_scalar", [self],
                            {"scalar": float(other), "reverse": reverse})

    def __add__(self, o):
        return self._binary("broadcast_add", o)

    def __radd__(self, o):
        return self._binary("broadcast_add", o, reverse=True)

    def __sub__(self, o):
        return self._binary("broadcast_sub", o)

    def __rsub__(self, o):
        return self._binary("broadcast_sub", o, reverse=True)

    def __mul__(self, o):
        return self._binary("broadcast_mul", o)

    def __rmul__(self, o):
        return self._binary("broadcast_mul", o, reverse=True)

    def __truediv__(self, o):
        return self._binary("broadcast_div", o)

    def __rtruediv__(self, o):
        return self._binary("broadcast_div", o, reverse=True)

    def __pow__(self, o):
        return self._binary("broadcast_power", o)

    def __neg__(self):
        return trace_invoke("negative", [self], {})

    def __getitem__(self, key):
        return trace_invoke("_slice_key", [self], {"key": key})

    def reshape(self, *shape, **kwargs):
        if len(shape) == 1 and isinstance(shape[0], (list, tuple)):
            shape = tuple(shape[0])
        shape = kwargs.get("shape", shape)
        return trace_invoke("reshape", [self], {"shape": tuple(shape)})

    def transpose(self, axes=None):
        return trace_invoke("transpose", [self], {"axes": axes})

    def astype(self, dtype, copy=True):
        return trace_invoke("Cast", [self], {"dtype": str(_np.dtype(dtype))})

    def flatten(self):
        return trace_invoke("flatten", [self], {})

    def expand_dims(self, axis):
        return trace_invoke("expand_dims", [self], {"axis": axis})

    def squeeze(self, axis=None):
        return trace_invoke("squeeze", [self], {"axis": axis})

    def sum(self, axis=None, keepdims=False):
        return trace_invoke("sum", [self], {"axis": axis, "keepdims": keepdims})

    def mean(self, axis=None, keepdims=False):
        return trace_invoke("mean", [self], {"axis": axis, "keepdims": keepdims})

    def __repr__(self):
        return f"<SymbolTracer {self.shape} {self._aval.dtype}>"


def _tracer_for(node, idx, in_avals_or_shape):
    return SymbolTracer((node, idx), in_avals_or_shape)


# trace observer: while a graph-tier trace is active (mxnet_tpu.graph.trace)
# the callback sees every op node IN CREATION ORDER — the graph IR keeps
# that order so its replay draws RNG keys and writes state updates in the
# exact sequence the imperative jit path would (bit-parity contract)
_TRACE_OBSERVER = [None]


def trace_invoke(opname, args, attrs):
    """Build a graph node from NDArray/SymbolTracer inputs during export
    tracing, propagating concrete avals via jax.eval_shape."""
    import jax

    from ..ndarray.ndarray import NDArray

    od = get_op(opname)
    attrs = {k: v for k, v in attrs.items()
             if v is not None or k in ("axis", "a_min", "a_max")}
    in_heads, in_avals = [], []
    for a in args:
        if a is None:
            continue
        if isinstance(a, SymbolTracer):
            in_heads.append(a._symhead)
            in_avals.append(a._aval)
        elif isinstance(a, NDArray):
            v = _np.asarray(a.asnumpy())
            node = _Node(None, _NAMER.get("_const"), {}, value=v)
            in_heads.append((node, 0))
            in_avals.append(jax.ShapeDtypeStruct(v.shape, v.dtype))
        else:
            v = _np.asarray(a)
            node = _Node(None, _NAMER.get("_const"), {}, value=v)
            in_heads.append((node, 0))
            in_avals.append(jax.ShapeDtypeStruct(v.shape, v.dtype))
    name = _NAMER.get(od.name)
    structs = list(in_avals)
    if od.needs_rng:
        structs = [jax.random.PRNGKey(0)] + structs
    out_aval = jax.eval_shape(lambda *xs: od.fn(*xs, **attrs), *structs)
    multi = isinstance(out_aval, (tuple, list))
    nout = len(out_aval) if multi else 1
    node = _Node(od.name, name, attrs, in_heads, nout=nout)
    obs = _TRACE_OBSERVER[0]
    if obs is not None:
        obs(node, out_aval if multi else (out_aval,))
    if not multi:
        return SymbolTracer((node, 0), out_aval)
    return [SymbolTracer((node, i), av) for i, av in enumerate(out_aval)]


def _input_slot_names(od):
    """Ordered array-input names for keyword binding: 'data' aliases the
    first slot; param-bearing ops use their canonical param names."""
    import inspect

    sig = [p for p in inspect.signature(od.fn).parameters.values()
           if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD,
                         p.VAR_POSITIONAL)]
    names = [p.name for p in sig]
    if od.needs_rng and names:
        names = names[1:]
    return names


def _make_symbol_function(od):
    def fn(*args, **kwargs):
        name = kwargs.pop("name", None)
        sym_kw = {k: v for k, v in kwargs.items() if isinstance(v, Symbol)}
        attrs = {k: v for k, v in kwargs.items() if not isinstance(v, Symbol)}
        sym_inputs = list(args)
        if sym_kw:
            # bind keyword symbol inputs by SLOT NAME, never by keyword
            # appearance order (reference: nnvm input-name composition)
            pv = _OP_PARAM_VARS.get(od.name)
            order = ["data"] + pv(attrs) if pv is not None else None
            if order is None:
                order = _input_slot_names(od)
                if order:
                    order = ["data"] + order[1:]  # first slot answers 'data'
            unresolved = [k for k in sym_kw if k not in order]
            if unresolved and len(sym_kw) == 1:
                sym_inputs.extend(sym_kw.values())
            elif unresolved:
                raise MXNetError(
                    f"op {od.name}: cannot map keyword inputs {unresolved} "
                    f"to input slots {order}; pass them positionally")
            else:
                for k in order:
                    if k in sym_kw:
                        sym_inputs.append(sym_kw[k])
        return _sym_invoke(od.name, sym_inputs, attrs, name=name)

    fn.__name__ = od.name
    fn.__doc__ = (od.fn.__doc__ or "") + "\n\n(symbolic form)"
    return fn


def populate_namespace(ns):
    """Code-gen the mx.sym.* op surface from the shared op table."""
    seen = set()
    for name, od in OP_TABLE.items():
        if id(od) in seen and name in ns:
            continue
        seen.add(id(od))
        ns[name] = _make_symbol_function(od)
        for alias in od.aliases:
            ns.setdefault(alias, ns[name])
    return ns
