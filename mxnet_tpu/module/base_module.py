"""BaseModule: the abstract training-loop surface (reference:
``python/mxnet/module/base_module.py`` — ``fit``/``score``/``predict`` over
bind/init_params/forward/backward/update).
"""
from __future__ import annotations

import logging

from ..base import MXNetError
from .. import metric as _metric
from .. import io as _io

__all__ = ["BaseModule"]


def _as_metric(m):
    if isinstance(m, _metric.EvalMetric):
        return m
    return _metric.create(m)


class BaseModule:
    """Abstract module. Subclasses implement bind/init_params/init_optimizer/
    forward/backward/update/get_outputs/update_metric."""

    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.for_training = False
        self.params_initialized = False
        self.optimizer_initialized = False

    # -- high-level train/eval loops (reference: BaseModule.fit:~150) ------
    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None,
            eval_end_callback=None, eval_batch_end_callback=None,
            kvstore="local", optimizer="sgd",
            optimizer_params=(("learning_rate", 0.01),),
            initializer=None, arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None):
        if num_epoch is None:
            raise MXNetError("num_epoch is required for fit")
        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True, force_rebind=force_rebind)
        if monitor is not None:
            self.install_monitor(monitor)
        self.init_params(initializer=initializer, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params,
                            force_init=force_init)

        eval_metric = _as_metric(eval_metric)
        validation_metric = (_as_metric(validation_metric)
                             if validation_metric is not None else eval_metric)

        for epoch in range(begin_epoch, num_epoch):
            eval_metric.reset()
            nbatch = 0
            train_data.reset()
            for data_batch in train_data:
                if monitor is not None:
                    monitor.tic()
                self.forward_backward(data_batch)
                self.update()
                self.update_metric(eval_metric, data_batch.label)
                if monitor is not None:
                    monitor.toc_print()
                if batch_end_callback is not None:
                    bec = _as_list(batch_end_callback)
                    params = _BatchEndParam(epoch=epoch, nbatch=nbatch,
                                            eval_metric=eval_metric, locals=locals())
                    for cb in bec:
                        cb(params)
                nbatch += 1
            for name, val in eval_metric.get_name_value():
                self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
            if epoch_end_callback is not None:
                arg_params, aux_params = self.get_params()
                for cb in _as_list(epoch_end_callback):
                    cb(epoch, self.symbol, arg_params, aux_params)
            if eval_data is not None:
                res = self.score(eval_data, validation_metric,
                                 score_end_callback=eval_end_callback,
                                 batch_end_callback=eval_batch_end_callback,
                                 epoch=epoch)
                for name, val in res:
                    self.logger.info("Epoch[%d] Validation-%s=%f",
                                     epoch, name, val)

    def score(self, eval_data, eval_metric, num_batch=None,
              batch_end_callback=None, score_end_callback=None, reset=True,
              epoch=0):
        if reset:
            eval_data.reset()
        eval_metric = _as_metric(eval_metric)
        eval_metric.reset()
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            self.update_metric(eval_metric, eval_batch.label)
            if batch_end_callback is not None:
                for cb in _as_list(batch_end_callback):
                    cb(_BatchEndParam(epoch=epoch, nbatch=nbatch,
                                      eval_metric=eval_metric, locals=locals()))
        if score_end_callback is not None:
            for cb in _as_list(score_end_callback):
                cb(_BatchEndParam(epoch=epoch, nbatch=0,
                                  eval_metric=eval_metric, locals=locals()))
        return eval_metric.get_name_value()

    def predict(self, eval_data, num_batch=None, merge_batches=True,
                reset=True, always_output_list=False):
        from ..ndarray import concatenate

        if reset:
            eval_data.reset()
        output_list = []
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            pad = getattr(eval_batch, "pad", 0) or 0
            outs = [o[0:o.shape[0] - pad] for o in self.get_outputs()]
            output_list.append(outs)
        if not output_list:
            return []
        if merge_batches:
            num_outputs = len(output_list[0])
            merged = [concatenate([o[i] for o in output_list])
                      for i in range(num_outputs)]
            if num_outputs == 1 and not always_output_list:
                return merged[0]
            return merged
        return output_list

    def forward_backward(self, data_batch):
        self.forward(data_batch, is_train=True)
        self.backward()

    def iter_predict(self, eval_data, num_batch=None, reset=True):
        if reset:
            eval_data.reset()
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            yield self.get_outputs(), nbatch, eval_batch

    def install_monitor(self, mon):
        pass

    # -- abstract ----------------------------------------------------------
    def bind(self, *a, **kw):
        raise NotImplementedError

    def init_params(self, *a, **kw):
        raise NotImplementedError

    def init_optimizer(self, *a, **kw):
        raise NotImplementedError

    def forward(self, data_batch, is_train=None):
        raise NotImplementedError

    def backward(self, out_grads=None):
        raise NotImplementedError

    def update(self):
        raise NotImplementedError

    def get_outputs(self, merge_multi_context=True):
        raise NotImplementedError

    def get_params(self):
        raise NotImplementedError

    def update_metric(self, eval_metric, labels):
        raise NotImplementedError


class _BatchEndParam:
    __slots__ = ("epoch", "nbatch", "eval_metric", "locals")

    def __init__(self, epoch, nbatch, eval_metric, locals):
        self.epoch = epoch
        self.nbatch = nbatch
        self.eval_metric = eval_metric
        self.locals = locals


def _as_list(x):
    if isinstance(x, (list, tuple)):
        return x
    return [x]
