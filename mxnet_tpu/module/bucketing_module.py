"""BucketingModule: per-bucket executors sharing parameters (reference:
``python/mxnet/module/bucketing_module.py``).

This is the reference's variable-length-sequence answer AND the TPU build's
dynamic-shape discipline (SURVEY.md §6.7): each bucket key (typically a
padded sequence length) gets its own jit-compiled executor, parameters are
shared across buckets, and inputs are padded to the bucket — so XLA sees
only a fixed, small set of shapes (≙ pad-to-bucket to avoid recompilation).
"""
from __future__ import annotations

import logging

from ..base import MXNetError
from .base_module import BaseModule
from .module import Module

__all__ = ["BucketingModule"]


class BucketingModule(BaseModule):
    def __init__(self, sym_gen, default_bucket_key=None, logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None, group2ctxs=None, compression_params=None):
        super().__init__(logger=logger)
        if default_bucket_key is None:
            raise MXNetError("default_bucket_key is required")
        self._sym_gen = sym_gen
        self._default_bucket_key = default_bucket_key
        self._context = context
        self._fixed_param_names = fixed_param_names
        self._buckets = {}
        self._curr_module = None
        self._curr_bucket_key = None
        self._init_args = None      # saved (initializer, arg, aux) for lazy buckets
        self._opt_args = None

    @property
    def default_bucket_key(self):
        return self._default_bucket_key

    @property
    def symbol(self):
        return self._curr_module.symbol

    def _gen_module(self, bucket_key):
        sym, data_names, label_names = self._sym_gen(bucket_key)
        return Module(sym, data_names=data_names, label_names=label_names,
                      logger=self.logger, context=self._context,
                      fixed_param_names=self._fixed_param_names)

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            return
        self.for_training = for_training
        self._bind_args = dict(inputs_need_grad=inputs_need_grad,
                               grad_req=grad_req)
        mod = self._gen_module(self._default_bucket_key)
        mod.bind(data_shapes, label_shapes, for_training=for_training,
                 inputs_need_grad=inputs_need_grad, grad_req=grad_req)
        self._buckets[self._default_bucket_key] = mod
        self._curr_module = mod
        self._curr_bucket_key = self._default_bucket_key
        self.binded = True

    def switch_bucket(self, bucket_key, data_shapes, label_shapes=None):
        if not self.binded:
            raise MXNetError("call bind before switch_bucket")
        if bucket_key not in self._buckets:
            default_mod = self._buckets[self._default_bucket_key]
            mod = self._gen_module(bucket_key)
            mod.bind(data_shapes, label_shapes, for_training=self.for_training,
                     shared_module=default_mod, **self._bind_args)
            # simple_bind's shared_exec aliases the parameter NDArray handles
            # with the default bucket, so values (and later updates) are
            # already shared — no copying needed
            self._buckets[bucket_key] = mod
            if self.params_initialized:
                mod.params_initialized = True
            if self.optimizer_initialized:
                # share the default bucket's optimizer/updater directly —
                # state must follow the shared params
                base = self._buckets[self._default_bucket_key]
                mod._optimizer = base._optimizer
                mod._updater = base._updater
                mod.optimizer_initialized = True
        self._curr_module = self._buckets[bucket_key]
        self._curr_bucket_key = bucket_key

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        if self.params_initialized and not force_init:
            return
        self._curr_module.init_params(initializer=initializer,
                                      arg_params=arg_params,
                                      aux_params=aux_params,
                                      allow_missing=allow_missing,
                                      force_init=force_init)
        self.params_initialized = True

    def get_params(self):
        return self._curr_module.get_params()

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        for mod in self._buckets.values():
            mod.set_params(arg_params, aux_params,
                           allow_missing=allow_missing,
                           allow_extra=allow_extra)
        self.params_initialized = True

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        self._opt_args = dict(kvstore=kvstore, optimizer=optimizer,
                              optimizer_params=optimizer_params)
        base = self._buckets[self._default_bucket_key]
        base.init_optimizer(**self._opt_args)
        # single shared optimizer/updater so state follows the shared params
        for mod in self._buckets.values():
            mod._updater = base._updater
            mod._optimizer = base._optimizer
            mod.optimizer_initialized = True
        self.optimizer_initialized = True

    def forward(self, data_batch, is_train=None):
        bucket_key = getattr(data_batch, "bucket_key", self._default_bucket_key)
        data_shapes = [(f"{name}", tuple(arr.shape)) for name, arr in
                       zip(self._curr_module.data_names, data_batch.data)]
        provide = getattr(data_batch, "provide_data", None) or data_shapes
        label_shapes = getattr(data_batch, "provide_label", None)
        self.switch_bucket(bucket_key, provide, label_shapes)
        self._curr_module.forward(data_batch, is_train=is_train)

    def backward(self, out_grads=None):
        self._curr_module.backward(out_grads=out_grads)

    def update(self):
        # parameter handles are aliased across buckets (shared_exec), so
        # updating through the current bucket updates them all
        self._curr_module.update()

    def get_outputs(self, merge_multi_context=True):
        return self._curr_module.get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        return self._curr_module.get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        self._curr_module.update_metric(eval_metric, labels)
