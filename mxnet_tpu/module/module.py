"""Module: symbolic training over a bound Executor (reference:
``python/mxnet/module/module.py`` + ``executor_group.py``).

The reference's Module slices each batch across a context list
(DataParallelExecutorGroup) and aggregates gradients via KVStore.  On TPU a
single jit'd executor already spans the device mesh through sharding (the
SPMD path in ``parallel/``), so Module binds ONE executor; multi-chip data
parallelism comes from binding with a sharded context (or using the Gluon
Trainer/TrainStep path, SURVEY.md §8 phase 6) rather than N per-device
executors glued together on the host.
"""
from __future__ import annotations

import logging

import numpy as _np

from ..base import MXNetError
from .base_module import BaseModule

__all__ = ["Module", "save_checkpoint", "load_checkpoint"]


class Module(BaseModule):
    def __init__(self, symbol, data_names=("data",), label_names=("softmax_label",),
                 logger=logging, context=None, work_load_list=None,
                 fixed_param_names=None, state_names=None, group2ctxs=None,
                 compression_params=None):
        super().__init__(logger=logger)
        from ..context import current_context

        self._symbol = symbol
        self._data_names = list(data_names)
        self._label_names = list(label_names or [])
        self._context = context or current_context()
        if isinstance(self._context, (list, tuple)):
            self._context = self._context[0]
        self._fixed_param_names = list(fixed_param_names or [])
        arg_names = symbol.list_arguments()
        self._param_names = [n for n in arg_names
                             if n not in self._data_names
                             and n not in self._label_names]
        self._aux_names = symbol.list_auxiliary_states()
        self._exec = None
        self._optimizer = None
        self._updater = None
        self._kvstore = None
        self._preloaded_params = None

    # -- properties --------------------------------------------------------
    @property
    def symbol(self):
        return self._symbol

    @property
    def data_names(self):
        return self._data_names

    @property
    def label_names(self):
        return self._label_names

    @property
    def output_names(self):
        return self._symbol.list_outputs()

    @property
    def data_shapes(self):
        return self._data_shapes

    @property
    def label_shapes(self):
        return self._label_shapes

    @property
    def output_shapes(self):
        return [(n, o.shape) for n, o in
                zip(self.output_names, self._exec.outputs)]

    # -- bind --------------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            return
        self.for_training = for_training
        self._data_shapes = [_as_desc(d) for d in data_shapes]
        self._label_shapes = ([_as_desc(l) for l in label_shapes]
                              if label_shapes else [])
        shapes = {name: shape for name, shape in
                  self._data_shapes + self._label_shapes}
        req = {}
        for n in self._symbol.list_arguments():
            if n in self._data_names:
                req[n] = "write" if inputs_need_grad else "null"
            elif n in self._label_names or n in self._fixed_param_names:
                req[n] = "null"
            else:
                req[n] = grad_req if for_training else "null"
        from .. import subgraph as _subgraph

        # MXNET_SUBGRAPH_BACKEND partitions at bind time (reference:
        # executor attach-time subgraph rewrite).  Only the executor sees
        # the fused graph: module.symbol / save_checkpoint keep the user's
        # original Symbol (the reference never mutates it either)
        bind_symbol = _subgraph.apply_env_backend(self._symbol)
        self._bind_symbol = bind_symbol
        shared_exec = shared_module._exec if shared_module is not None else None
        self._exec = bind_symbol.simple_bind(
            self._context, grad_req=req, shared_exec=shared_exec, **shapes)
        self.binded = True
        if shared_module is not None and shared_module.params_initialized:
            self.params_initialized = True
        if self._preloaded_params is not None:
            arg, aux = self._preloaded_params
            self.set_params(arg, aux, allow_missing=False)
            self._preloaded_params = None

    # -- params ------------------------------------------------------------
    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        if self.params_initialized and not force_init:
            return
        if not self.binded:
            raise MXNetError("call bind before init_params")
        from .. import initializer as _init
        from ..ndarray import NDArray

        initializer = initializer or _init.Uniform(0.01)
        var_attrs = self._symbol.attr_dict()
        for name in self._param_names:
            arr = self._exec.arg_dict[name]
            if arg_params is not None and name in arg_params:
                src = arg_params[name]
                arr._set(src._get().astype(arr._get().dtype)
                         if isinstance(src, NDArray)
                         else _np.asarray(src, dtype="float32"))
            elif arg_params is not None and not allow_missing:
                raise MXNetError(f"parameter {name!r} missing from arg_params "
                                 "(pass allow_missing=True to initialize it)")
            else:
                initializer(_init.InitDesc(name, var_attrs.get(name)), arr)
        for name in self._aux_names:
            arr = self._exec.aux_dict[name]
            if aux_params is not None and name in aux_params:
                src = aux_params[name]
                arr._set(src._get().astype(arr._get().dtype))
            else:
                initializer(_init.InitDesc(name), arr)
        self.params_initialized = True

    def get_params(self):
        if not self.binded:
            raise MXNetError("module not bound")
        arg = {n: self._exec.arg_dict[n].copy() for n in self._param_names}
        aux = {n: self._exec.aux_dict[n].copy() for n in self._aux_names}
        return arg, aux

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        if not self.binded:
            self._preloaded_params = (arg_params, aux_params)
            self.params_initialized = True
            return
        self._exec.copy_params_from(arg_params, aux_params,
                                    allow_extra_params=allow_extra)
        self.params_initialized = True

    # -- optimizer ---------------------------------------------------------
    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        if self.optimizer_initialized and not force_init:
            return
        from .. import optimizer as _opt

        if isinstance(optimizer, str):
            optimizer = _opt.create(optimizer, **dict(optimizer_params))
        self._optimizer = optimizer
        self._updater = _opt.get_updater(optimizer)
        self.optimizer_initialized = True
        pending = getattr(self, "_pending_opt_states", None)
        if pending is not None:
            self.load_optimizer_states(pending)
            self._pending_opt_states = None

    # -- forward/backward/update -------------------------------------------
    def forward(self, data_batch, is_train=None):
        if is_train is None:
            is_train = self.for_training
        feeds = {}
        for name, arr in zip(self._data_names, data_batch.data):
            feeds[name] = arr
        if self._label_names and data_batch.label:
            for name, arr in zip(self._label_names, data_batch.label):
                feeds[name] = arr
        self._exec.forward(is_train=is_train, **feeds)

    def backward(self, out_grads=None):
        self._exec.backward(out_grads=out_grads)

    def update(self):
        if not self.optimizer_initialized:
            raise MXNetError("call init_optimizer before update")
        for i, name in enumerate(self._param_names):
            grad = self._exec.grad_dict.get(name)
            if grad is None:
                continue
            self._updater(i, grad, self._exec.arg_dict[name])

    def get_outputs(self, merge_multi_context=True):
        return self._exec.outputs

    def get_input_grads(self, merge_multi_context=True):
        return [self._exec.grad_dict[n] for n in self._data_names]

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        eval_metric.update(labels, self.get_outputs())

    # -- checkpointing (reference: Module.save_checkpoint) ----------------
    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        arg, aux = self.get_params()
        save_checkpoint(prefix, epoch, self._symbol, arg, aux)
        if save_optimizer_states and self._updater is not None:
            # Updater.get_states() already returns pickled bytes
            with open(f"{prefix}-{epoch:04d}.states", "wb") as f:
                f.write(self._updater.get_states())

    def load_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("call init_optimizer before load_optimizer_states")
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())

    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        sym, arg, aux = load_checkpoint(prefix, epoch)
        mod = Module(sym, **kwargs)
        mod._preloaded_params = (arg, aux)
        mod.params_initialized = True
        if load_optimizer_states:
            mod._pending_opt_states = f"{prefix}-{epoch:04d}.states"
        return mod


def _as_desc(d):
    # accepts DataDesc, (name, shape)
    if hasattr(d, "name"):
        return (d.name, tuple(d.shape))
    name, shape = d
    return (name, tuple(shape))


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params):
    """Reference: mx.model.save_checkpoint — symbol json + .params file."""
    from ..ndarray import serialization

    symbol.save(f"{prefix}-symbol.json")
    data = {f"arg:{k}": v for k, v in arg_params.items()}
    data.update({f"aux:{k}": v for k, v in aux_params.items()})
    serialization.save(f"{prefix}-{epoch:04d}.params", data)


def load_checkpoint(prefix, epoch):
    from .. import symbol as _sym
    from ..ndarray import serialization

    sym = _sym.load(f"{prefix}-symbol.json")
    loaded = serialization.load(f"{prefix}-{epoch:04d}.params")
    arg_params, aux_params = {}, {}
    for k, v in loaded.items():
        if k.startswith("arg:"):
            arg_params[k[4:]] = v
        elif k.startswith("aux:"):
            aux_params[k[4:]] = v
        else:
            arg_params[k] = v
    return sym, arg_params, aux_params
