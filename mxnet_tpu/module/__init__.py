"""mx.mod — the legacy symbolic training API (reference:
python/mxnet/module/)."""
from .base_module import BaseModule
from .module import Module, save_checkpoint, load_checkpoint
from .bucketing_module import BucketingModule

__all__ = ["BaseModule", "Module", "BucketingModule", "save_checkpoint",
           "load_checkpoint"]
