"""GPipe-style pipeline parallelism over the ``pp`` mesh axis.

Capability upgrade over the reference (MXNet 1.x has no pipeline
parallelism; its answer to big models was parameter servers).  TPU-native
shape, per the scaling-book recipe: each pp device holds ONE stage's
parameters (weight-stationary); microbatches stream through the pipeline
with ``lax.ppermute`` passing activations over ICI between ticks.  With M
microbatches and S stages the loop runs M+S-1 ticks and every device is
busy in the steady state (bubble fraction (S-1)/(M+S-1)).

The whole schedule is one jit-able, differentiable function —
``jax.grad`` through it gives 1F1B-equivalent memory behavior when
combined with per-stage ``jax.checkpoint``.

Usage::

    S = mesh.shape["pp"]
    # stage_params: pytree whose leaves have leading axis S (stage-major)
    out = pipeline_apply(stage_fn, stage_params, x, mesh,
                         num_microbatches=M)
"""
from __future__ import annotations

from ..base import MXNetError

__all__ = ["pipeline_apply", "stack_stage_params"]


def stack_stage_params(per_stage_params):
    """[stage0_tree, stage1_tree, ...] -> one tree with leading stage axis
    (what pipeline_apply shards over pp)."""
    import jax
    import jax.numpy as jnp

    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs),
                                  *per_stage_params)


def pipeline_apply(stage_fn, stage_params, x, mesh, num_microbatches,
                   axis="pp", remat_stage=False):
    """Run ``stage_fn`` as an S-stage pipeline over the mesh's pp axis.

    stage_fn(params_one_stage, x_mb) -> y_mb, where y_mb has x_mb's shape
    (homogeneous stages — the transformer-stack case).
    stage_params: pytree, leaves shaped (S, ...); sharded over pp here.
    x: global batch, leading dim divisible by num_microbatches.
    Returns stage_{S-1}(...stage_0(x)) with the same sharding as x.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    S = mesh.shape[axis]
    M = int(num_microbatches)
    if x.shape[0] % M:
        raise MXNetError(f"batch {x.shape[0]} not divisible by "
                         f"num_microbatches {M}")
    n_stages = {leaf.shape[0]
                for leaf in jax.tree_util.tree_leaves(stage_params)}
    if n_stages != {S}:
        raise MXNetError(
            f"stage_params leading dim {sorted(n_stages)} must equal the "
            f"pp axis size {S} (one stage per device)")

    def leaf_spec(leaf):
        return P(axis, *([None] * (leaf.ndim - 1)))

    pspecs = jax.tree_util.tree_map(leaf_spec, stage_params)
    stage_params = jax.tree_util.tree_map(
        lambda leaf, spec: jax.device_put(leaf, NamedSharding(mesh, spec)),
        stage_params, pspecs)

    if remat_stage:
        stage_fn = jax.checkpoint(stage_fn)

    def pp_fn(params_local, xs):
        # params_local: leaves (1, ...) — this device's stage
        # xs: (M, mb, ...) microbatched input (replicated over pp)
        s = jax.lax.axis_index(axis)
        p_one = jax.tree_util.tree_map(lambda l: l[0], params_local)
        mb_shape = xs.shape[1:]
        state = jnp.zeros(mb_shape, xs.dtype)     # activation in flight
        outputs = jnp.zeros_like(xs)
        perm = [(i, (i + 1) % S) for i in range(S)]

        def tick(carry, t):
            state, outputs = carry
            # stage 0 ingests microbatch t (garbage after t >= M is
            # masked out on the output side)
            mb_in = xs[jnp.minimum(t, M - 1)]
            inp = jnp.where(s == 0, mb_in, state)
            # double-where: on bubble ticks (device s busy only for
            # s <= t < s+M) substitute a finite placeholder, so stage_fn
            # never evaluates on garbage — otherwise a NaN-capable stage
            # poisons the BACKWARD pass (0 cotangent x NaN Jacobian = NaN)
            # even though the forward masks discard the value
            valid = (t >= s) & (t < s + M)
            inp = jnp.where(valid, inp, xs[0])
            out = stage_fn(p_one, inp)
            # last stage completed microbatch t-(S-1) at this tick
            done_idx = t - (S - 1)
            write = (s == S - 1) & (done_idx >= 0)
            di = jnp.maximum(done_idx, 0)
            # jnp.where (not arithmetic masking): warmup-tick garbage can
            # be NaN and NaN*0 would poison valid outputs
            outputs = outputs.at[di].set(
                jnp.where(write, out, outputs[di]))
            # pass activations downstream (stage S-1 -> 0 link carries
            # garbage; stage 0 ignores its input)
            state = jax.lax.ppermute(out, axis, perm)
            return (state, outputs), None

        # scan (not fori_loop): the schedule must be reverse-differentiable
        (_, outputs), _ = jax.lax.scan(tick, (state, outputs),
                                       jnp.arange(M + S - 1))
        # result lives on the last stage; broadcast over pp
        outputs = jnp.where(s == S - 1, outputs, jnp.zeros_like(outputs))
        return jax.lax.psum(outputs, axis)

    xs = x.reshape((M, x.shape[0] // M) + x.shape[1:])
    in_specs = (pspecs, P(*([None] * xs.ndim)))
    out_spec = P(*([None] * xs.ndim))
    y = shard_map(pp_fn, mesh=mesh, in_specs=in_specs,
                  out_specs=out_spec, check_rep=False)(stage_params, xs)
    return y.reshape(x.shape)
