"""Pipeline parallelism over the ``pp`` mesh axis (GPipe + 1F1B).

Capability upgrade over the reference (MXNet 1.x has no pipeline
parallelism; its answer to big models was parameter servers).  TPU-native
shape, per the scaling-book recipe: each pp device holds ONE stage's
parameters (weight-stationary); microbatches stream through the pipeline
with ``lax.ppermute`` passing activations over ICI between ticks.  With M
microbatches and S stages the loop runs M+S-1 ticks and every device is
busy in the steady state (bubble fraction (S-1)/(M+S-1)).

Two schedules:

- ``schedule='gpipe'`` (default): the whole tick loop is one
  reverse-differentiable ``lax.scan``; jax AD stores per-tick residuals
  (or recomputes them under ``remat_stage=True``).
- ``schedule='1f1b'``: a hand-written ``jax.custom_vjp`` backward in 1F1B
  order — the forward stashes ONLY each microbatch's stage input (M
  small buffers per device); the backward replays stages one microbatch
  at a time (recompute + vjp), streaming activation cotangents upstream
  over the reverse ppermute ring.  Peak activation memory is O(M input
  stashes + 1 in-flight), independent of the tick count — the property
  the 1F1B schedule exists for.

Composes with data parallelism: pass ``batch_axes`` to shard the
microbatch dimension over dp/fsdp while the pipeline runs over pp
(collectives stay inside their own mesh axes).
"""
from __future__ import annotations

from ..base import MXNetError

__all__ = ["pipeline_apply", "stack_stage_params"]


def stack_stage_params(per_stage_params):
    """[stage0_tree, stage1_tree, ...] -> one tree with leading stage axis
    (what pipeline_apply shards over pp)."""
    import jax
    import jax.numpy as jnp

    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs),
                                  *per_stage_params)


def pipeline_apply(stage_fn, stage_params, x, mesh, num_microbatches,
                   axis="pp", remat_stage=False, schedule="gpipe",
                   batch_axes=(), in_jit_sharding=None):
    """Run ``stage_fn`` as an S-stage pipeline over the mesh's pp axis.

    stage_fn(params_one_stage, x_mb) -> y_mb, where y_mb has x_mb's shape
    (homogeneous stages — the transformer-trunk case; heterogeneous
    embed/head ends run OUTSIDE the pipeline, see TrainStep(pipeline=...)).
    stage_params: pytree, leaves shaped (S, ...); sharded over pp here.
    x: global batch, leading dim divisible by num_microbatches (and by
    the product of ``batch_axes`` mesh axes, which shard it).
    Returns stage_{S-1}(...stage_0(x)) with x's sharding.

    ``in_jit_sharding`` selects the layout of TRACED stage params (the
    TrainStep path, where the stacked tree is built inside an outer
    jit): False/None = the replicated workaround for the jax-0.4.37
    GSPMD miscompile (see below); True = true weight-stationary
    ``P(pp)`` in_specs — flip via the planner
    (``ShardingPlan.pipeline_in_jit_sharding`` /
    ``MXNET_PLANNER_PIPELINE_IN_JIT``) once a jax upgrade proves it
    correct on multi-axis meshes.  Concrete (non-traced) stage params
    are always placed weight-stationary; stage specs come from the
    planner (:func:`planner.rules.stage_spec`).
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from .collectives import shard_map
    from .planner.rules import stage_spec

    if in_jit_sharding is None:
        from .. import env as _env

        in_jit_sharding = _env.planner_pipeline_in_jit()
    if schedule not in ("gpipe", "1f1b"):
        raise MXNetError(f"unknown pipeline schedule {schedule!r}")
    S = mesh.shape[axis]
    M = int(num_microbatches)
    if x.shape[0] % M:
        raise MXNetError(f"batch {x.shape[0]} not divisible by "
                         f"num_microbatches {M}")
    n_stages = {leaf.shape[0]
                for leaf in jax.tree_util.tree_leaves(stage_params)}
    if n_stages != {S}:
        raise MXNetError(
            f"stage_params leading dim {sorted(n_stages)} must equal the "
            f"pp axis size {S} (one stage per device)")

    def leaf_spec(leaf):
        # planner-owned stage layout: leading stage dim over pp
        return P(*stage_spec(leaf.ndim, axis))

    traced = any(isinstance(leaf, jax.core.Tracer)
                 for leaf in jax.tree_util.tree_leaves(stage_params))
    replicated_in = traced and not in_jit_sharding
    if replicated_in:
        # Inside an outer jit (TrainStep): the stage params were stacked
        # by TRACED ops, and feeding that product into shard_map with a
        # P(axis) spec miscompiles under GSPMD when the mesh carries
        # more axes than pp (observed on XLA:CPU, jax 0.4.37: garbage
        # outputs on a dp×pp mesh even with check_rep on and replicated
        # batch — the exact composition TrainStep(pipeline=...) builds;
        # eager shard_map of the identical program is correct).  Route
        # around the partitioner: pass the stacked tree in REPLICATED
        # (P()) and let each device gather its own stage by axis index
        # inside the body.  Memory is unchanged for the TrainStep path —
        # its source params are replicated storage anyway.
        # ``in_jit_sharding=True`` (planner flag) restores the
        # weight-stationary P(axis) specs — re-test after a jax upgrade;
        # on real pods it avoids holding every stage's params per device
        # inside the pipe region.
        pspecs = jax.tree_util.tree_map(lambda leaf: P(), stage_params)
    else:
        pspecs = jax.tree_util.tree_map(leaf_spec, stage_params)
        if not traced:
            # concrete params: place weight-stationary up front
            # (tracers cannot be device_put — in-jit sharding rides the
            # in_specs alone)
            stage_params = jax.tree_util.tree_map(
                lambda leaf, spec: jax.device_put(
                    leaf, NamedSharding(mesh, spec)),
                stage_params, pspecs)

    if remat_stage:
        # gpipe: AD recomputes per-tick; 1f1b: bounds the intra-stage
        # residuals each per-microbatch jax.vjp in the backward stores
        stage_fn = jax.checkpoint(stage_fn)

    perm_fwd = [(i, (i + 1) % S) for i in range(S)]
    perm_bwd = [(i, (i - 1) % S) for i in range(S)]

    def run_forward(xs, p_one, s, stash):
        """The M+S-1 tick loop.  When ``stash`` is True, also record each
        microbatch's stage INPUT (the 1f1b residual)."""
        mb_shape = xs.shape[1:]
        state = jnp.zeros(mb_shape, xs.dtype)
        outputs = jnp.zeros_like(xs)
        saved = jnp.zeros_like(xs) if stash else None

        def tick(carry, t):
            state, outputs, saved = carry
            mb_in = xs[jnp.minimum(t, M - 1)]
            inp = jnp.where(s == 0, mb_in, state)
            # double-where: on bubble ticks substitute a finite
            # placeholder so stage_fn never evaluates on garbage (a NaN
            # Jacobian x 0 cotangent would still poison the backward)
            valid = (t >= s) & (t < s + M)
            inp = jnp.where(valid, inp, xs[0])
            if stash:
                mi = jnp.clip(t - s, 0, M - 1)
                saved = saved.at[mi].set(
                    jnp.where(valid, inp, saved[mi]))
            out = stage_fn(p_one, inp)
            done_idx = t - (S - 1)
            write = (s == S - 1) & (done_idx >= 0)
            di = jnp.maximum(done_idx, 0)
            outputs = outputs.at[di].set(
                jnp.where(write, out, outputs[di]))
            state = jax.lax.ppermute(out, axis, perm_fwd)
            return (state, outputs, saved), None

        (_, outputs, saved), _ = jax.lax.scan(
            tick, (state, outputs, saved), jnp.arange(M + S - 1))
        outputs = jnp.where(s == S - 1, outputs, jnp.zeros_like(outputs))
        return jax.lax.psum(outputs, axis), saved

    def pp_fn(params_local, xs):
        if replicated_in:
            # replicated-in params: each device selects its stage (the
            # gather's transpose scatter-adds grads back to the right
            # stage slice, so AD composes)
            s0 = jax.lax.axis_index(axis)
            p_one = jax.tree_util.tree_map(
                lambda l: jax.lax.dynamic_index_in_dim(
                    l, s0, 0, keepdims=False), params_local)
        else:
            # weight-stationary: P(axis) left one stage per device
            p_one = jax.tree_util.tree_map(lambda l: l[0], params_local)

        if schedule == "gpipe":
            out, _ = run_forward(xs, p_one, jax.lax.axis_index(axis),
                                 stash=False)
            return out

        # NOTE: each custom_vjp piece recomputes axis_index itself —
        # closing over the tracer from pp_fn would leak it into the
        # separately-traced fwd/bwd functions

        @jax.custom_vjp
        def f(p_one, xs):
            out, _ = run_forward(xs, p_one, jax.lax.axis_index(axis),
                                 stash=False)
            return out

        def f_fwd(p_one, xs):
            out, saved = run_forward(xs, p_one, jax.lax.axis_index(axis),
                                     stash=True)
            # residual: saved only (same (M, mb, ...) shape as xs) — also
            # carrying xs would double the stashed-activation footprint
            # the 1F1B schedule exists to minimize
            return out, (p_one, saved)

        def f_bwd(res, d_out):
            # 1F1B-ordered backward: reverse ticks; each device handles
            # the cotangent of one microbatch per tick, recomputing its
            # stage forward from the stashed input and streaming the
            # input-cotangent upstream.  Live state: the M input stashes
            # + one cotangent in flight — no per-tick residual stack.
            p_one, saved = res
            s = jax.lax.axis_index(axis)
            # boundary convention (check_rep=False): the replicated
            # output's cotangent arrives as d_true/S on each device; the
            # forward's own psum transposes to psum, so recover d_true
            # explicitly here
            d_out = jax.lax.psum(d_out, axis)
            dxs0 = jnp.zeros_like(saved)
            dp0 = jax.tree_util.tree_map(jnp.zeros_like, p_one)
            g0 = jnp.zeros(saved.shape[1:], saved.dtype)

            def btick(carry, t):
                g_state, dxs, dp = carry
                m = t - s                       # microbatch this device
                valid = (m >= 0) & (m < M)      # handles at reverse tick
                mi = jnp.clip(m, 0, M - 1)
                inp = saved[mi]
                # last stage seeds from the output cotangent; upstream
                # stages consume what flowed back over the ring
                g_in = jnp.where(s == S - 1, d_out[mi], g_state)
                g_in = jnp.where(valid, g_in, jnp.zeros_like(g_in))
                _, vjp = jax.vjp(stage_fn, p_one, inp)
                dp_t, dx = vjp(g_in)
                dp = jax.tree_util.tree_map(lambda a, b: a + b, dp, dp_t)
                dxs = dxs.at[mi].add(
                    jnp.where(valid & (s == 0), dx,
                              jnp.zeros_like(dx)))
                g_state = jax.lax.ppermute(dx, axis, perm_bwd)
                return (g_state, dxs, dp), None

            # reverse order: tick M+S-2 first (the 1F1B tail) down to 0
            (_, dxs, dp), _ = jax.lax.scan(
                btick, (g0, dxs0, dp0),
                jnp.arange(M + S - 2, -1, -1))
            # xs is a replicated input: shard_map's own transpose psums
            # per-device contributions (only stage 0's is nonzero), so
            # return the local contribution un-summed
            return dp, dxs

        f.defvjp(f_fwd, f_bwd)
        return f(p_one, xs)

    xs = x.reshape((M, x.shape[0] // M) + x.shape[1:])
    bspec = tuple(batch_axes) if batch_axes else None
    xs_spec = P(None, bspec, *([None] * (xs.ndim - 2)))
    in_specs = (pspecs, xs_spec)
    y = shard_map(pp_fn, mesh=mesh, in_specs=in_specs,
                  out_specs=xs_spec)(stage_params, xs)
    return y.reshape(x.shape)
