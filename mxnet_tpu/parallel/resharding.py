"""Plan-to-plan live resharding: move sharded state between meshes
in-flight, without a checkpoint disk round-trip.

Reference: "Efficient and Memory-Bounded Array Redistribution"
(PAPERS.md, arXiv:2112.01075) — redistributing an N-D array between two
shardings decomposes into per-(source shard, target shard) slice
intersections, and the slice moves can be scheduled under a bounded
in-flight byte budget so the redistribution never needs a second full
copy of the array resident.  This module applies that scheme to the
repo's elasticity gap (ROADMAP "zero-downtime elasticity"): a preempted
or resized pod re-shards surviving parameters and ZeRO optimizer shards
from the OLD :class:`~mxnet_tpu.parallel.planner.ShardingPlan`'s layout
to the NEW plan's layout directly, instead of restoring from disk and
paying the checkpoint round trip.

Two layers:

- :func:`compute_transfer_plan` — **pure and digest-stable**: from
  (source plan, target plan, parameter signature) it derives, per
  parameter, the N-D block grid each plan induces (PartitionSpec ×
  mesh axes → per-dim partition counts) and emits one *move* per
  non-empty (source block, target block) intersection.  ZeRO flat
  buckets ride the same plan as 1-D entries whose blocks are the
  clipped :func:`~mxnet_tpu.parallel.bucketing.shard_layout` spans.
  Every SPMD peer computes the identical plan (``digest()`` compared by
  the CI smoke) — the same determinism contract as bucket plans and
  sharding plans.
- :func:`apply_transfer` — executes the moves in rounds whose total
  in-flight bytes stay under ``MXNET_RESHARD_INFLIGHT_MB``, through the
  :mod:`~mxnet_tpu.parallel.collectives` placement helpers.  The
  transfer NEVER mutates its inputs: it builds new arrays under the
  target layout and the caller swaps on success, so a fault mid-flight
  leaves the source state whole.  Fault seam ``resharding.transfer``:
  single-process the whole transfer is retried under the PR 2 policy
  (the function is pure, so a retry is safe); multi-process the seam
  only checks — a unilateral retry would desync peers (the PR 2
  no-unilateral-retry contract), so a real transient failure escalates
  to ``run_with_recovery``, whose checkpoint path is the fallback.

SPMD contract (machine-enforced by mxtpu-check pass
``resharding-transfer``, MXT080): every process that computes a
transfer plan must either :func:`apply_transfer` it or explicitly
:meth:`TransferPlan.discard` it, at uniform SPMD level — a
rank-conditional ``apply_transfer`` deadlocks the mesh exactly like a
rank-conditional collective (MXT001).
"""
from __future__ import annotations

import hashlib
import json
import time

import numpy as _np

from .. import env as _env
from .. import fault as _fault
from .. import flight_recorder as _flight
from .. import telemetry as _telemetry
from ..base import MXNetError
from . import bucketing as _bucketing

__all__ = ["TransferPlan", "compute_transfer_plan",
           "compute_flat_transfer_plan", "apply_transfer",
           "transfer_params", "peers_agree_intact",
           "observe_restart_to_first_step", "record_live_reshard",
           "record_reshard_fallback"]

_BYTES = _telemetry.counter(
    "mxnet_reshard_bytes_total",
    "bytes moved by live resharding transfers (counted once per move)",
    labelnames=("kind",))
_TRANSFERS = _telemetry.counter(
    "mxnet_reshard_transfers_total", "apply_transfer executions")
_SECONDS = _telemetry.histogram(
    "mxnet_reshard_seconds", "apply_transfer wall time")
_RESTART_HIST = _telemetry.histogram(
    "mxnet_elastic_restart_to_first_step_seconds",
    "wall time from recovery start to the first trained step after an "
    "elastic restart (live-reshard or checkpoint path)")
_LIVE_TOTAL = _telemetry.counter(
    "mxnet_recovery_live_reshards_total",
    "recoveries served by live resharding instead of checkpoint restore")
_FALLBACK_TOTAL = _telemetry.counter(
    "mxnet_recovery_reshard_fallbacks_total",
    "live-reshard attempts that fell back to the checkpoint path")


def observe_restart_to_first_step(seconds):
    """Record one restart-to-first-step measurement (bench / smoke /
    embedders clock the real first step; run_with_recovery cannot see
    inside train_fn)."""
    _RESTART_HIST.observe(float(seconds))


def record_live_reshard():
    """Count one recovery served by the live-reshard path (called by
    ``run_with_recovery`` — public so the supervisor never depends on
    this module's private counter objects)."""
    _LIVE_TOTAL.inc()


def record_reshard_fallback():
    """Count one live-reshard attempt that fell back to the checkpoint
    path."""
    _FALLBACK_TOTAL.inc()


def inflight_budget_bytes():
    """Bounded in-flight byte budget per transfer round
    (``MXNET_RESHARD_INFLIGHT_MB``, default 64 MiB)."""
    return max(1, _env.reshard_inflight_mb()) << 20


# --------------------------------------------------------------------------
# pure plan computation
# --------------------------------------------------------------------------
def _dim_parts(entry, axes):
    """Partition count one PartitionSpec dim entry induces under mesh
    ``axes`` (None/absent/size-1 axes are vacuous)."""
    if entry is None or entry == ():
        return 1
    names = entry if isinstance(entry, (list, tuple)) else (entry,)
    n = 1
    for a in names:
        n *= int(axes.get(a, 1))
    return n


def _grid_parts(shape, spec, axes):
    """Per-dim partition counts for one parameter (1 for dims the spec
    does not cover)."""
    spec = tuple(spec or ())
    parts = []
    for d, size in enumerate(shape):
        p = _dim_parts(spec[d], axes) if d < len(spec) else 1
        if p > 1 and size % p:
            raise MXNetError(
                f"dim {d} of shape {tuple(shape)} not divisible by "
                f"{p} (spec {spec!r})")
        parts.append(p)
    return tuple(parts)


def _blocks(shape, parts):
    """Distinct shard blocks in row-major block-coordinate order:
    list of per-dim (start, stop) tuples."""
    out = [()]
    for size, p in zip(shape, parts):
        step = size // p
        out = [b + ((i * step, (i + 1) * step),)
               for b in out for i in range(p)]
    return out


def _intersect(a, b):
    """N-D intersection of two block index tuples, or None."""
    out = []
    for (a0, a1), (b0, b1) in zip(a, b):
        lo, hi = max(a0, b0), min(a1, b1)
        if lo >= hi:
            return None
        out.append((lo, hi))
    return tuple(out)


def _span_blocks(size, dp):
    """Clipped contiguous rank spans of a flat buffer under
    :func:`bucketing.shard_layout` — the ZeRO state layout.  Spans past
    the true size are empty (padding holds no state)."""
    padded, shard, _ = _bucketing.shard_layout(size, dp)
    return [((r * shard, min((r + 1) * shard, size)),)
            for r in range(dp)], padded, shard


def _moves_between(shape, dtype, src_blocks, tgt_blocks):
    itemsize = _np.dtype(dtype).itemsize
    moves = []
    for t, tb in enumerate(tgt_blocks):
        if any(a >= b for a, b in tb):
            continue                      # empty target span (flat pad)
        for s, sb in enumerate(src_blocks):
            if any(a >= b for a, b in sb):
                continue
            inter = _intersect(sb, tb)
            if inter is None:
                continue
            n = 1
            for a, b in inter:
                n *= b - a
            moves.append({"src": s, "tgt": t,
                          "index": [[int(a), int(b)] for a, b in inter],
                          "bytes": int(n * itemsize)})
    return moves


class TransferPlan:
    """Immutable schedule of slice-wise moves between two plans' layouts.

    ``entries`` is a list of dicts — kind ``param`` (N-D, block grids
    from the plans' PartitionSpecs) or ``zero`` (1-D flat optimizer
    buckets, clipped ``shard_layout`` spans) — each carrying its moves.
    Pure data: JSON/digest-stable across processes (the determinism
    fingerprint CI compares), no devices, no wall clock."""

    def __init__(self, entries, src_axes, tgt_axes):
        self.entries = list(entries)
        self.src_axes = dict(src_axes)
        self.tgt_axes = dict(tgt_axes)

    def total_bytes(self):
        return sum(m["bytes"] for e in self.entries for m in e["moves"])

    def to_json(self):
        return json.dumps({"entries": self.entries,
                           "src_axes": self.src_axes,
                           "tgt_axes": self.tgt_axes}, sort_keys=True)

    def digest(self):
        """Cross-process determinism fingerprint (equal iff the plans
        are byte-identical, like ``ShardingPlan.digest``)."""
        return hashlib.sha256(self.to_json().encode()).hexdigest()

    def discard(self):
        """Explicitly drop a computed-but-not-executed plan.  The
        MXT080 contract: every process either applies a computed plan
        or discards it — both at uniform SPMD level — so a plan can
        never be half-executed across the mesh.  Pure bookkeeping (the
        plan holds no device state); exists so intent is visible to
        readers and to the checker."""
        return None


def _spec_json(spec):
    """PartitionSpec tuple → JSON-stable form (inner tuples → lists)."""
    return [list(e) if isinstance(e, tuple) else e
            for e in tuple(spec or ())]


def _spec_from_json(spec):
    return tuple(tuple(e) if isinstance(e, list) else e for e in spec)


def _entry_for_param(name, shape, dtype, src_spec, src_axes, tgt_spec,
                     tgt_axes):
    shape = tuple(int(x) for x in shape)
    src_parts = _grid_parts(shape, src_spec, src_axes)
    tgt_parts = _grid_parts(shape, tgt_spec, tgt_axes)
    moves = _moves_between(shape, dtype, _blocks(shape, src_parts),
                           _blocks(shape, tgt_parts))
    return {"name": str(name), "kind": "param", "shape": list(shape),
            "dtype": str(dtype), "src_parts": list(src_parts),
            "tgt_parts": list(tgt_parts),
            "tgt_spec": _spec_json(tgt_spec), "moves": moves}


def _entry_for_flat(name, size, dtype, src_dp, tgt_dp):
    src_blocks, src_padded, _ = _span_blocks(size, src_dp)
    tgt_blocks, tgt_padded, tgt_shard = _span_blocks(size, tgt_dp)
    moves = _moves_between((size,), dtype, src_blocks, tgt_blocks)
    return {"name": str(name), "kind": "zero", "size": int(size),
            "dtype": str(dtype), "src_dp": int(src_dp),
            "tgt_dp": int(tgt_dp), "src_padded": int(src_padded),
            "tgt_padded": int(tgt_padded), "tgt_shard": int(tgt_shard),
            "moves": moves}


def compute_transfer_plan(src_plan, tgt_plan, signature, zero_buckets=()):
    """(source ShardingPlan, target ShardingPlan, signature) → the
    per-parameter slice-move schedule of arXiv:2112.01075.

    ``signature`` is the planner's ordered ``(name, shape, dtype)``
    tuple (``planner.signature_of``); each parameter's source and
    target block grids come from the respective plan's resolved spec.
    ``zero_buckets`` optionally adds flat optimizer-shard entries —
    iterable of ``(label, size, dtype, n_state)``; state leaf ``i`` of
    bucket ``label`` becomes entry ``zero:{label}.s{i}`` moving from
    ``src_plan.zero_shards`` contiguous spans to ``tgt_plan``'s.

    Pure function: no devices, no env, no wall clock — every SPMD peer
    (and every restart) computes a plan with the identical
    :meth:`TransferPlan.digest`."""
    entries = []
    src_axes = dict(src_plan.axes)
    tgt_axes = dict(tgt_plan.axes)
    for name, shape, dtype in signature:
        entries.append(_entry_for_param(
            name, shape, dtype, src_plan.specs.get(name, ()), src_axes,
            tgt_plan.specs.get(name, ()), tgt_axes))
    for label, size, dtype, n_state in zero_buckets:
        for i in range(int(n_state)):
            entries.append(_entry_for_flat(
                f"zero:{label}.s{i}", size, dtype,
                src_plan.zero_shards, tgt_plan.zero_shards))
    return TransferPlan(entries, src_axes, tgt_axes)


def compute_flat_transfer_plan(buffers, src_dp, tgt_dp):
    """Flat-buffer-only transfer plan: ``buffers`` is an iterable of
    ``(name, size, dtype)`` each sharded as contiguous clipped
    ``shard_layout`` spans over ``src_dp`` ranks, moving to ``tgt_dp``.
    The ZeRO engine's :meth:`~mxnet_tpu.parallel.zero.ZeroBucketEngine.
    reshard` rides this directly (its shard count may be clamped below
    the plan's ``zero_shards`` by the live device count).  Pure and
    digest-stable like :func:`compute_transfer_plan`."""
    entries = [_entry_for_flat(name, size, dtype, src_dp, tgt_dp)
               for name, size, dtype in buffers]
    return TransferPlan(entries, {"dp": int(src_dp)},
                        {"dp": int(tgt_dp)})


# --------------------------------------------------------------------------
# execution
# --------------------------------------------------------------------------
def _pack_rounds(units, budget):
    """Greedy round packing: each unit is (sort-stable id, bytes);
    rounds carry at most ``budget`` in-flight bytes (a single oversized
    unit gets its own round — it cannot be split further than the plan
    already sliced it)."""
    rounds, cur, cur_bytes = [], [], 0
    for uid, nbytes in units:
        if cur and cur_bytes + nbytes > budget:
            rounds.append(cur)
            cur, cur_bytes = [], 0
        cur.append(uid)
        cur_bytes += nbytes
    if cur:
        rounds.append(cur)
    return rounds


def _tgt_shardings(plan, devices=None):
    """(param target mesh, per-dp zero meshes) for the plan's target
    layout, over the leading devices (the elastic sub-mesh convention
    ShardingPlan.build_mesh established)."""
    import jax
    from jax.sharding import Mesh

    from .mesh import make_mesh

    ax = {a: int(plan.tgt_axes.get(a, 1))
          for a in ("dp", "fsdp", "tp", "sp", "ep", "pp")}
    n = 1
    for v in ax.values():
        n *= v
    devs = list(devices) if devices is not None else jax.devices()
    param_mesh = None
    if any(e["kind"] == "param" for e in plan.entries):
        param_mesh = make_mesh(dp=ax["dp"], fsdp=ax["fsdp"], tp=ax["tp"],
                               sp=ax["sp"], ep=ax["ep"], pp=ax["pp"],
                               devices=devs[:max(1, n)])
    zero_meshes = {}
    for e in plan.entries:
        if e["kind"] == "zero" and e["tgt_dp"] not in zero_meshes:
            zero_meshes[e["tgt_dp"]] = Mesh(
                _np.array(devs[:e["tgt_dp"]]), ("dp",))
    return param_mesh, zero_meshes


def _entry_tgt_sharding(entry, param_mesh, zero_meshes):
    from jax.sharding import NamedSharding, PartitionSpec as P

    if entry["kind"] == "zero":
        return NamedSharding(zero_meshes[entry["tgt_dp"]], P("dp"))
    return NamedSharding(param_mesh,
                         P(*_spec_from_json(entry.get("tgt_spec", []))))


def _assemble_blocks(entry, src_arr, moves):
    """Lazy per-target-block values from the source array: one jnp
    value per distinct target block touched by ``moves`` (global
    coordinates; slices execute on device)."""
    import jax.numpy as jnp

    if entry["kind"] == "zero":
        shard = entry["tgt_shard"]
        blocks = {}
        for m in moves:
            t = m["tgt"]
            (a, b), = m["index"]
            base = t * shard
            buf = blocks.get(t)
            if buf is None:
                buf = jnp.zeros((shard,), entry["dtype"])
            piece = jnp.asarray(src_arr[a:b], entry["dtype"])
            blocks[t] = buf.at[a - base:b - base].set(piece)
        return blocks
    shape = tuple(entry["shape"])
    parts = tuple(entry["tgt_parts"])
    steps = [s // p for s, p in zip(shape, parts)]
    blocks = {}
    for m in moves:
        t = m["tgt"]
        # target block origin from its row-major block id
        coord, div = [], 1
        for p in reversed(parts):
            coord.append((t // div) % p)
            div *= p
        coord.reverse()
        origin = [c * st for c, st in zip(coord, steps)]
        sl = tuple(slice(a, b) for a, b in m["index"])
        piece = jnp.asarray(src_arr[sl], entry["dtype"])
        buf = blocks.get(t)
        if buf is None:
            block_shape = tuple(steps)
            if all((b - a) == bs for (a, b), bs
                   in zip(m["index"], block_shape)):
                blocks[t] = piece      # one move covers the whole block
                continue
            buf = jnp.zeros(block_shape, entry["dtype"])
        rel = tuple(slice(a - o, b - o)
                    for (a, b), o in zip(m["index"], origin))
        blocks[t] = buf.at[rel].set(piece)
    return blocks


def _block_id_of_index(entry, index):
    """Row-major target block id for a device's index tuple."""
    if entry["kind"] == "zero":
        (a, _b), = index
        return a // entry["tgt_shard"]
    shape = tuple(entry["shape"])
    parts = tuple(entry["tgt_parts"])
    steps = [s // p for s, p in zip(shape, parts)]
    bid = 0
    for (a, _b), st, p in zip(index, steps, parts):
        bid = bid * p + (a // st)
    return bid


def _norm_index(idx_tuple, shape):
    out = []
    for sl, size in zip(idx_tuple, shape):
        start = 0 if sl.start is None else sl.start
        stop = size if sl.stop is None else sl.stop
        out.append((int(start), int(stop)))
    return tuple(out)


def _apply_single_process(plan, arrays, budget):
    """Device-to-device slice moves, assembled per target block and
    placed shard-by-shard — never a full host gather.  Rounds bound the
    in-flight bytes: within a round the blocks are assembled, placed
    onto their final target devices, and fenced; only the PLACED
    per-device shards (the target array's own residency, needed either
    way) survive the round — intermediates are released, so peak extra
    memory is one round's worth, per the arXiv:2112.01075 bounded
    scheme."""
    import jax

    param_mesh, zero_meshes = _tgt_shardings(plan)
    units = []       # ((entry_idx, tgt_block), bytes)
    per_entry_moves = {}
    meta = {}        # entry_idx -> (sharding, shape, idx_map,
    #                                block -> [devices])
    for ei, e in enumerate(plan.entries):
        if e["name"] not in arrays:
            continue
        by_block = {}
        for m in e["moves"]:
            by_block.setdefault(m["tgt"], []).append(m)
        per_entry_moves[ei] = by_block
        sharding = _entry_tgt_sharding(e, param_mesh, zero_meshes)
        shape = (e["tgt_padded"],) if e["kind"] == "zero" \
            else tuple(e["shape"])
        idx_map = sharding.devices_indices_map(shape)
        devs_of_block = {}
        for dev, idx in idx_map.items():
            bid = _block_id_of_index(e, _norm_index(idx, shape))
            devs_of_block.setdefault(bid, []).append(dev)
        meta[ei] = (sharding, shape, idx_map, devs_of_block)
        for t, ms in sorted(by_block.items()):
            # replicated target blocks are placed once per device:
            # budget the true in-flight bytes
            reps = max(1, len(devs_of_block.get(t, ())))
            units.append(((ei, t),
                          sum(m["bytes"] for m in ms) * reps))
    rounds = _pack_rounds(units, budget)
    placed = {}      # (entry_idx, tgt_block, device) -> placed shard
    for rnd in rounds:
        refs = []
        for ei, t in rnd:
            e = plan.entries[ei]
            blocks = _assemble_blocks(e, arrays[e["name"]],
                                      per_entry_moves[ei][t])
            val = blocks[t]
            for dev in meta[ei][3].get(t, ()):
                buf = jax.device_put(val, dev)
                placed[(ei, t, dev)] = buf
                refs.append(buf)
            _BYTES.labels(kind=e["kind"]).inc(
                sum(m["bytes"] for m in per_entry_moves[ei][t]))
        # fence: the round's copies land before the next round's slices
        # are issued, and `val`/`blocks` intermediates die here
        jax.block_until_ready(refs)
    out = {}
    for ei, e in enumerate(plan.entries):
        if e["name"] not in arrays:
            continue
        sharding, shape, idx_map, _devs = meta[ei]
        bufs = []
        for dev, idx in idx_map.items():
            bid = _block_id_of_index(e, _norm_index(idx, shape))
            buf = placed.get((ei, bid, dev))
            if buf is None:      # block with no moves (flat pad tail)
                import jax.numpy as jnp

                if e["kind"] == "zero":
                    val = jnp.zeros((e["tgt_shard"],), e["dtype"])
                else:
                    val = jnp.zeros(
                        tuple(b - a
                              for a, b in _norm_index(idx, shape)),
                        e["dtype"])
                buf = jax.device_put(val, dev)
            bufs.append(buf)
        out[e["name"]] = jax.make_array_from_single_device_arrays(
            shape, sharding, bufs)
    return out


def _apply_multi_process(plan, arrays):
    """Multi-process path: non-addressable shards cannot be sliced
    device-to-device from Python, so each entry goes host-gather →
    place under the target sharding (both helpers are collectives-safe
    and reached uniformly — the caller contract).  The byte budget is
    vacuous here; the paper's bounded scheme applies per entry."""
    from .collectives import fetch_global, place_global

    param_mesh, zero_meshes = _tgt_shardings(plan)
    out = {}
    for e in plan.entries:
        if e["name"] not in arrays:
            continue
        sharding = _entry_tgt_sharding(e, param_mesh, zero_meshes)
        host = _np.asarray(fetch_global(arrays[e["name"]]))
        if e["kind"] == "zero":
            host = host[:e["size"]]
            if host.size < e["tgt_padded"]:
                host = _np.pad(host, (0, e["tgt_padded"] - host.size))
        _BYTES.labels(kind=e["kind"]).inc(int(host.nbytes))
        out[e["name"]] = place_global(host, sharding)
    return out


def apply_transfer(plan, arrays, budget_bytes=None):
    """Execute a :class:`TransferPlan` over ``arrays`` (name → array in
    the SOURCE layout); returns a NEW dict of arrays in the TARGET
    layout.  Inputs are never mutated — a fault mid-transfer leaves the
    source state whole, which is what makes the retry safe.

    SPMD: must be reached at uniform level on every process (MXT080);
    the ``resharding.transfer`` seam is retried only single-process
    (PR 2 no-unilateral-retry contract — multi-process a transient
    failure escalates to run_with_recovery's checkpoint fallback)."""
    import jax

    if budget_bytes is None:
        budget_bytes = inflight_budget_bytes()
    t0 = time.perf_counter()

    def _run():
        if jax.process_count() == 1:
            return _apply_single_process(plan, arrays, budget_bytes)
        return _apply_multi_process(plan, arrays)

    # ONE ledger entry frames the whole transfer (the multi-process
    # path's per-entry fetch_global gathers stamp their own sequence
    # numbers inside it — entry iteration is deterministic, so the
    # nesting is identical on every peer); generation = the plan digest
    # prefix, so a desync across differently-computed plans is blamable
    with _flight.collective("reshard_transfer",
                            generation=plan.digest()[:12]):
        if jax.process_count() == 1:
            out = _fault.call_with_retries("resharding.transfer", _run)
        else:
            _fault.check("resharding.transfer")
            out = _run()
    _TRANSFERS.inc()
    dt = time.perf_counter() - t0
    _SECONDS.observe(dt)
    # goodput ledger: transfer wall time is recovery work, not training
    _telemetry.goodput_note("reshard", dt)
    return out


def transfer_params(arrays, src_plan=None, tgt_plan=None,
                    budget_bytes=None):
    """One-call param move between two ShardingPlans (either may be
    None = replicated single-host layout): computes the transfer plan
    from the arrays' own signature and applies it.  The serving replica
    handoff and the elastic TrainStep path both ride this."""
    from .planner import PlannerConfig, plan_sharding, signature_of

    sig = signature_of(arrays)

    def _trivial():
        cfg = PlannerConfig(mesh={"dp": 1}, rules="replicated")
        return plan_sharding(cfg, sig, 1)

    src = src_plan if src_plan is not None else _trivial()
    tgt = tgt_plan if tgt_plan is not None else _trivial()
    plan = compute_transfer_plan(src, tgt, sig)
    return apply_transfer(plan, dict(arrays), budget_bytes=budget_bytes)


def peers_agree_intact(local_ok):
    """ONE collective agreeing the surviving in-process state is intact
    on EVERY peer: returns True only when no process reports damage.
    The inverse of ``allreduce_any`` (any veto wins), issued
    unconditionally so SPMD collective counts stay uniform — callers
    must reach this on every process before choosing the live-reshard
    path over the checkpoint fallback."""
    from .collectives import allreduce_any

    return not allreduce_any(not bool(local_ok))
