"""ZeRO-1 optimizer-state sharding on the bucketed dense-grad path.

Reference: "Automatic Cross-Replica Sharding of Weight Update in
Data-Parallel Training" (PAPERS.md, arXiv:2004.13336) — the weight
update of data-parallel training is itself data-parallel over the
replicas: instead of every replica paying one full-size allreduce per
grad bucket and then redundantly applying the identical optimizer
update to a full replica of the optimizer state, the flat bucket is

    reduce-scatter'd  →  each rank updates ONLY its 1/dp shard
                         (momentum/Adam moments live permanently
                         sharded)              →  the updated params
                         are all-gather'd back to every replica.

Net effect: optimizer HBM drops to ~1/dp per rank, and the one
bucket-sized allreduce becomes two half-cost collectives (a
reduce-scatter moves the same bytes an allreduce's reduce phase does;
the all-gather moves parameter bytes, which equal gradient bytes) —
plus the update math itself runs on 1/dp of the elements.

Layering (mirrors PR 4's bucketed fused allreduce, which this replaces
when ``MXNET_ZERO=1``):

- the :class:`~mxnet_tpu.parallel.bucketing.Bucketer` plan still decides
  the flat bucket composition deterministically on every SPMD peer; the
  per-rank shard layout is :func:`bucketing.shard_layout` — flat size
  padded to dp-divisible, contiguous rank shards — and is a pure
  function of (bucket size, dp), so every peer computes the same shards.
  The shard COUNT itself derives from the sharding planner when a plan
  governs the engine (``ZeroBucketEngine(opt, plan=...)`` or the
  session default via ``planner.set_default_plan``): ``dp`` =
  ``ShardingPlan.zero_shards`` (the plan's data-parallel degree), so an
  elastic restore onto a different planner-chosen mesh is first-class —
  the payload below was already dp-agnostic.
- optimizer state is keyed by **(plan generation, bucket index)** —
  exactly like the 2-bit compression residual keys — so a replan can
  never alias state across different bucket compositions.  On a
  generation bump the old shards are harvested back to per-parameter
  host pieces and re-flattened into the new plan (momentum survives a
  replan, and the same machinery restores a checkpoint onto a
  *different* dp size or bucket cap).
- the collective pair is issued inside ONE jitted ``shard_map``:
  :func:`collectives.reduce_scatter` and :func:`collectives.all_gather`
  at the same uniformity level in the same function — the contract the
  ``MXT005`` static-analysis pass enforces for every future call site.

Gating: ``MXNET_ZERO`` (default off).  Row-sparse and host-promoted
keys stay on the per-key bypass (their payload is touched rows, not a
stable flat span); non-float buckets and optimizers without a flat
sharded implementation (:func:`supports`) fall back to the replicated
path.  Gradient compression currently applies only to bypass keys in
ZeRO mode — quantizing *inside* the reduce-scatter is the EQuARX item's
hook (ROADMAP).
"""
from __future__ import annotations

import numpy as _np

from .. import env as _env
from .. import fault as _fault
from .. import flight_recorder as _flight
from .. import telemetry as _telemetry
from ..base import MXNetError
from . import bucketing as _bucketing

__all__ = ["zero_enabled", "supports", "ZeroBucketEngine",
           "payload_to_states", "fold_into_updater"]

# one reduce-scatter + one all-gather per bucket per step, each counted
# exactly once at the issue site (the PR 4 byte-accounting discipline:
# flat-buffer bytes, never re-added per member)
_RS_BYTES = _telemetry.counter(
    "mxnet_zero_reduce_scatter_bytes_total",
    "flat-bucket bytes through the ZeRO reduce-scatter (padded, counted "
    "once per bucket)")
_AG_BYTES = _telemetry.counter(
    "mxnet_zero_all_gather_bytes_total",
    "updated-param bytes through the ZeRO all-gather (padded, counted "
    "once per bucket)")
_COLLECTIVES = _telemetry.counter(
    "mxnet_zero_collectives_total",
    "ZeRO collectives issued (exactly 2 per bucket per step: one "
    "reduce-scatter + one all-gather)")
_STATE_BYTES = _telemetry.gauge(
    "mxnet_zero_optimizer_bytes_per_rank",
    "per-rank bytes of sharded optimizer state currently resident "
    "(~1/dp of the replicated path's)")
_SHARD_BYTES = _telemetry.gauge(
    "mxnet_zero_shard_bytes", "per-rank shard bytes of one bucket",
    labelnames=("bucket",))

# optimizers with a flat sharded update implementation; the math mirrors
# ops/optimizer_ops.py element for element so trajectories match the
# replicated kernels
_SUPPORTED = {"SGD": "sgd", "Adam": "adam"}


def zero_enabled():
    """Whether ZeRO-1 sharding is on (``MXNET_ZERO``, default off)."""
    return _env.zero_enabled()


def supports(optimizer):
    """True when ``optimizer`` has a flat sharded update (SGD/Adam)."""
    return type(optimizer).__name__ in _SUPPORTED


def kind_of(optimizer):
    """The engine kind string for ``optimizer`` (None if unsupported)."""
    return _SUPPORTED.get(type(optimizer).__name__)


class ZeroBucketEngine:
    """Sharded weight update for flat grad buckets.

    One engine instance owns the sharded optimizer state of one
    optimizer (a Trainer's, or a kvstore's server-side one).  Per
    bucket-step the caller hands the packed flat gradient contributions
    and the packed flat weight; the engine returns the updated flat
    weight (a single-device array — callers broadcast it back into the
    params/store exactly like a pulled bucket).
    """

    def __init__(self, optimizer, plan=None):
        kind = kind_of(optimizer)
        if kind is None:
            raise MXNetError(
                f"ZeRO sharded update unsupported for "
                f"{type(optimizer).__name__} (supported: "
                f"{sorted(_SUPPORTED)})")
        self.optimizer = optimizer
        self._kind = kind
        # shard layout source: an explicit ShardingPlan, else the
        # session default plan (planner.set_default_plan), else the
        # pre-planner behavior (1/dp over every device).  The payload
        # stays dp-agnostic either way — a checkpoint saved under one
        # plan restores onto any other (elastic-resume contract).
        if plan is None:
            from .planner import get_default_plan

            plan = get_default_plan()
        self._plan = plan
        # (generation tag, bucket index) -> {"leaves", "members", "size",
        # "dtype"}; leaves are global arrays sharded P("dp").  The
        # generation tag is any hashable the CALLER derives from its plan
        # generation (trainer: ("gen", Bucketer.generation); kvstore
        # per-key: ("key", k, version)) — state can never alias across
        # plans with different bucket compositions, exactly like the
        # 2-bit compression residual keys
        self._state = {}
        # per-parameter state pieces awaiting (re)assembly into bucket
        # shards: filled by load_state_payload (checkpoint restore, any
        # dp / plan) and by a generation bump (replan harvest)
        self._carry = {}
        # optional hook: called with an optimizer index when a bucket
        # member has no carried state; may return per-param state leaves
        # (numpy, param-shaped) adopted from a replicated updater — a
        # replicated checkpoint restored into ZeRO mode keeps momentum
        self.adopt = None
        self._jits = {}
        self._mesh = None

    # -- mesh / placement ---------------------------------------------------
    def _get_mesh(self):
        import jax
        from jax.sharding import Mesh

        if self._mesh is None:
            self._mesh = Mesh(_np.array(jax.devices()[:self.dp]), ("dp",))
        return self._mesh

    @property
    def dp(self):
        """Shard count.  From the plan's data-parallel degree
        (``ShardingPlan.zero_shards``) when a plan governs this engine;
        otherwise the full device mesh (every device owns 1/dp of every
        bucket's optimizer state — the pre-planner layout, which a
        full-device dp plan reproduces exactly).  Multi-process jobs
        always use the full mesh: the elastic sub-device plan is a
        single-process concept — ``_contributions`` builds one
        ``n_local``-row block per process, so a mesh missing some
        processes' devices could not place the contribution stack."""
        import jax

        n = len(jax.devices())
        if self._plan is not None and jax.process_count() == 1:
            return max(1, min(n, self._plan.zero_shards))
        return n

    def _place(self, host, spec):
        """Place a host array as a global array with PartitionSpec
        ``spec`` (multi-process safe: built from addressable shards)."""
        from jax.sharding import NamedSharding

        from . import collectives as coll

        return coll.place_global(host, NamedSharding(self._get_mesh(),
                                                     spec))

    # -- the jitted reduce-scatter -> sharded update -> all-gather step ----
    def _get_step(self, padded, dtype, clip, vec_lr, vec_wd):
        key = (padded, str(dtype), clip, vec_lr, vec_wd, self._n_state())
        if key not in self._jits:
            self._jits[key] = self._make_step(padded, clip, vec_lr, vec_wd)
        return self._jits[key]

    def _make_step(self, padded, clip, vec_lr, vec_wd):
        """Build the jitted shard_map step for one (padded size, hyper
        shape) signature.  ``clip`` is static (it selects whether the
        clamp exists in the program, mirroring ops/optimizer_ops.py);
        lr/wd/momentum/rescale are traced so schedules never retrace."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from . import collectives as coll

        mesh = self._get_mesh()
        dp = self.dp
        shard = padded // dp
        kind = self._kind
        # lr/wd ride as scalars (replicated) unless per-param multipliers
        # differ, then as flat vectors sharded exactly like the state
        lr_spec = P("dp") if vec_lr else P()
        wd_spec = P("dp") if vec_wd else P()

        def prep(gstack, wf, wd, rescale):
            # gstack: (1, padded) — this rank's contribution row.  The
            # reduce-scatter sums all ranks' contributions and hands each
            # rank its contiguous 1/dp shard of the summed gradient;
            # then the same rescale -> clip -> +wd*w order as
            # ops/optimizer_ops.py _prep, on the shard only.
            # mxtpu: noqa[MXT100] traced shard_map body — step_bucket stamps the issued pair
            g = coll.reduce_scatter(gstack[0], axis_name="dp")
            g = g * rescale
            if clip is not None:
                g = jnp.clip(g, -clip, clip)
            return g + wd * wf

        def own_shard(wfull):
            idx = jax.lax.axis_index("dp")
            return jax.lax.dynamic_slice(wfull, (idx * shard,), (shard,))

        # kind/momentum are construction-time optimizer config, identical
        # on every SPMD peer; each arm DEFINES one jitted body issuing
        # exactly the rs+ag pair — mxtpu: noqa[MXT003]
        if kind == "adam":
            def body(gstack, wfull, m, v, lr_t, wd, b1, b2, eps, rescale):
                wf = own_shard(wfull)
                g = prep(gstack, wf, wd, rescale)
                # lr_t carries the bias correction (folded by the
                # frontend like optimizer.Adam.update); eps sits outside
                # the raw sqrt(v), matching adam_update
                m_new = b1 * m + (1 - b1) * g
                v_new = b2 * v + (1 - b2) * jnp.square(g)
                wf_new = wf - lr_t * m_new / (jnp.sqrt(v_new) + eps)
                # mxtpu: noqa[MXT100] traced shard_map body — step_bucket stamps the issued pair
                w_new = coll.all_gather(wf_new, axis_name="dp", axis=0,
                                        tiled=True)
                return w_new, (m_new, v_new)

            in_specs = (P("dp", None), P(), P("dp"), P("dp"), lr_spec,
                        wd_spec, P(), P(), P(), P())
            out_specs = (P(), (P("dp"), P("dp")))
        elif self._n_state():  # sgd with momentum
            def body(gstack, wfull, mom, lr, wd, mu, rescale):
                wf = own_shard(wfull)
                g = prep(gstack, wf, wd, rescale)
                # identical math to the sgd_mom_update kernel, on 1/dp
                # of the elements; lr folds into the momentum buffer so
                # schedules keep trajectories bit-identical
                mom_new = mu * mom - lr * g
                wf_new = wf + mom_new
                # mxtpu: noqa[MXT100] traced shard_map body — step_bucket stamps the issued pair
                w_new = coll.all_gather(wf_new, axis_name="dp", axis=0,
                                        tiled=True)
                return w_new, (mom_new,)

            in_specs = (P("dp", None), P(), P("dp"), lr_spec, wd_spec,
                        P(), P())
            out_specs = (P(), (P("dp"),))
        else:  # stateless sgd (momentum == 0)
            def body(gstack, wfull, lr, wd, rescale):
                wf = own_shard(wfull)
                g = prep(gstack, wf, wd, rescale)
                # mxtpu: noqa[MXT100] traced shard_map body — step_bucket stamps the issued pair
                w_new = coll.all_gather(wf - lr * g, axis_name="dp",
                                        axis=0, tiled=True)
                return w_new, ()

            in_specs = (P("dp", None), P(), lr_spec, wd_spec, P())
            out_specs = (P(), ())
        return jax.jit(coll.shard_map(body, mesh, in_specs=in_specs,
                                      out_specs=out_specs))

    def _n_state(self):
        if self._kind == "adam":
            return 2
        return 1 if getattr(self.optimizer, "momentum", 0.0) else 0

    # -- contributions ------------------------------------------------------
    def _contributions(self, grad_flats, padded, dtype):
        """The (total_devices, padded) contribution stack: row j carries
        the j-th local contribution (one per device slot), every other
        row is zeros — the reduce-scatter's sum is then EXACTLY the sum
        of contributions, in any reduction order (x + 0 is exact), which
        is what keeps ZeRO trajectories bit-identical to the replicated
        path when there is a single contribution."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = self._get_mesh()
        sharding = NamedSharding(mesh, P("dp", None))
        n_total = self.dp
        n_local = jax.local_device_count()
        grad_flats = list(grad_flats)
        if len(grad_flats) > n_total:
            # a plan with fewer zero shards than device slots (elastic
            # restore onto a smaller plan): fold the overflow
            # contributions into the first rows — the reduce-scatter
            # sums every row anyway, so the total is unchanged
            base, extra = grad_flats[:n_total], grad_flats[n_total:]
            for j, f in enumerate(extra):
                k = j % n_total
                base[k] = jnp.asarray(base[k], dtype) + \
                    jnp.asarray(f, dtype)
            grad_flats = base
        if jax.process_count() == 1:
            rows = [jnp.pad(jnp.asarray(f, dtype),
                            (0, padded - f.size)).reshape(1, padded)
                    for f in grad_flats[:n_total]]
            if len(rows) < n_total:
                rows.append(jnp.zeros((n_total - len(rows), padded),
                                      dtype))
            return jax.device_put(jnp.concatenate(rows), sharding)
        # multi-process: each process contributes its local block; row 0
        # of the block is this process's reduced gradient, the rest zeros
        block = _np.zeros((n_local, padded), dtype)
        for j, f in enumerate(grad_flats[:n_local]):
            block[j, :f.size] = _np.asarray(f)
        return jax.make_array_from_process_local_data(sharding, block)

    # -- state assembly / harvest -------------------------------------------
    def _assemble(self, state_key, bucket, opt_keys, padded, dtype):
        """Build the sharded state leaves for one bucket, re-flattening
        any carried per-parameter pieces (checkpoint restore at any dp,
        replan harvest, replicated-updater adoption) and zero-filling
        the rest."""
        from jax.sharding import PartitionSpec as P

        n_state = self._n_state()
        flats = [_np.zeros(padded, dtype) for _ in range(n_state)]
        for key, off, size, shape in zip(opt_keys, bucket.offsets,
                                         bucket.sizes, bucket.shapes):
            pieces = self._carry.pop(key, None)
            if pieces is None and self.adopt is not None:
                pieces = self.adopt(key)
            if pieces is None:
                continue
            if any(p is not None and _np.asarray(p).size != size
                   for p in pieces):
                # the parameter changed shape since this state was
                # harvested/saved (e.g. a checkpoint restored onto an
                # edited model): its old momentum is meaningless — reset
                # to zeros instead of crashing on the size mismatch
                continue
            for flat, piece in zip(flats, pieces):
                if piece is not None:
                    flat[off:off + size] = _np.asarray(
                        piece, dtype).reshape(-1)
        leaves = tuple(self._place(f, P("dp")) for f in flats)
        self._state[state_key] = {
            "leaves": leaves, "members": tuple(
                (k, off, size, tuple(shape))
                for k, off, size, shape in zip(
                    opt_keys, bucket.offsets, bucket.sizes,
                    bucket.shapes)),
            "size": bucket.size, "dtype": str(dtype)}
        self._record_hbm(state_key)
        return self._state[state_key]

    @staticmethod
    def _shard_label(state_key):
        tag = "-".join(str(p) for p in state_key[0]) if \
            isinstance(state_key[0], tuple) else str(state_key[0])
        return f"{tag}.b{state_key[1]}"

    def _record_hbm(self, state_key=None):
        total = 0
        for sk, entry in self._state.items():
            per_rank = sum(lv.nbytes for lv in entry["leaves"]) // self.dp
            total += per_rank
            if state_key is None or sk == state_key:
                _SHARD_BYTES.labels(bucket=self._shard_label(sk)).set(
                    per_rank // max(1, self._n_state() or 1))
        _STATE_BYTES.set(total)

    def _harvest_entry(self, entry):
        """Dissolve one bucket's sharded state back into per-parameter
        host pieces (``self._carry``): flat state is re-flattened member
        by member via the shard metadata, never reinterpreted in place.
        Reached uniformly on every process (replans are deterministic),
        so the multi-process gather inside fetch_global is SPMD-safe."""
        from .collectives import fetch_global

        fulls = [fetch_global(lv)[:entry["size"]]
                 for lv in entry["leaves"]]
        for key, off, size, shape in entry["members"]:
            self._carry[key] = tuple(
                full[off:off + size].reshape(shape) for full in fulls)

    def reshard(self, plan, budget_bytes=None):
        """Live plan-to-plan resharding of every resident sharded-state
        bucket: momentum/Adam moments move from the old plan's mesh to
        ``plan``'s through the :mod:`~mxnet_tpu.parallel.resharding`
        slice-move schedule (arXiv:2112.01075) — the in-flight
        alternative to the retire → host-harvest → re-assemble round
        trip (and, one level up, to the checkpoint disk round trip).
        State identity (generation keys, member layout, true sizes) is
        unchanged; only the flat padded leaves re-shard, so subsequent
        ``step_bucket`` calls under the new plan continue the exact
        trajectory a checkpoint restore would produce.

        Never tears state: the transfer builds NEW leaves and the swap
        happens only after the whole transfer succeeded (a
        ``resharding.transfer`` fault costs one supervised retry)."""
        from . import resharding as _resharding

        old_dp = self.dp
        old_plan, old_mesh = self._plan, self._mesh
        # the new dp derives from the plan — probe it, but COMMIT
        # nothing until the transfer succeeded (never-torn contract)
        self._plan = plan
        self._mesh = None
        new_dp = self.dp
        if not self._state:
            self._jits = {}
            self._record_hbm()
            return self
        arrays, buffers, layout = {}, [], []
        for sk, entry in self._state.items():
            label = self._shard_label(sk)
            dtype = entry["dtype"]
            for i, leaf in enumerate(entry["leaves"]):
                name = f"zero:{label}.s{i}"
                arrays[name] = leaf
                buffers.append((name, entry["size"], dtype))
            layout.append((sk, label, len(entry["leaves"])))
        tplan = _resharding.compute_flat_transfer_plan(buffers, old_dp,
                                                      new_dp)
        try:
            moved = _resharding.apply_transfer(tplan, arrays,
                                               budget_bytes=budget_bytes)
        except BaseException:
            # roll the layout metadata back: the old leaves were never
            # touched, so the engine keeps stepping under the old plan
            # (or the caller falls back to the checkpoint path)
            self._plan, self._mesh = old_plan, old_mesh
            raise
        for sk, label, n in layout:
            self._state[sk]["leaves"] = tuple(
                moved[f"zero:{label}.s{i}"] for i in range(n))
        # jitted step bodies bake the old mesh/shard size into their
        # shard_map: they can never be reused under the new plan
        self._jits = {}
        self._record_hbm()
        return self

    def retire(self, generation):
        """A replan retired ``generation``'s bucket compositions for
        good: harvest its shards to per-parameter pieces so momentum
        survives into the next plan's (different) shard layout.  Callers
        MUST retire the old generation before stepping a new one —
        state is generation-keyed and would otherwise leak."""
        for sk in [sk for sk in self._state if sk[0] == generation]:
            self._harvest_entry(self._state.pop(sk))
            # a retired shard is no longer resident: its labeled series
            # must read 0, not its last value forever
            _SHARD_BYTES.labels(bucket=self._shard_label(sk)).set(0)
        self._record_hbm()

    # -- the per-bucket step -----------------------------------------------
    def step_bucket(self, generation, bucket, grad_flats, weight_flat,
                    opt_keys=None):
        """Reduce-scatter ``grad_flats`` (one flat contribution per local
        device slot), apply this rank's shard of the optimizer update,
        and all-gather the updated flat weight.

        Returns the updated flat weight as a single-device array (the
        caller broadcasts it back into params/store like a pulled
        bucket).  ``generation`` is the caller's plan-generation tag
        (any hashable; see ``_state``) — sharded state is keyed on it,
        and the caller retires a stale generation via :meth:`retire`
        before stepping the replacing one.  ``opt_keys`` maps bucket
        members to optimizer indices (defaults to ``bucket.keys``)."""
        import math

        # the chaos seam: an injected transient here raises BEFORE any
        # optimizer/state mutation, so run_with_recovery's restart costs
        # exactly one step.  Never retried locally in multi-process
        # (PR 2: a unilateral re-issue desyncs SPMD collective counts).
        _fault.check("collectives.allreduce")
        opt = self.optimizer
        keys = list(bucket.keys) if opt_keys is None else list(opt_keys)
        dtype = _np.dtype(bucket.dtype)
        # one layout source: shard_layout(size, dp) with dp already
        # plan-derived via the ``dp`` property (ShardingPlan.
        # shard_layout is the same pure function for external callers)
        padded, shard, _pad = _bucketing.shard_layout(bucket.size,
                                                      self.dp)
        state_key = (generation, bucket.index)
        entry = self._state.get(state_key)
        if entry is None:
            entry = self._assemble(state_key, bucket, keys, padded, dtype)
        # hyperparameters: per-member update counts first (matches the
        # per-key updater's calling order), then lr/wd, vectorized only
        # when per-param multipliers actually differ
        for k in keys:
            opt._update_count(k)
        lrs = [opt._get_lr(k) for k in keys]
        wds = [opt._get_wd(k) for k in keys]
        if self._kind == "adam":
            lrs = [lr * math.sqrt(1.0 - opt.beta2 ** opt._index_update_count[k])
                   / (1.0 - opt.beta1 ** opt._index_update_count[k])
                   for lr, k in zip(lrs, keys)]
        lr_arg, vec_lr = self._hyper_arg(lrs, bucket, padded)
        wd_arg, vec_wd = self._hyper_arg(wds, bucket, padded)
        clip = opt.clip_gradient if (opt.clip_gradient or 0) > 0 else None
        rescale = opt.rescale_grad
        jitted = self._get_step(padded, dtype, clip, vec_lr, vec_wd)
        gstack = self._contributions(grad_flats, padded, dtype)
        wfull = self._pad_weight(weight_flat, padded, dtype)
        # the Python issue point of the shard_map-internal rs+ag pair:
        # ONE ledger entry per bucket-step, tag carrying the bucket
        # generation so a replay desync is blamable at the exact plan
        # (dispatch is async — see flight_recorder's exit-stamp note)
        with _flight.collective("zero_rs_ag", shape=(padded,),
                                dtype=dtype, axis="dp",
                                generation=f"{generation}/b{bucket.index}"):
            if self._kind == "adam":
                m, v = entry["leaves"]
                w_new, (m2, v2) = jitted(gstack, wfull, m, v, lr_arg,
                                         wd_arg, opt.beta1, opt.beta2,
                                         opt.epsilon, rescale)
                entry["leaves"] = (m2, v2)
            elif self._n_state():
                (mom,) = entry["leaves"]
                w_new, (mom2,) = jitted(gstack, wfull, mom, lr_arg,
                                        wd_arg,
                                        getattr(opt, "momentum", 0.0),
                                        rescale)
                entry["leaves"] = (mom2,)
            else:
                w_new, _ = jitted(gstack, wfull, lr_arg, wd_arg, rescale)
        nbytes = padded * dtype.itemsize
        _RS_BYTES.inc(nbytes)
        _AG_BYTES.inc(nbytes)
        _COLLECTIVES.inc(2)
        self._record_hbm(state_key)
        # the all-gathered output is replicated on every device; hand the
        # caller one addressable copy so params stay single-device values
        return w_new.addressable_data(0)

    def _hyper_arg(self, values, bucket, padded):
        """A scalar when every member shares the value, else a flat
        padded per-element vector sharded like the state."""
        from jax.sharding import PartitionSpec as P

        if len(set(values)) <= 1:
            return (values[0] if values else 0.0), False
        flat = _np.zeros(padded, _np.float32)
        for val, off, size in zip(values, bucket.offsets, bucket.sizes):
            flat[off:off + size] = val
        return self._place(flat, P("dp")), True

    def _pad_weight(self, weight_flat, padded, dtype):
        """The replicated (P()) flat weight input: padded to the
        dp-divisible size and placed over the WHOLE mesh — a
        single-device array cannot feed a jit whose other operands span
        all devices."""
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        w = jnp.asarray(weight_flat, dtype)
        if w.size != padded:
            w = jnp.pad(w, (0, padded - w.size))
        return self._place(w, P())

    # -- checkpoint payload -------------------------------------------------
    @property
    def has_state(self):
        return bool(self._state) or bool(self._carry)

    def state_payload(self):
        """Per-parameter host pieces of every resident shard — the
        checkpoint representation.  Re-flattened from the per-bucket
        shard metadata (member offsets), so a restore works onto ANY dp
        size or bucket plan: assembly happens lazily at the first
        step_bucket of each bucket."""
        from .collectives import fetch_global

        members = {}
        for key, pieces in self._carry.items():
            members[key] = tuple(None if p is None else _np.asarray(p)
                                 for p in pieces)
        for entry in self._state.values():
            fulls = [fetch_global(lv)[:entry["size"]]
                     for lv in entry["leaves"]]
            for key, off, size, shape in entry["members"]:
                members[key] = tuple(
                    full[off:off + size].reshape(shape).copy()
                    for full in fulls)
        return {"version": 1, "kind": self._kind, "members": members}

    def load_state_payload(self, payload):
        if payload.get("kind") != self._kind:
            raise MXNetError(
                f"ZeRO state payload is for a {payload.get('kind')!r} "
                f"optimizer, engine runs {self._kind!r}")
        for sk in self._state:
            _SHARD_BYTES.labels(bucket=self._shard_label(sk)).set(0)
        self._state.clear()
        self._carry = {k: tuple(v) for k, v in payload["members"].items()}
        self._record_hbm()


def updater_adopter(updater):
    """An ``ZeroBucketEngine.adopt`` hook pulling per-parameter state out
    of a replicated :class:`~mxnet_tpu.optimizer.optimizer.Updater` — a
    replicated checkpoint restored into ZeRO mode keeps its momentum
    (the state moves into the bucket shards and out of the updater)."""
    def _adopt(key):
        from ..kvstore import _flatten_state

        st = updater.states.pop(key, None)
        if st is None:
            return None
        updater.states_synced.pop(key, None)
        leaves, _ = _flatten_state(st)
        return tuple(None if lv is None else _np.asarray(lv._get())
                     for lv in leaves)
    return _adopt


def fold_into_updater(updater, payload):
    """Fold an engine checkpoint payload into a replicated
    :class:`~mxnet_tpu.optimizer.optimizer.Updater` — the one place that
    pokes the updater's state bookkeeping when a ZeRO checkpoint is
    restored with ``MXNET_ZERO`` off (Trainer and kvstore restore paths
    both call this)."""
    states = payload_to_states(payload)
    updater.states.update(states)
    for k in states:
        updater.states_synced[k] = True


def payload_to_states(payload):
    """Convert an engine checkpoint payload to replicated per-key
    optimizer state NDArrays (``Updater.states`` layout) — restoring a
    ZeRO checkpoint with ``MXNET_ZERO`` off keeps the momentum."""
    import jax.numpy as jnp

    from ..ndarray.ndarray import NDArray

    kind = payload.get("kind")
    out = {}
    for key, pieces in payload["members"].items():
        nds = [None if p is None else NDArray._from_jax(jnp.asarray(p))
               for p in pieces]
        if kind == "adam":
            out[key] = tuple(nds)
        elif len(nds) == 1:
            out[key] = nds[0]
        else:
            out[key] = None
    return out
