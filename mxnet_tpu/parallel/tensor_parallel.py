"""Megatron-style tensor parallelism as GSPMD sharding specs.

Capability upgrade over the reference (MXNet 1.x has no TP — SURVEY.md §3.3
parallelism statement): instead of hand-written column/row-parallel layers
with explicit allreduces (the Megatron-LM recipe), parameters get
``PartitionSpec`` annotations over the ``tp`` mesh axis and GSPMD inserts
the collectives — the sharding-annotation formulation of the same math
(PAPERS.md / scaling-book recipe):

- **column-parallel** (q/k/v, gate/up projections, lm_head): weight
  ``(out, in)`` sharded on the out dim → each device computes a head/
  intermediate slice, no communication on entry.
- **row-parallel** (o_proj, down_proj): weight sharded on the in dim →
  partial sums psum'd by GSPMD where the residual stream needs the total.
- embeddings shard the hidden dim; norms replicate.

Use with ``TrainStep(..., mesh=mesh, extra_param_specs=
tensor_parallel.megatron_specs(step_params, mesh))`` or standalone through
``specs_from_rules`` for custom architectures.
"""
from __future__ import annotations

import re
from collections import OrderedDict

from ..base import MXNetError

__all__ = ["specs_from_rules", "megatron_specs", "moe_expert_specs",
           "MEGATRON_RULES", "validate_specs"]


def _P():
    from jax.sharding import PartitionSpec

    return PartitionSpec


# (regex searched against the param name, spec template) — templates use the
# literal string "tp" where the tp axis goes (substituted with the actual
# axis name at build time); a template without "tp" pins the spec verbatim
# (e.g. (None,) force-replicates a matching param); position i applies to
# weight dim i
MEGATRON_RULES = (
    (r"(q_proj|k_proj|v_proj|gate_proj|up_proj|lm_head)_weight$",
     ("tp", None)),
    (r"(o_proj|down_proj)_weight$", (None, "tp")),
    (r"embed_tokens_weight$", (None, "tp")),
    # biases of column-parallel layers live on the sharded out dim
    (r"(q_proj|k_proj|v_proj|gate_proj|up_proj|lm_head)_bias$", ("tp",)),
)


def specs_from_rules(params, rules, mesh, axis="tp", default=None):
    """Build {name: PartitionSpec} from (regex, template) rules.

    ``params`` maps name -> array-like with ``.shape``.  A rule only
    applies when the sharded dim is divisible by the axis size; otherwise
    the param falls back to ``default`` (replicated) — a warning-free
    degrade matching GSPMD's requirement for even sharding."""
    P = _P()
    n = mesh.shape[axis]
    compiled = [(re.compile(pat), tpl) for pat, tpl in rules]
    specs = OrderedDict()
    for name, v in params.items():
        spec = default if default is not None else P()
        for pat, tpl in compiled:
            if pat.search(name):
                tpl_axes = tuple(axis if t == "tp" else t for t in tpl)
                if "tp" not in tpl:
                    # rule pins an explicit spec (e.g. force-replicate)
                    spec = P(*tpl_axes)
                else:
                    sdim = tpl.index("tp")
                    # exact-rank match: a 3-D stacked-expert weight must
                    # not be captured by the 2-D dense rule
                    if len(v.shape) == len(tpl) and v.shape[sdim] % n == 0:
                        spec = P(*tpl_axes)
                break
        specs[name] = spec
    return specs


def megatron_specs(params, mesh, axis="tp"):
    """Column/row-parallel specs for transformer params named with the
    q/k/v/o_proj, gate/up/down_proj, embed_tokens, lm_head convention
    (model_zoo.language models produce these names)."""
    if axis not in mesh.shape:
        raise MXNetError(f"mesh has no {axis!r} axis: {dict(mesh.shape)}")
    return specs_from_rules(params, MEGATRON_RULES, mesh, axis=axis)


def moe_expert_specs(params, mesh, axis="ep"):
    """Expert-parallel specs for stacked-expert MoE weights (leading
    expert axis, e.g. model_zoo.language LlamaMoEMLP's (E, H, I) tensors):
    shard the expert dim over ``axis``, replicate routers.  Merge on top
    of megatron_specs for combined tp+ep meshes."""
    if axis not in mesh.shape:
        raise MXNetError(f"mesh has no {axis!r} axis: {dict(mesh.shape)}")
    P = _P()
    n = mesh.shape[axis]
    specs = OrderedDict()
    for name, v in params.items():
        if re.search(r"(gate_proj|up_proj|down_proj)_weight$", name) \
                and len(v.shape) == 3 and v.shape[0] % n == 0:
            specs[name] = P(axis, None, None)
        elif re.search(r"router_weight$", name):
            specs[name] = P()
    return specs


def validate_specs(params, specs, mesh):
    """Check every spec divides its param evenly; raise with the offending
    names (GSPMD would otherwise fail deep inside compilation)."""
    bad = []
    for name, spec in specs.items():
        v = params.get(name)
        if v is None:
            continue
        for d, ax in enumerate(tuple(spec)):
            if ax is None:
                continue
            axes = ax if isinstance(ax, tuple) else (ax,)
            n = 1
            for a in axes:
                n *= mesh.shape[a]
            if d >= len(v.shape) or v.shape[d] % n != 0:
                bad.append((name, tuple(v.shape), tuple(spec)))
    if bad:
        raise MXNetError(f"indivisible tensor-parallel specs: {bad}")
    return True
