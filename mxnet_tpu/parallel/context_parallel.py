"""Ring attention: context/sequence parallelism over the device mesh.

Net-new vs the reference (MXNet 1.x had NO sequence/context parallelism —
SURVEY.md §6.7); required first-class by the TPU build: sequences longer
than one chip's HBM shard across the `sp` mesh axis, and K/V blocks rotate
around the ICI ring (`lax.ppermute`) while each device accumulates online
softmax — compute overlaps the ring transfer, the scaling-book recipe.

Use inside `shard_map` (``ring_attention``) or via the convenience wrapper
(``context_parallel_attention``) that builds the shard_map over a mesh.
"""
from __future__ import annotations

import functools
import math

__all__ = ["ring_attention", "context_parallel_attention",
           "ulysses_attention",
           "ulysses_context_parallel_attention"]


def ring_attention(q, k, v, axis_name="sp", causal=False, sm_scale=None):
    """Blockwise attention with K/V ring rotation.  Call INSIDE shard_map.

    q, k, v: (B, H, L_local, D) — the local sequence shard.  GQA: repeat kv
    heads before sharding.  Returns (B, H, L_local, D).
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    n = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    b, h, lloc, d = q.shape

    qf = q.astype(jnp.float32) * sm_scale
    q_pos = my * lloc + jnp.arange(lloc)

    perm = [(j, (j + 1) % n) for j in range(n)]

    def step(carry, i):
        m, l, acc, k_cur, v_cur = carry
        src = (my - i) % n                      # which shard this kv block is
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, k_cur.astype(jnp.float32))
        if causal:
            k_pos = src * lloc + jnp.arange(lloc)
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask[None, None], s, -1e30)
        m_new = jnp.maximum(m, s.max(axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])
        l_new = l * alpha + p.sum(axis=-1)
        acc_new = acc * alpha[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_cur.astype(jnp.float32))
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        return (m_new, l_new, acc_new, k_next, v_next), None

    m0 = jnp.full((b, h, lloc), -1e30, dtype=jnp.float32)
    l0 = jnp.zeros((b, h, lloc), dtype=jnp.float32)
    acc0 = jnp.zeros((b, h, lloc, d), dtype=jnp.float32)
    (m, l, acc, _, _), _ = lax.scan(step, (m0, l0, acc0, k, v),
                                    jnp.arange(n))
    l = jnp.maximum(l, 1e-30)
    return (acc / l[..., None]).astype(q.dtype)


def context_parallel_attention(q, k, v, mesh, axis_name="sp", causal=False,
                               sm_scale=None):
    """Full-sequence attention with the sequence axis sharded over
    ``axis_name``: q/k/v are unsharded (B,H,L,D) host-side arrays; the
    shard_map splits L, rings K/V, and regathers the output."""
    import jax
    from jax.sharding import PartitionSpec as P

    spec = P(None, None, axis_name, None)
    fn = functools.partial(ring_attention, axis_name=axis_name, causal=causal,
                           sm_scale=sm_scale)
    from .collectives import shard_map

    sharded = shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                        out_specs=spec)
    return sharded(q, k, v)


def ulysses_attention(q, k, v, axis_name="sp", causal=False, sm_scale=None):
    """DeepSpeed-Ulysses-style sequence parallelism: all_to_all reshards
    (heads-local, seq-full), full attention runs per head shard, a second
    all_to_all restores (heads-full, seq-local).  Call INSIDE shard_map.

    The complement of :func:`ring_attention` (PAPERS.md Ulysses): two
    all_to_alls over ICI instead of n ppermute hops — better when
    H >= n and the interconnect favors bulk all_to_all.  Requires the
    head count divisible by the sp axis size; GQA: repeat kv heads first.

    q, k, v: (B, H, L_local, D) — the local sequence shard.
    Returns (B, H, L_local, D).
    """
    from jax import lax

    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    n = lax.psum(1, axis_name)
    h = q.shape[1]
    if h % n:
        raise ValueError(f"ulysses_attention needs heads ({h}) divisible "
                         f"by the {axis_name!r} axis size ({n})")

    def to_seq(x):     # (B, H, L/n, D) -> (B, H/n, L, D)
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    def to_heads(x):   # (B, H/n, L, D) -> (B, H, L/n, D)
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    qs, ks, vs = to_seq(q), to_seq(k), to_seq(v)
    # the per-head-shard attention is the shared dense reference kernel
    # (one implementation to fix, same numerics as the flash fallback)
    from ..ops.flash_attention import _mha_reference

    o = _mha_reference(qs, ks, vs, causal, sm_scale)
    return to_heads(o)


def ulysses_context_parallel_attention(q, k, v, mesh, axis_name="sp",
                                       causal=False, sm_scale=None):
    """Full-sequence attention with the sequence axis sharded over
    ``axis_name`` via the Ulysses all_to_all schedule (the seq-sharded
    analog of :func:`context_parallel_attention`)."""
    import jax
    from jax.sharding import PartitionSpec as P

    spec = P(None, None, axis_name, None)
    fn = functools.partial(ulysses_attention, axis_name=axis_name,
                           causal=causal, sm_scale=sm_scale)
    from .collectives import shard_map

    sharded = shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                        out_specs=spec)
    return sharded(q, k, v)
