"""Multi-process bootstrap + sharded-optimizer update.

Reference mapping (SURVEY.md §3.3, §4.4): replaces the ps-lite
worker/server/scheduler triangle.

- ``init()`` ≙ ``Postoffice::Start`` rendezvous: reads the same env contract
  the reference launcher sets (``DMLC_PS_ROOT_URI/PORT``, ``DMLC_NUM_WORKER``,
  ``DMLC_WORKER_ID``) and calls ``jax.distributed.initialize`` so every
  process sees the global device mesh.
- ``ShardedOptimizerUpdater`` ≙ the server-side optimizer
  (``KVStoreDistServer::ApplyUpdates`` + key-range sharding): gradients are
  reduce-scattered over the mesh, each shard of the optimizer state lives on
  one device, and the updated weight is all-gathered — the "Automatic
  Cross-Replica Sharding of Weight Update" recipe (PAPERS.md), expressed as
  sharding annotations that GSPMD lowers to reduce-scatter + all-gather on
  ICI/DCN.
"""
from __future__ import annotations

import os
from functools import partial

import numpy as _np

from ..base import MXNetError

__all__ = ["init", "is_initialized", "ShardedOptimizerUpdater"]

_STATE = {"initialized": False}


def init(coordinator_address=None, num_processes=None, process_id=None,
         local_device_ids=None):
    """Initialize jax.distributed from args or the launcher env contract.

    Env fallbacks (reference: ps-lite bootstrap, tools/launch.py):
      MXNET_COORDINATOR_ADDRESS  or  DMLC_PS_ROOT_URI + DMLC_PS_ROOT_PORT
      MXNET_NUM_WORKERS          or  DMLC_NUM_WORKER
      MXNET_WORKER_ID            or  DMLC_WORKER_ID

    No-op (returns False) when the env describes a single-process job.
    """
    import jax

    if _STATE["initialized"]:
        return True
    if coordinator_address is None:
        coordinator_address = os.environ.get("MXNET_COORDINATOR_ADDRESS")
        if coordinator_address is None:
            uri = os.environ.get("DMLC_PS_ROOT_URI")
            port = os.environ.get("DMLC_PS_ROOT_PORT")
            if uri and port:
                coordinator_address = f"{uri}:{port}"
    if num_processes is None:
        num_processes = int(os.environ.get(
            "MXNET_NUM_WORKERS", os.environ.get("DMLC_NUM_WORKER", "1")))
    if process_id is None:
        process_id = int(os.environ.get(
            "MXNET_WORKER_ID", os.environ.get("DMLC_WORKER_ID", "0")))
    if num_processes <= 1 or coordinator_address is None:
        return False
    # preemptible jobs see transient coordinator errors (the scheduler
    # restarts every process of an SPMD job together, so peers race the
    # coordinator coming back): retry the rendezvous with bounded backoff
    # instead of failing the whole restart (MXNET_FAULT_MAX_RETRIES /
    # MXNET_FAULT_BACKOFF_MS; seam `distributed.init` for chaos tests)
    from .. import fault

    fault.call_with_retries(
        "distributed.init", jax.distributed.initialize,
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids)
    _STATE["initialized"] = True
    # multi-process jobs are the preemptible case: turn on the per-step
    # stop agreement (every peer must exit at the same step or the mesh
    # deadlocks in its next collective) and catch the scheduler's
    # SIGTERM so a preemption publishes a final checkpoint instead of
    # dying mid-step.  MXNET_LIFECYCLE_SIGNALS=0 opts out for embedders
    # that own their signal dispositions.
    from .. import env as _env
    from .. import lifecycle

    lifecycle.coordinate_stops(True)
    if _env.get_bool("MXNET_LIFECYCLE_SIGNALS", True):
        lifecycle.install_signal_handlers()
    return True


def is_initialized():
    return _STATE["initialized"]


# --------------------------------------------------------------------------
# sharded optimizer update (update_on_kvstore distributed semantics)
# --------------------------------------------------------------------------
_SUPPORTED = {"SGD": "sgd", "Adam": "adam"}


def supports_sharded_update(optimizer):
    return type(optimizer).__name__ in _SUPPORTED


class ShardedOptimizerUpdater:
    """Per-key reduce-scatter + sharded optimizer state + all-gather.

    The weight stays replicated on every process; the optimizer state
    (momentum / Adam moments) for each key is a flat padded array sharded
    over the full device mesh — each device owns exactly its shard of the
    update, which is what the reference's key-range-sharded servers do.
    """

    def __init__(self, optimizer):
        kind = _SUPPORTED.get(type(optimizer).__name__)
        if kind is None:
            raise MXNetError(
                f"sharded update unsupported for {type(optimizer).__name__}")
        self.optimizer = optimizer
        self._kind = kind
        self._state = {}   # key -> dict of flat sharded arrays
        self._jits = {}    # (shape, dtype) -> compiled step
        self._mesh = None

    # -- mesh / sharding helpers -------------------------------------------
    def _get_mesh(self):
        import jax
        from jax.sharding import Mesh

        if self._mesh is None:
            self._mesh = Mesh(_np.array(jax.devices()), ("w",))
        return self._mesh

    def _flat_spec(self, size):
        import jax

        n = len(jax.devices())
        pad = (-size) % n
        return pad

    def _put(self, host, sharding):
        """Place a host array with `sharding` without cross-host
        transfers (see collectives.place_global)."""
        from .collectives import place_global

        return place_global(host, sharding)

    # -- jit step ----------------------------------------------------------
    def _make_step(self, shape, dtype, clip):
        """clip (clip_gradient, or None) is static: it selects whether the
        clamp appears in the program, mirroring ops/optimizer_ops.py _prep."""
        import jax
        import jax.numpy as jnp
        from jax import lax
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = self._get_mesh()
        n_local = jax.local_device_count()
        size = int(_np.prod(shape)) if shape else 1
        pad = self._flat_spec(size)
        shard = NamedSharding(mesh, P("w"))
        repl = NamedSharding(mesh, P())
        kind = self._kind

        def to_shard(x):
            xf = jnp.pad(x.reshape(-1), (0, pad))
            return lax.with_sharding_constraint(xf, shard)

        def prep(gstack, wf, wd, rescale):
            # sum over the per-device contributions: feeding a sharded
            # consumer, GSPMD lowers this to a reduce-scatter.  Same
            # rescale -> clip -> +wd*w order as ops/optimizer_ops.py _prep.
            g = gstack.sum(axis=0) * (1.0 / n_local) * rescale
            gf = to_shard(g)
            if clip is not None:
                gf = jnp.clip(gf, -clip, clip)
            return gf + wd * wf

        if kind == "sgd":
            def step(w, gstack, mom, lr, wd, mu, rescale):
                wf = to_shard(w)
                gf = prep(gstack, wf, wd, rescale)
                # lr folds into the momentum buffer exactly like the dense
                # sgd_mom_update kernel, so lr schedules keep trajectories
                # identical to single-process training
                mom_new = mu * mom - lr * gf
                wf_new = wf + mom_new
                w_new = wf_new[:size].reshape(shape)  # replicated out ⇒ all-gather
                return w_new, (mom_new,)
        else:  # adam
            def step(w, gstack, m, v, lr_t, wd, b1, b2, eps, rescale):
                # lr_t carries the bias correction (frontend folds it, see
                # optimizer.Adam.update); eps sits outside the raw sqrt(v),
                # matching ops/optimizer_ops.py adam_update
                wf = to_shard(w)
                gf = prep(gstack, wf, wd, rescale)
                m_new = b1 * m + (1 - b1) * gf
                v_new = b2 * v + (1 - b2) * gf * gf
                wf_new = wf - lr_t * m_new / (jnp.sqrt(v_new) + eps)
                w_new = wf_new[:size].reshape(shape)
                return w_new, (m_new, v_new)

        n_state = 1 if kind == "sgd" else 2
        jitted = jax.jit(step, out_shardings=(repl, (shard,) * n_state))
        return jitted, pad, size

    def _init_state(self, key, shape, dtype):
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = self._get_mesh()
        size = int(_np.prod(shape)) if shape else 1
        pad = self._flat_spec(size)
        shard = NamedSharding(mesh, P("w"))
        zeros = _np.zeros(size + pad, dtype)
        if self._kind == "sgd":
            return (self._put(zeros, shard),)
        return (self._put(zeros, shard), self._put(zeros.copy(), shard))

    def _stack_contributions(self, g):
        """Build the global (num_global_devices, ...) contribution array:
        every local device carries this process's reduced gradient; the jit
        divides by local_device_count so the global sum equals the
        cross-process sum."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = self._get_mesh()
        n_local = jax.local_device_count()
        local = jnp.broadcast_to(g[None], (n_local,) + g.shape)
        if jax.process_count() == 1:
            return jax.device_put(local, NamedSharding(mesh, P("w")))
        return jax.make_array_from_process_local_data(
            NamedSharding(mesh, P("w")), _np.asarray(local))

    def _replicate_weight(self, w):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        repl = NamedSharding(self._get_mesh(), P())
        if isinstance(w, jax.Array) and w.sharding == repl:
            return w  # steady state: the previous jit output is already global
        if jax.process_count() == 1:
            return jax.device_put(w, repl)
        return self._put(_np.asarray(w), repl)

    # -- the updater interface (matches opt_mod.get_updater's calling seam) --
    def __call__(self, index, grad_nd, weight_nd):
        opt = self.optimizer
        key = index
        w = self._replicate_weight(weight_nd._get())
        g = grad_nd._get()
        shape, dtype = tuple(w.shape), w.dtype
        clip = opt.clip_gradient if (opt.clip_gradient or 0) > 0 else None
        sig = (key, shape, str(dtype), clip)
        if sig not in self._jits:
            self._jits[sig] = self._make_step(shape, dtype, clip)
        jitted, pad, size = self._jits[sig]
        if key not in self._state:
            self._state[key] = self._init_state(key, shape, dtype)
        opt._update_count(index)
        lr = opt._get_lr(index)
        wd = opt._get_wd(index)
        rescale = opt.rescale_grad
        gstack = self._stack_contributions(g)
        if self._kind == "sgd":
            (mom,) = self._state[key]
            w_new, (mom_new,) = jitted(w, gstack, mom, lr, wd,
                                       getattr(opt, "momentum", 0.0), rescale)
            self._state[key] = (mom_new,)
        else:
            import math

            t = opt._index_update_count[index]
            lr_t = lr * math.sqrt(1.0 - opt.beta2 ** t) / (1.0 - opt.beta1 ** t)
            m, v = self._state[key]
            w_new, (m2, v2) = jitted(w, gstack, m, v, lr_t, wd,
                                     opt.beta1, opt.beta2, opt.epsilon,
                                     rescale)
            self._state[key] = (m2, v2)
        weight_nd._set(w_new)

    # -- state io (Trainer.save_states compatibility) ----------------------
    def get_states(self, dump_optimizer=False):
        import pickle

        from .collectives import fetch_global

        # fetch_global, not np.asarray: the state leaves span the whole
        # mesh and a multi-process save must gather them to every host
        host = {k: tuple(fetch_global(s) for s in v)
                for k, v in self._state.items()}
        # version 2: sgd momentum buffer carries the lr-folded form
        # (mom' = mu*mom - lr*g); adam state is (m, v) with t in the
        # optimizer's update count
        payload = {"state": host, "kind": self._kind, "version": 2}
        if dump_optimizer:
            payload["optimizer"] = self.optimizer
        return pickle.dumps(payload)

    def set_states(self, blob):
        import pickle
        from jax.sharding import NamedSharding, PartitionSpec as P

        payload = pickle.loads(blob)
        if payload.get("version", 1) < 2 and \
                payload.get("kind", self._kind) == "sgd":
            raise MXNetError(
                "optimizer state blob predates the lr-folded sgd momentum "
                "layout and cannot be migrated (the fold depends on the lr "
                "at save time); re-save states with the current build")
        mesh = self._get_mesh()
        shard = NamedSharding(mesh, P("w"))
        restored = {}
        for k, states in payload["state"].items():
            if payload.get("kind", self._kind) == "adam" and len(states) == 3:
                # legacy blob layout (m, v, t): t now lives in the
                # optimizer's update count, keyed like the dense path
                m, v, t = states
                states = (m, v)
                self.optimizer._index_update_count[k] = int(_np.asarray(t))
            rs = []
            for s in states:
                arr = _np.asarray(s)
                rs.append(self._put(
                    arr, shard if arr.ndim else NamedSharding(mesh, P())))
            restored[k] = tuple(rs)
        self._state = restored
        if "optimizer" in payload:
            self.optimizer = payload["optimizer"]

    def adopt_dense_states(self, states):
        """Fold replicated per-key optimizer state (base ``Updater.states``
        layout, or ZeRO payload member pieces — numpy/NDArray leaves,
        single or tuple) into this updater's flat padded sharded layout.

        This is how a checkpoint written by a *different* updater shape —
        the ZeRO bucket engine (``MXNET_ZERO=1`` at save time) or a
        single-process replicated updater — restores onto the per-key
        sharded path: the momentum buffers carry the same lr-folded form
        on every path, so values transfer without migration."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        shard = NamedSharding(self._get_mesh(), P("w"))
        n_state = 1 if self._kind == "sgd" else 2
        for k, st in states.items():
            leaves = st if isinstance(st, (tuple, list)) else (st,)
            rs = []
            for s in leaves:
                if s is None:
                    continue
                arr = _np.asarray(s._get() if hasattr(s, "_get")
                                  else s).reshape(-1)
                arr = _np.pad(arr, (0, self._flat_spec(arr.size)))
                rs.append(self._put(arr, shard))
            if len(rs) == n_state:
                self._state[k] = tuple(rs)
