"""Functionalize a Gluon block into a pure (params, rng, *inputs) -> outputs fn.

The reference stages Gluon models through CachedOp (SURVEY.md §4.6); here the
same trace machinery (gluon.block._TraceContext) yields a *pure pytree
function* suitable for jax transforms: jit, grad, shard_map, pjit sharding.
This is the bridge between the imperative Gluon surface and the SPMD training
paths in parallel/ — the TPU-native equivalent of handing the NNVM graph to
the GraphExecutor.
"""
from __future__ import annotations

from collections import OrderedDict

__all__ = ["functionalize"]


def functionalize(net, train_mode=False, with_state=False):
    """Return ``(apply_fn, params)`` for an initialized Gluon block.

    ``params`` is an OrderedDict name -> jax.Array (the current values).
    ``apply_fn(params_dict, rng_key, *input_arrays)`` is pure and
    jax-traceable.

    with_state=False: running-state updates (BatchNorm moving stats) are
    dropped from the trace (XLA DCEs their computation).
    with_state=True: ``apply_fn`` returns ``(outputs, state_dict)`` where
    state_dict maps the state parameter's name to its new value — thread it
    back into ``params`` between steps to keep moving stats live (the
    functional analog of the reference's stateful FCompute).
    """
    from ..gluon.block import _TRACE, _TraceContext
    from ..gluon.parameter import DeferredInitializationError
    from ..ndarray.ndarray import NDArray
    from .. import autograd as _ag
    from .. import random as _rnd

    plist = [(name, p) for name, p in sorted(net.collect_params().items())]
    try:
        params = OrderedDict((name, p.data()._get()) for name, p in plist)
    except DeferredInitializationError as e:
        raise DeferredInitializationError(
            str(e) + " — run one eager forward (net(x)) before "
            "functionalize() so deferred shapes are resolved") from e
    param_objs = [p for _, p in plist]
    names = [name for name, _ in plist]
    name_of = {id(p): name for name, p in plist}

    def apply_fn(params_dict, rng_key, *input_vals):
        pmap = {}
        for name, pobj in zip(names, param_objs):
            pmap[pobj] = NDArray._from_jax(params_dict[name], None)
        tc = _TraceContext(pmap)
        prev = _TRACE.ctx
        _TRACE.ctx = tc
        _rnd._push_trace_key(rng_key)
        prev_rec = _ag.set_recording(False)
        prev_train = _ag.set_training(train_mode)
        try:
            nd_args = [NDArray._from_jax(v, None) for v in input_vals]
            out = net.forward(*nd_args)
        finally:
            _ag.set_training(prev_train)
            _ag.set_recording(prev_rec)
            _rnd._pop_trace_key()
            _TRACE.ctx = prev
        if isinstance(out, NDArray):
            out = out._get()
        elif isinstance(out, (list, tuple)):
            out = tuple(o._get() if isinstance(o, NDArray) else o for o in out)
        if not with_state:
            return out
        state = OrderedDict(
            (name_of[id(p)], v) for p, v in tc.state_updates if id(p) in name_of)
        return out, state

    return apply_fn, params
