"""Functionalize a Gluon block into a pure (params, rng, *inputs) -> outputs fn.

The reference stages Gluon models through CachedOp (SURVEY.md §4.6); here the
same trace machinery (gluon.block._TraceContext) yields a *pure pytree
function* suitable for jax transforms: jit, grad, shard_map, pjit sharding.
This is the bridge between the imperative Gluon surface and the SPMD training
paths in parallel/ — the TPU-native equivalent of handing the NNVM graph to
the GraphExecutor.
"""
from __future__ import annotations

from collections import OrderedDict

from ..base import MXNetError

__all__ = ["functionalize"]


def functionalize(net, train_mode=False, with_state=False):
    """Return ``(apply_fn, params)`` for an initialized Gluon block.

    ``params`` is an OrderedDict name -> jax.Array (the current values).
    ``apply_fn(params_dict, rng_key, *input_arrays)`` is pure and
    jax-traceable.

    with_state=False: running-state updates (BatchNorm moving stats) are
    dropped from the trace (XLA DCEs their computation).
    with_state=True: ``apply_fn`` returns ``(outputs, state_dict)`` where
    state_dict maps the state parameter's name to its new value — thread it
    back into ``params`` between steps to keep moving stats live (the
    functional analog of the reference's stateful FCompute).
    """
    from ..gluon.block import _TRACE, _TraceContext
    from ..gluon.parameter import DeferredInitializationError
    from ..ndarray.ndarray import NDArray
    from .. import autograd as _ag
    from .. import random as _rnd

    plist = [(name, p) for name, p in sorted(net.collect_params().items())]
    try:
        params = OrderedDict((name, p.data()._get()) for name, p in plist)
    except DeferredInitializationError as e:
        raise DeferredInitializationError(
            str(e) + " — run one eager forward (net(x)) before "
            "functionalize() so deferred shapes are resolved") from e
    param_objs = [p for _, p in plist]
    names = [name for name, _ in plist]
    name_of = {id(p): name for name, p in plist}

    def imperative_apply(params_dict, rng_key, *input_vals):
        pmap = {}
        for name, pobj in zip(names, param_objs):
            pmap[pobj] = NDArray._from_jax(params_dict[name], None)
        tc = _TraceContext(pmap)
        prev = _TRACE.ctx
        _TRACE.ctx = tc
        _rnd._push_trace_key(rng_key)
        prev_rec = _ag.set_recording(False)
        prev_train = _ag.set_training(train_mode)
        try:
            nd_args = [NDArray._from_jax(v, None) for v in input_vals]
            out = net.forward(*nd_args)
        finally:
            _ag.set_training(prev_train)
            _ag.set_recording(prev_rec)
            _rnd._pop_trace_key()
            _TRACE.ctx = prev
        if isinstance(out, NDArray):
            out = out._get()
        elif isinstance(out, (list, tuple)):
            out = tuple(o._get() if isinstance(o, NDArray) else o for o in out)
        if not with_state:
            return out
        state = OrderedDict(
            (name_of[id(p)], v) for p, v in tc.state_updates if id(p) in name_of)
        return out, state

    # graph-compiler tier (ISSUE 11): trace once per signature into the
    # typed graph IR, run the pass pipeline, and replay the OPTIMIZED
    # graph — TrainStep, pipeline_apply, and the serving export/AOT path
    # all lower this function, so they all run the optimized program.
    # Validation pins the graph replay's avals to the imperative trace's;
    # any mismatch (or an untraceable forward) falls back.
    graph_cache = {}

    def _graph_entry(params_dict, input_vals):
        import time as _time

        import jax

        from .. import graph as _graph
        from .. import telemetry as _telemetry
        from ..ndarray.ndarray import _AMP

        if not _graph.enabled():
            return None
        try:
            input_avals = [jax.ShapeDtypeStruct(tuple(v.shape), v.dtype)
                           for v in input_vals]
            param_avals = {n: jax.ShapeDtypeStruct(
                tuple(params_dict[n].shape), params_dict[n].dtype)
                for n in names}
        except Exception:
            return None
        sig = (tuple((tuple(a.shape), str(a.dtype)) for a in input_avals),
               tuple((tuple(param_avals[n].shape), str(param_avals[n].dtype))
                     for n in names),
               _AMP["epoch"] if _AMP["on"] else None,
               getattr(net, "_cache_version", 0))
        if sig in graph_cache:
            return graph_cache[sig]
        t0 = _time.perf_counter()
        entry = None
        try:
            g = _graph.trace_block(net, plist, input_avals,
                                   train_mode=train_mode)
            if not with_state:
                # the imperative path drops state updates from the trace
                # (XLA DCEs them); drop the heads so the DCE pass does too
                g = g.copy()
                g.state = []
            opt = _graph.default_pipeline().run(g)
            gfn = _graph.make_block_fn(opt)
            key_aval = jax.eval_shape(lambda: jax.random.PRNGKey(0))
            got = jax.eval_shape(
                gfn, [param_avals[n] for n in names], key_aval,
                *input_avals)
            ref = jax.eval_shape(imperative_apply, param_avals, key_aval,
                                 *input_avals)
            n_state = len(opt.state)
            got_out = list(got[:len(got) - n_state] if n_state else got)
            ref_out = ref[0] if with_state else ref
            ref_flat = jax.tree_util.tree_leaves(ref_out)
            if [(tuple(a.shape), str(a.dtype)) for a in got_out] != \
                    [(tuple(a.shape), str(a.dtype)) for a in ref_flat]:
                raise MXNetError("graph tier: output aval mismatch")
            if with_state:
                ref_state = ref[1]
                if sorted(ref_state) != sorted(n for n, _ in opt.state):
                    raise MXNetError("graph tier: state name mismatch")
            entry = (gfn, [n for n, _ in opt.state], opt.single)
        except Exception as e:
            _graph.record_fallback()
            _telemetry.compile_event(
                "graph", getattr(net, "name", type(net).__name__) or
                type(net).__name__,
                _time.perf_counter() - t0, "fallback",
                reason=repr(e)[:200])
        graph_cache[sig] = entry
        return entry

    def apply_fn(params_dict, rng_key, *input_vals):
        entry = _graph_entry(params_dict, input_vals)
        if entry is None:
            return imperative_apply(params_dict, rng_key, *input_vals)
        gfn, state_names, single = entry
        flat = gfn([params_dict[n] for n in names], rng_key, *input_vals)
        n_state = len(state_names)
        real = flat[:len(flat) - n_state] if n_state else flat
        out = real[0] if single else tuple(real)
        if not with_state:
            return out
        state_vals = flat[len(flat) - n_state:] if n_state else ()
        return out, OrderedDict(zip(state_names, state_vals))

    return apply_fn, params
