"""Sharded, jit-compiled training step over the device mesh.

This is the TPU-native successor of the reference's whole DP stack
(SURVEY.md §3.3: KVStoreLocal/CommDevice reduce + Trainer._allreduce_grads +
optimizer update ops): one XLA program computes forward, backward, gradient
reduction and the optimizer update, with collectives inserted by the
compiler from sharding annotations (GSPMD) instead of hand-written NCCL/
ps-lite calls (SURVEY.md §4.4 TPU mapping).

- batch sharded over ``dp`` (and ``fsdp``) → grads of replicated params
  become an automatic psum riding ICI;
- ``param_sharding='fsdp'`` shards parameters/optimizer state over the
  ``fsdp`` axis (ZeRO-style: all-gather on use, reduce-scatter on grads —
  cf. PAPERS.md "Automatic Cross-Replica Sharding of Weight Update");
- tensor-parallel specs from parallel.tensor_parallel compose with the same
  step; everything under one jit.
"""
from __future__ import annotations

from collections import OrderedDict
from functools import partial

from ..base import MXNetError
from .functional import functionalize

__all__ = ["TrainStep", "make_sgd_update", "make_adam_update",
           "replicated_specs", "fsdp_specs"]


def _jax():
    import jax

    return jax


# --------------------------------------------------------------------------
# pure optimizer updates (the jit-fused analog of src/operator/optimizer_op.cc)
# --------------------------------------------------------------------------
def make_sgd_update(lr=0.01, momentum=0.9, wd=0.0):
    import jax

    def init(params):
        return {"mom": jax.tree_util.tree_map(lambda p: p * 0.0, params)}

    def update(params, grads, state):
        def upd(p, g, m):
            g = g + wd * p
            m_new = momentum * m + g
            return p - lr * m_new, m_new

        out = jax.tree_util.tree_map(upd, params, grads, state["mom"])
        new_p = jax.tree_util.tree_map(lambda t: t[0], out,
                                       is_leaf=lambda t: isinstance(t, tuple))
        new_m = jax.tree_util.tree_map(lambda t: t[1], out,
                                       is_leaf=lambda t: isinstance(t, tuple))
        return new_p, {"mom": new_m}

    return init, update


def make_adam_update(lr=1e-3, beta1=0.9, beta2=0.999, eps=1e-8, wd=0.0):
    import jax
    import jax.numpy as jnp

    def init(params):
        z = jax.tree_util.tree_map(lambda p: p * 0.0, params)
        return {"m": z, "v": jax.tree_util.tree_map(lambda p: p * 0.0, params),
                "t": jnp.zeros((), "int32")}

    def update(params, grads, state):
        t = state["t"] + 1
        c1 = 1.0 - beta1 ** t.astype("float32")
        c2 = 1.0 - beta2 ** t.astype("float32")

        def upd(p, g, m, v):
            g = g + wd * p
            m_new = beta1 * m + (1 - beta1) * g
            v_new = beta2 * v + (1 - beta2) * g * g
            step = lr * (m_new / c1) / (jnp.sqrt(v_new / c2) + eps)
            return p - step.astype(p.dtype), m_new, v_new

        out = jax.tree_util.tree_map(upd, params, grads, state["m"], state["v"])
        pick = lambda i: jax.tree_util.tree_map(
            lambda t_: t_[i], out, is_leaf=lambda t_: isinstance(t_, tuple))
        return pick(0), {"m": pick(1), "v": pick(2), "t": t}

    return init, update


# --------------------------------------------------------------------------
# sharding spec builders — thin shims over the planner's rule engine
# (parallel/planner owns the heuristics; these keep the original API)
# --------------------------------------------------------------------------
def replicated_specs(params):
    from jax.sharding import PartitionSpec as P

    from .planner.rules import named_rule_set

    rs = named_rule_set("replicated")
    return OrderedDict((k, P(*rs.spec_for(k, getattr(v, "shape", ()),
                                          {})))
                       for k, v in params.items())


def fsdp_specs(params, mesh, axis="fsdp"):
    """Shard each parameter's first evenly-divisible dim over the fsdp
    axis (ZeRO-3 layout); replication for small/indivisible params.
    Delegates to the planner's shape heuristic — the planner must
    reproduce this hand-wired layout bit-identically, so there is
    exactly one implementation."""
    from jax.sharding import PartitionSpec as P

    from .planner.rules import RuleSet

    rs = RuleSet(heuristic_axis=axis, name="fsdp")
    sizes = dict(mesh.shape)
    return OrderedDict(
        (k, P(*rs.spec_for(k, v.shape, sizes)))
        for k, v in params.items())


class TrainStep:
    """One fused XLA training step for a Gluon net.

    Usage::

        step = TrainStep(net, loss_fn, optimizer='sgd',
                         optimizer_params={'learning_rate': 0.1},
                         mesh=mesh, param_sharding='fsdp')
        loss = step(x, y)          # x, y numpy/jax arrays (global batch)
        step.write_back()          # sync trained params into the Gluon net
    """

    def __init__(self, net, loss_fn, optimizer="sgd", optimizer_params=None,
                 mesh=None, param_sharding="replicated", extra_param_specs=None,
                 batch_axes=("dp", "fsdp"), donate=True, train_mode=True,
                 dtype=None, pipeline=None, remat=False, plan=None,
                 compile_cache=None):
        """``pipeline``: dict enabling pipeline parallelism over a mesh
        axis — {'num_microbatches': M, 'axis': 'pp', 'schedule':
        'gpipe'|'1f1b', 'remat_stage': bool}.  The net must implement
        ``pipeline_decompose(n_stages, train_mode)`` (the model zoo's
        LlamaForCausalLM does): heterogeneous embed/head ends run outside
        the pipe, the homogeneous trunk streams over pp, and dp/fsdp
        batch axes compose with it in the same jit.

        ``plan``: a :class:`~mxnet_tpu.parallel.planner.ShardingPlan` —
        the planner-native entry.  Supplies the mesh (built from the
        plan's axes when ``mesh`` is not also given), every parameter's
        PartitionSpec, the batch spec, and the pipeline in-jit-sharding
        flag; ``param_sharding`` is ignored (``extra_param_specs`` still
        applies last, as the per-call escape hatch).  Without ``plan``,
        the legacy string/dict modes are themselves routed through the
        planner (``ShardingPlan.from_specs``), so every sharded
        TrainStep now has exactly one audited layout object."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        if remat and pipeline is not None:
            raise MXNetError(
                "TrainStep(remat=True) does not compose with pipeline=; "
                "use pipeline={'remat_stage': True} for per-stage "
                "rematerialization inside the pipe")
        # plan-first resolution: the plan supplies mesh and batch axes
        # BEFORE the pipeline block filters them
        self._plan = plan
        if plan is not None:
            if mesh is None:
                mesh = plan.build_mesh()
            else:
                for ax, size in plan.axes.items():
                    if size != mesh.shape.get(ax, 1):
                        raise MXNetError(
                            f"plan axis {ax}={size} does not match the "
                            f"mesh ({dict(mesh.shape)}) — build the mesh "
                            "with plan.build_mesh() or re-plan")
            batch_axes = tuple(plan.batch_axes)
        self._net = net
        apply_fn, params = functionalize(net, train_mode=train_mode,
                                         with_state=train_mode)
        if remat:
            # whole-model rematerialization: backward recomputes the
            # forward instead of storing activations — the standard lever
            # for 2x batch (PERF_NOTES escalation step 2).  Models with
            # finer-grained remat (Llama's per-layer checkpoint) should
            # use their own option instead.
            base_apply = apply_fn

            def apply_fn(p, rng, *args):
                import jax as _jx

                return _jx.checkpoint(
                    lambda pp, aa: base_apply(pp, rng, *aa))(p, args)
        self._apply_fn = apply_fn
        self._with_state = train_mode
        self._pipeline = None
        if pipeline is not None:
            if mesh is None:
                raise MXNetError("pipeline parallelism needs a mesh")
            pp_axis = pipeline.get("axis", "pp")
            if pp_axis not in mesh.axis_names:
                raise MXNetError(f"mesh has no {pp_axis!r} axis")
            decomp = net.pipeline_decompose(mesh.shape[pp_axis],
                                            train_mode=train_mode)
            self._pipeline = {
                "M": int(pipeline["num_microbatches"]),
                "axis": pp_axis,
                "schedule": pipeline.get("schedule", "gpipe"),
                "remat_stage": bool(pipeline.get("remat_stage", False)),
                "decomp": decomp,
                "batch_axes": tuple(a for a in batch_axes
                                    if a in mesh.axis_names
                                    and a != pp_axis),
            }
        # split trainable vs frozen/state params (grad_req='null' covers
        # BatchNorm running stats and user-frozen params): gradients and
        # optimizer updates apply only to the trainable set
        grad_req = {name: p.grad_req
                    for name, p in net.collect_params().items()}
        self._train_names = [k for k in params if grad_req.get(k) != "null"]
        opt_params = dict(optimizer_params or {})
        if optimizer == "sgd":
            init, update = make_sgd_update(
                lr=opt_params.get("learning_rate", 0.01),
                momentum=opt_params.get("momentum", 0.0),
                wd=opt_params.get("wd", 0.0))
        elif optimizer == "adam":
            init, update = make_adam_update(
                lr=opt_params.get("learning_rate", 1e-3),
                beta1=opt_params.get("beta1", 0.9),
                beta2=opt_params.get("beta2", 0.999),
                eps=opt_params.get("epsilon", 1e-8),
                wd=opt_params.get("wd", 0.0))
        else:
            raise MXNetError(f"TrainStep optimizer {optimizer!r} not supported "
                             "(use 'sgd' or 'adam', or the imperative Trainer)")

        from . import planner as _planner

        self._mesh = mesh
        if mesh is not None:
            if plan is None:
                # legacy modes: resolve exactly as before, then wrap as
                # a plan — one audited layout object either way
                if param_sharding == "fsdp":
                    specs = fsdp_specs(params, mesh)
                elif param_sharding == "replicated":
                    specs = replicated_specs(params)
                elif isinstance(param_sharding, dict):
                    specs = OrderedDict(
                        (k, param_sharding.get(k, P())) for k in params)
                else:
                    raise MXNetError(
                        f"bad param_sharding {param_sharding!r}")
                plan = _planner.ShardingPlan.from_specs(
                    dict(mesh.shape), specs, batch_axes,
                    _planner.signature_of(params),
                    optimizer=("adam" if optimizer == "adam" else
                               ("sgd_momentum"
                                if opt_params.get("momentum") else "sgd")))
            missing = [k for k in params if k not in plan.specs]
            if missing and plan.specs:
                # a plan keyed on a DIFFERENT net instance's auto-names
                # would silently replicate everything — make it loud
                import warnings

                warnings.warn(
                    f"sharding plan covers none of/only part of this "
                    f"net's params ({len(missing)}/{len(params)} "
                    f"missing, e.g. {missing[0]!r}); missing params "
                    "replicate. Re-plan from THIS net's signature "
                    "(planner.signature_of) — gluon auto-name prefixes "
                    "differ between instances.", stacklevel=2)
            specs = plan.partition_specs(params.keys())
            if extra_param_specs:
                specs.update(extra_param_specs)
            self._plan = plan
            self._param_shard = OrderedDict(
                (k, NamedSharding(mesh, s)) for k, s in specs.items())
            self._batch_shard = NamedSharding(mesh, plan.batch_spec())
            # copy first: device_put returns the SAME buffer when the target
            # sharding already matches (1-device mesh, replicated params), and
            # jit donation below would then invalidate the Gluon net's own
            # parameter buffers
            params = OrderedDict(
                (k, jax.device_put(jnp.array(v, copy=True),
                                   self._param_shard[k]))
                for k, v in params.items())
        else:
            self._param_shard = None
            self._batch_shard = None
            # copy: jit donation below must not invalidate the jax buffers
            # the Gluon net's Parameters still reference
            params = OrderedDict((k, jnp.array(v, copy=True))
                                 for k, v in params.items())

        train_names = self._train_names
        self.train_params = OrderedDict((k, params[k]) for k in train_names)
        self.rest_params = OrderedDict(
            (k, v) for k, v in params.items() if k not in self.train_params)
        self.opt_state = init(self.train_params)
        if mesh is not None:
            self.opt_state = jax.tree_util.tree_map(
                lambda leaf: jax.device_put(leaf, NamedSharding(mesh, P()))
                if leaf.ndim == 0 else leaf, self.opt_state)

        with_state = self._with_state
        # mixed precision (AMP): trace the model under the bf16/fp16 cast
        # policy — master weights stay fp32, matmuls/convs run low-precision
        # on the MXU, loss is computed in fp32 (contrib.amp._cast_scope)
        if dtype is None:
            from contextlib import nullcontext

            amp_scope = nullcontext
        else:
            from ..contrib.amp import _cast_scope

            amp_scope = partial(_cast_scope, dtype)

        pipeline_cfg = self._pipeline
        mesh_ = mesh
        # planner flag: keep the jax-0.4.37 GSPMD replicated workaround
        # unless the plan (or MXNET_PLANNER_PIPELINE_IN_JIT) asks for
        # true in-jit P(pp) stage sharding (ROADMAP "re-test after jax
        # upgrade" is now a config flip, not a code hunt)
        pipe_in_jit = self._plan.pipeline_in_jit_sharding \
            if self._plan is not None else None

        def pipelined_forward(p, rng, x):
            from .pipeline_parallel import pipeline_apply, stack_stage_params

            d = pipeline_cfg["decomp"]
            S = mesh_.shape[pipeline_cfg["axis"]]
            L = len(d["layer_names"])
            per = L // S
            h = d["pre_fn"]({k: p[k] for k in d["pre_names"]}, rng, x)
            # leaves (S, per, ...): inner stack = layers within a stage,
            # outer stack = the stage-major axis pipeline_apply shards
            stage_trees = [
                stack_stage_params(
                    [{k0: p[d["layer_names"][li][k0]]
                      for k0 in d["layer0_names"]}
                     for li in range(si * per, (si + 1) * per)])
                for si in range(S)]
            stacked = stack_stage_params(stage_trees)

            def stage_fn(sp, h_mb):
                # fold stage + layer indices into the key so every trunk
                # layer draws DISTINCT dropout masks (a shared key would
                # correlate all layers).  The key must not depend on the
                # tick/microbatch: the 1F1B backward recomputes the stage
                # from the stashed input and has to reproduce the exact
                # forward masks.
                s_idx = jax.lax.axis_index(pipeline_cfg["axis"])
                s_rng = jax.random.fold_in(rng, s_idx)
                n_layers = jax.tree_util.tree_leaves(sp)[0].shape[0]

                def body(hh, pl_li):
                    pl, li = pl_li
                    return d["layer_fn"](
                        pl, jax.random.fold_in(s_rng, li), hh), None

                out, _ = jax.lax.scan(
                    body, h_mb, (sp, jnp.arange(n_layers)))
                return out

            h = pipeline_apply(
                stage_fn, stacked, h, mesh_, pipeline_cfg["M"],
                axis=pipeline_cfg["axis"],
                schedule=pipeline_cfg["schedule"],
                remat_stage=pipeline_cfg["remat_stage"],
                batch_axes=pipeline_cfg["batch_axes"],
                in_jit_sharding=pipe_in_jit)
            return d["post_fn"]({k: p[k] for k in d["post_names"]}, rng, h)

        def step(train_params, rest_params, opt_state, rng, x, y):
            def loss_of(tp):
                p = dict(rest_params)
                p.update(tp)
                with amp_scope():
                    if pipeline_cfg is not None:
                        out = pipelined_forward(p, rng, x)
                        state = {}
                    elif with_state:
                        out, state = apply_fn(p, rng, x)
                    else:
                        out = apply_fn(p, rng, x)
                        state = {}
                if dtype is not None:
                    out = jax.tree_util.tree_map(
                        lambda o: o.astype(jnp.float32)
                        if jnp.issubdtype(o.dtype, jnp.floating) else o, out)
                return jnp.mean(loss_fn(out, y)), state

            (loss, state), grads = jax.value_and_grad(
                loss_of, has_aux=True)(train_params)
            new_tp, new_opt = update(train_params, grads, opt_state)
            new_rest = dict(rest_params)
            for k, v in state.items():
                if k in new_rest:
                    new_rest[k] = v
            return loss, new_tp, new_rest, new_opt

        donate_argnums = (0, 1, 2) if donate else ()
        self._step = jax.jit(step, donate_argnums=donate_argnums)
        self._rng_seed = 0
        self.step_count = 0      # steps taken (lifecycle train_state)
        self._seen_sigs = set()  # telemetry: (x, y) avals already compiled
        # warm-start compile cache (mxnet_tpu/compile_cache.py): a
        # cached lowered executable for this exact signature skips the
        # trace entirely on resume — zero fresh traces, compile-tracer
        # visible only as a cache hit.  Static config the avals cannot
        # see rides the key: optimizer/pipeline/AMP config, the net's
        # structural repr (gluon reprs carry layer classes, units and
        # activations, so an architecture edit under unchanged param
        # shapes misses), and loss_fn's qualname.  Python BODY edits
        # under an unchanged structure/name are the one thing no key
        # component can see — bump MXNET_COMPILE_CACHE_SALT (README
        # "Elasticity" documents the invalidation matrix).
        from .. import compile_cache as _ccache

        self._cc = _ccache.resolve(compile_cache)
        self._cc_fns = {}        # batch sig -> cached callable | None
        self._cc_meta = {}       # batch sig -> cache-entry meta (flops)
        self._cc_pending = {}    # batch sig -> (key, avals) to store
        # per-signature AOT executables (lower().compile() on the cold
        # path): the compiled object is what steady state dispatches,
        # and its cost_analysis() FLOP count — captured ONCE here, at
        # compile time — feeds the online MFU gauge with zero
        # steady-state work (mxnet_tpu/introspection.py).  Each sig
        # keeps a small MRU list of (compiled, flops) variants: GSPMD
        # may hand the first step's outputs back in a different layout
        # than the plan placed, and the re-lower at the drifted-stable
        # layout is the same silent recompile jit dispatch performed
        # here before the AOT path existed
        self._compiled = {}      # batch sig -> [(compiled|None, flops)]
        pipe_key = None
        if self._pipeline is not None:
            pipe_key = (self._pipeline["M"], self._pipeline["axis"],
                        self._pipeline["schedule"],
                        self._pipeline["remat_stage"],
                        self._pipeline["batch_axes"])
        self._cc_extra = (
            optimizer, tuple(sorted(opt_params.items())), str(dtype),
            bool(remat), pipe_key, bool(train_mode), bool(donate),
            getattr(loss_fn, "__qualname__", None) or repr(loss_fn),
            " ".join(repr(net).split()),
            tuple(sorted((k, str(v)) for k, v in
                         (extra_param_specs or {}).items())))

    @property
    def params(self):
        merged = OrderedDict(self.rest_params)
        merged.update(self.train_params)
        return merged

    def _stage_batch(self, v):
        """Place one input on device under the step's batch sharding via
        the shared staging decision tree (``prefetcher.stage_leaf``): an
        array the prefetcher already put with the right sharding passes
        through untouched — the overlap path must add zero work here (and
        must NOT round-trip device arrays through numpy)."""
        v = getattr(v, "_get", lambda: v)()
        if self._batch_shard is None:
            return v
        from ..gluon.data.prefetcher import stage_leaf

        return stage_leaf(v, self._batch_shard)

    @staticmethod
    def _plain_tree(t):
        """Canonicalize mapping containers to plain dicts.  The step's
        state trees drift between OrderedDict and dict across calls
        (``step`` rebuilds ``rest_params`` with ``dict()``); jax.jit
        shrugs, but an exported artifact's calling convention is
        structure-STRICT — so the compile-cache path speaks plain dicts
        on both the export and every invocation.  Key-based flattening
        means the leaf mapping is unchanged."""
        if isinstance(t, dict):
            return {k: TrainStep._plain_tree(v) for k, v in t.items()}
        if isinstance(t, tuple):
            return tuple(TrainStep._plain_tree(v) for v in t)
        if isinstance(t, list):
            return [TrainStep._plain_tree(v) for v in t]
        return t

    def _cc_avals(self, rng, x, y):
        """ShapeDtypeStruct pytree mirroring one _step invocation's
        operands (shardings preserved — a resharded layout must key
        differently), canonicalized to plain-dict structure."""
        import jax

        def aval(v):
            return jax.ShapeDtypeStruct(
                tuple(v.shape), v.dtype,
                sharding=getattr(v, "sharding", None))

        return self._plain_tree((
            jax.tree_util.tree_map(aval, self.train_params),
            jax.tree_util.tree_map(aval, self.rest_params),
            jax.tree_util.tree_map(aval, self.opt_state),
            aval(rng),
            jax.ShapeDtypeStruct(tuple(x.shape), x.dtype,
                                 sharding=getattr(x, "sharding", None)),
            jax.ShapeDtypeStruct(tuple(y.shape), y.dtype,
                                 sharding=getattr(y, "sharding",
                                                  None))))

    def _cc_lookup(self, sig, rng, x, y):
        """Resolve the cached executable for one batch signature (once
        per sig): a hit replaces self._step for that sig; a miss
        schedules an export right after the first (tracing) call."""
        import jax.numpy as jnp

        from .. import compile_cache as _ccache

        x = x if hasattr(x, "shape") else jnp.asarray(x)
        y = y if hasattr(y, "shape") else jnp.asarray(y)
        avals = self._cc_avals(rng, x, y)
        key = self._cc.key(
            f"train_step:{type(self._net).__name__}",
            (_ccache.aval_signature(avals), self._cc_extra),
            plan_digest=self._plan.digest()
            if self._plan is not None else None)
        fn, meta = self._cc.load_executable_entry(key)
        self._cc_fns[sig] = fn
        self._cc_meta[sig] = meta
        if fn is None:
            self._cc_pending[sig] = (key, avals)
        return fn

    def __call__(self, x, y):
        from jax import random as jr

        x = self._stage_batch(x)
        y = self._stage_batch(y)
        rng = jr.PRNGKey(self._rng_seed)
        self._rng_seed += 1
        # telemetry compile tracer: an unseen batch signature means this
        # call traces+compiles the whole step before running it.  The set
        # is capped like dispatch_cache._COMPILE_SEEN — a variable-shape
        # workload must not leak memory proportional to distinct sigs
        # (past the cap fresh compiles simply go unrecorded)
        sig = (tuple(getattr(x, "shape", ())), str(getattr(x, "dtype", "")),
               tuple(getattr(y, "shape", ())), str(getattr(y, "dtype", "")))
        step_fn = self._step
        flops = None
        if self._cc is not None:
            cached = self._cc_fns[sig] if sig in self._cc_fns else \
                self._cc_lookup(sig, rng, x, y)
            if cached is not None:
                # warm start: no trace happens, so no compile event —
                # the cache-hit counter carries the observability and
                # the zero-fresh-trace assertion holds by construction.
                # The FLOP count rides the cache entry (stored with the
                # executable), so MFU accounting stays warm too.
                step_fn = cached
                self._seen_sigs.add(sig)
                flops = self._cc_meta.get(sig, {}).get("flops")
        fresh = sig not in self._seen_sigs and len(self._seen_sigs) < 4096
        if fresh:
            import time as _t

            self._seen_sigs.add(sig)
            t0 = _t.perf_counter()
        # plain-dict calling convention for EVERY dispatch (see
        # _plain_tree): the step's state trees drift OrderedDict→dict
        # across calls, and both the AOT executable and a cached
        # exported artifact are structure-strict; key-based flattening
        # keeps the leaf mapping identical either way
        args = (self._plain_tree(self.train_params),
                self._plain_tree(self.rest_params),
                self._plain_tree(self.opt_state), rng, x, y)
        if step_fn is self._step:
            # per-signature AOT: the cold path lowers + compiles ONCE
            # (capturing XLA's cost_analysis FLOPs while the executable
            # is in hand); steady state is one dict lookup + dispatch —
            # no retrace, no host sync, no new work
            out, flops = self._call_aot(sig, args)
        else:
            out = step_fn(*args)
        loss, self.train_params, self.rest_params, self.opt_state = out
        self.step_count += 1
        if flops:
            from .. import introspection as _introspection

            _introspection.account_flops(flops, kind="train_step")
        if fresh:
            from .. import telemetry as _telemetry

            _telemetry.compile_event(
                "train_step", type(self._net).__name__,
                _t.perf_counter() - t0,
                "new_step" if len(self._seen_sigs) == 1 else "new_shape")
            pending = self._cc_pending.pop(sig, None)
            if pending is not None:
                # cold path: persist the executable so the NEXT process
                # with this signature starts warm (the export re-traces
                # once — still the cold path, and our tracer already
                # recorded this signature's compile above).  The FLOP
                # count rides the entry so the warm process keeps its
                # MFU gauge without a compile to ask.
                key, avals = pending
                self._cc.store_executable(
                    key, self._step, *avals,
                    meta={"flops": flops} if flops else None)
        return loss

    def _aot_step(self, args):
        """Lower + compile one operand tuple ahead of time and capture
        its cost-analysis FLOPs.  Graceful fallback: when the AOT path
        is unavailable (platform quirk), the jit dispatch path serves
        the signature and the FLOP count — hence the MFU gauge — is
        simply absent, never wrong."""
        try:
            compiled = self._step.lower(*args).compile()
        except Exception as e:
            import warnings

            warnings.warn(
                f"TrainStep AOT compile unavailable ({e!r}); falling "
                "back to jit dispatch (no per-step FLOPs for this "
                "signature — MFU gauge unaffected, just unfed)",
                stacklevel=3)
            return (None, None)
        from .. import introspection as _introspection

        return (compiled, _introspection.flops_of(compiled))

    def _call_aot(self, sig, args):
        """Dispatch one step through the per-signature AOT executables;
        returns ``(outputs, flops)``.

        A compiled object is layout-STRICT: when GSPMD hands a step's
        outputs back in a different sharding than it was lowered with
        (observed on multi-axis meshes — the plan places ``P('tp',
        None)``, the executable returns ``P('fsdp')``), the next call
        raises ValueError.  jit dispatch used to absorb exactly this
        with a silent recompile; here ANY ValueError from a compiled
        variant falls through to a fresh re-lower at the current
        operand layout (the error message wording is not a stable API,
        so no substring matching) — a genuine error reproduces on the
        freshly-lowered executable and propagates from there, costing
        one extra compile, never masking.  The small MRU variant list
        keeps a ping-ponging layout from recompiling every step."""
        variants = self._compiled.setdefault(sig, [])
        if not variants:
            variants.append(self._aot_step(args))
        for i, (compiled, flops) in enumerate(variants):
            if compiled is None:
                # AOT unavailable for this signature: jit dispatch
                return self._step(*args), None
            try:
                out = compiled(*args)
            except ValueError:
                continue
            if i:
                variants.insert(0, variants.pop(i))
            return out, flops
        entry = self._aot_step(args)
        variants.insert(0, entry)
        del variants[4:]
        compiled, flops = entry
        if compiled is None:
            return self._step(*args), None
        return compiled(*args), flops

    def run(self, batches, steps=None, prefetch=None, guard=None):
        """Drive the fused step over an iterator of ``(x, y)`` batches with
        device prefetch: a background thread keeps the next
        ``MXNET_PREFETCH_BUFFER`` batches in flight (non-blocking
        ``device_put`` with this step's batch sharding), so host-side input
        staging overlaps the previous step's compute.  ``prefetch``
        overrides the depth (0 = serial staging).  Returns the per-step
        losses (device scalars — only the last is synced).

        ``guard`` (a :class:`mxnet_tpu.guard.Guard`, default = a fresh
        one when ``MXNET_GUARD=1``) polls the loss sentinel after every
        step: the fused jit commits its update before any verdict can
        land (donated buffers), so an anomalous verdict cannot skip —
        ``Guard.poll_loss`` escalates persistent anomalies straight to
        ``GuardRewind``, which ``run_with_recovery`` absorbs as a
        rewind-class restart from the latest valid checkpoint.  The
        poll feeds on the step's lazily-dispatched loss scalar: with the
        default sync stride it adds no trace and no extra collective
        beyond the one agreement the verdict needs.

        With ``steps=N`` the loop never pops past batch N, but the
        background pipeline has up to ``depth`` more batches staged which
        ``close()`` drops — callers chunking ONE shared iterator across
        several ``run`` calls should pass ``prefetch=0`` (or slice the
        batch list) so no batch is consumed and discarded.

        Preemption contract (:mod:`mxnet_tpu.lifecycle`): every step
        boundary polls ``lifecycle.check_stop()`` (agreed across SPMD
        peers, and it beats the stall-watchdog heartbeat); on a stop the
        loop returns the losses so far — the caller checks
        ``lifecycle.stop_requested()``, publishes its final checkpoint,
        and raises ``lifecycle.GracefulExit``."""
        from .. import flight_recorder as _flight
        from .. import guard as _guard_mod
        from .. import lifecycle as _lifecycle
        from ..gluon.data.prefetcher import PrefetchIterator

        if guard is None and _guard_mod.enabled():
            guard = _guard_mod.Guard()

        if prefetch is None:
            # resolve through the tuning funnel with THIS step's plan
            # digest, so a per-signature winner (bench.py --tune) can
            # steer the depth; env pin > winner > default, and the
            # iterator's own env fallback still guards a broken tier
            try:
                from .. import tuning as _tuning

                prefetch = int(_tuning.resolve(
                    "prefetch_buffer",
                    plan_digest=self._plan.digest()
                    if self._plan is not None else None))
            except Exception:
                prefetch = None
        it = PrefetchIterator(iter(batches), depth=prefetch,
                              sharding=self._batch_shard)
        losses = []
        try:
            try:
                while steps is None or len(losses) < steps:
                    if _lifecycle.check_stop():
                        break
                    try:
                        batch = next(it)
                    except StopIteration:
                        break
                    x, y = batch[0], batch[1]
                    losses.append(self(x, y))
                    if guard is not None:
                        guard.poll_loss(losses[-1], step=len(losses))
            finally:
                it.close()
            if losses:
                import numpy as _np

                # ONE deliberate end-of-run sync so step errors surface
                # inside run(), not at the caller's first read:
                # mxtpu: noqa[MXT010]
                _np.asarray(losses[-1])
        except _lifecycle.GracefulExit:
            raise          # clean preemption, not a crash — no black box
        except Exception:
            # unhandled failure in the training loop: dump this rank's
            # collective ledger (atomic, per-rank, never a collective)
            # so the cross-rank blame merge has a ring to align
            _flight.dump_blackbox("train_step_failure")
            raise
        return losses

    def write_back(self):
        """Copy trained parameter values back into the Gluon net."""
        merged = self.params
        for name, p in self._net.collect_params().items():
            if name in merged:
                p.data()._set(merged[name])
