"""Device-mesh construction for SPMD parallelism.

The reference's parallelism (SURVEY.md §3.3) is KVStore data-parallelism plus
manual device placement; the TPU build's idiomatic substrate is a named
``jax.sharding.Mesh`` over which every flavor (dp/fsdp/tp/pp/sp/ep) is a
PartitionSpec.  This module owns mesh creation and the session default mesh.
"""
from __future__ import annotations

import numpy as _np

from ..base import MXNetError

__all__ = ["make_mesh", "get_default_mesh", "set_default_mesh", "AXES"]

AXES = ("dp", "fsdp", "tp", "sp", "ep", "pp")

_DEFAULT = None


def make_mesh(dp=None, tp=1, sp=1, ep=1, pp=1, fsdp=1, devices=None):
    """Build a Mesh with named axes; dp absorbs the remaining devices.

    Example: 64 chips, tp=4 -> mesh ('dp','fsdp','tp','sp','ep','pp') =
    (16,1,4,1,1,1).  Axes of size 1 are kept so PartitionSpecs are stable
    across configurations.
    """
    import jax
    from jax.sharding import Mesh

    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    fixed = tp * sp * ep * pp * fsdp
    if n % fixed != 0:
        raise MXNetError(f"{n} devices not divisible by tp*sp*ep*pp*fsdp={fixed}")
    if dp is None:
        dp = n // fixed
    if dp * fixed != n:
        raise MXNetError(f"mesh {dp}x{fsdp}x{tp}x{sp}x{ep}x{pp} != {n} devices")
    arr = _np.array(devices).reshape(dp, fsdp, tp, sp, ep, pp)
    return Mesh(arr, AXES)


def set_default_mesh(mesh):
    global _DEFAULT
    _DEFAULT = mesh


def get_default_mesh():
    global _DEFAULT
    if _DEFAULT is None:
        _DEFAULT = make_mesh()
    return _DEFAULT
