"""Expert parallelism: a switch-style MoE layer over the ``ep`` mesh axis.

Capability upgrade over the reference (MXNet 1.x has no MoE).  TPU-native
formulation (Mesh-TF/Switch-Transformer style): routing is expressed as
dense one-hot dispatch/combine einsums — compiler-friendly static shapes —
with the expert dimension sharded over ``ep``; GSPMD turns the
token→expert regrouping einsums into all_to_all collectives riding ICI.

Top-1 (switch) routing with capacity dropping: tokens beyond an expert's
capacity pass through the residual (combine weight 0), the standard
overflow behavior.
"""
from __future__ import annotations

from ..base import MXNetError

__all__ = ["moe_apply", "stack_expert_params", "inject_aux_loss"]


def stack_expert_params(per_expert):
    """[expert0_tree, ...] -> tree with leading expert axis (sharded
    over ep by moe_apply)."""
    from .pipeline_parallel import stack_stage_params

    return stack_stage_params(per_expert)


def moe_apply(expert_fn, expert_params, router_weight, x, mesh=None,
              axis="ep", capacity_factor=1.25):
    """Top-1 MoE layer.

    expert_fn(params_one_expert, tokens (C, d)) -> (C, d)
    expert_params: pytree, leaves (E, ...); router_weight (d, E);
    x (T, d).  Returns (out (T, d), aux) where aux has the load-balancing
    loss (Switch-Transformer eq. 4) and per-expert load.
    """
    import jax
    import jax.numpy as jnp

    T, d = x.shape
    E = router_weight.shape[1]
    if mesh is not None and E % mesh.shape[axis]:
        raise MXNetError(f"num experts {E} not divisible by ep axis "
                         f"{mesh.shape[axis]}")
    C = max(1, int(capacity_factor * T / E))

    logits = x @ router_weight                       # (T, E)
    gates = jax.nn.softmax(logits, axis=-1)
    expert_idx = jnp.argmax(gates, axis=-1)          # (T,)
    gate = jnp.take_along_axis(gates, expert_idx[:, None], axis=1)[:, 0]
    sel = jax.nn.one_hot(expert_idx, E, dtype=x.dtype)   # (T, E)

    # position of each token within its expert's queue; >= C drops.
    # Counted in int32, NOT x.dtype: with bf16 activations integer counts
    # above 256 are unrepresentable and queue positions would collide,
    # silently merging/dropping tokens.
    sel_i = jax.nn.one_hot(expert_idx, E, dtype=jnp.int32)
    pos = jnp.cumsum(sel_i, axis=0) * sel_i - 1          # (T, E) int32
    keep = (pos >= 0) & (pos < C)
    dispatch = sel[:, :, None] * jax.nn.one_hot(
        jnp.clip(pos, 0, C - 1), C,
        dtype=x.dtype)                                   # (T, E, C)
    dispatch = dispatch * keep.astype(x.dtype)[:, :, None]
    combine = dispatch * gate[:, None, None]

    expert_in = jnp.einsum("tec,td->ecd", dispatch, x)   # (E, C, d)
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        expert_in = jax.lax.with_sharding_constraint(
            expert_in, NamedSharding(mesh, P(axis, None, None)))
        expert_params = jax.tree_util.tree_map(
            lambda leaf: jax.device_put(leaf, NamedSharding(
                mesh, P(axis, *([None] * (leaf.ndim - 1))))),
            expert_params)
    expert_out = jax.vmap(expert_fn)(expert_params, expert_in)  # (E, C, d)
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        expert_out = jax.lax.with_sharding_constraint(
            expert_out, NamedSharding(mesh, P(axis, None, None)))
    out = jnp.einsum("tec,ecd->td", combine, expert_out)

    # Switch load-balance loss: E * sum_e f_e * p_e.  Stats accumulate in
    # int32/fp32 — summing a bf16 one-hot over >256 tokens saturates.
    f = sel_i.astype(jnp.float32).mean(axis=0)            # fraction routed
    p = gates.astype(jnp.float32).mean(axis=0)            # mean router prob
    aux = {"load_balance_loss": E * jnp.sum(f * p),
           "expert_load": sel_i.sum(axis=0),
           "dropped": T - jnp.sum(keep.astype(jnp.int32))}
    return out, aux


def _make_inject():
    import jax

    @jax.custom_vjp
    def inject(x, aux_scalar):
        return x

    def fwd(x, aux_scalar):
        return x, None

    def bwd(_, g):
        import jax.numpy as jnp

        # the aux scalar receives cotangent 1 regardless of the
        # downstream reduction: it behaves exactly as if added to the
        # final scalar loss with coefficient 1 (the fairscale/DeepSeek
        # AddAuxiliaryLoss pattern)
        return g, jnp.ones((), g.dtype)

    inject.defvjp(fwd, bwd)
    return inject


_INJECT = None


def inject_aux_loss(x, aux_scalar):
    """Forward identity on ``x``; in backward, ``aux_scalar`` contributes
    its gradient as if summed into the final loss.  Lets a block deep in a
    network (e.g. an MoE router's load-balance term) add a loss term
    without threading it to the training loop."""
    global _INJECT
    if _INJECT is None:
        _INJECT = _make_inject()
    return _INJECT(x, aux_scalar)
