"""SPMD parallelism over the TPU mesh.

This package is the TPU-native replacement for the reference's entire
communication stack (SURVEY.md §3.3, §6.8) AND the capability upgrade the
north star requires (TP/FSDP/SP that MXNet 1.x never had):

- mesh:            named Mesh construction (dp/fsdp/tp/sp/ep/pp axes)
- collectives:     psum/all_gather/reduce_scatter/ppermute/all_to_all wrappers
- data_parallel:   jit-compiled sharded train step (≙ kvstore 'device' +
                   Trainer, fused into one XLA program); also provides
                   fsdp_specs (ZeRO-style sharding, cf. PAPERS.md
                   "Automatic Cross-Replica Sharding of Weight Update")
- tensor_parallel: Megatron-style column/row PartitionSpec rules
- distributed:     multi-process bootstrap + sharded-optimizer updater
- context_parallel: ring attention (ppermute) + Ulysses all_to_all
  sequence parallelism
- pipeline_parallel: GPipe schedule over the pp axis (weight-stationary
                   stages, ppermute activation passing, differentiable)
- expert_parallel: switch-MoE layer with GSPMD all_to_all over ep
- planner:         the sharding planner — logical-axis rules + HBM-model
                   mesh auto-selection → one ShardingPlan every sharded
                   consumer (TrainStep / pipeline / ZeRO / serving) reads
"""
from . import mesh
from . import collectives
from . import distributed
from . import tensor_parallel
from . import pipeline_parallel
from . import expert_parallel
from . import planner
from .mesh import make_mesh, get_default_mesh, set_default_mesh
from .context_parallel import (ring_attention,
                               context_parallel_attention,
                               ulysses_attention,
                               ulysses_context_parallel_attention)
from .pipeline_parallel import pipeline_apply, stack_stage_params
from .expert_parallel import moe_apply, stack_expert_params

__all__ = ["mesh", "collectives", "distributed", "tensor_parallel",
           "make_mesh", "get_default_mesh", "set_default_mesh",
           "ring_attention", "context_parallel_attention",
           "ulysses_attention", "ulysses_context_parallel_attention",
           "pipeline_parallel", "expert_parallel", "pipeline_apply",
           "stack_stage_params", "moe_apply", "stack_expert_params",
           "planner"]
