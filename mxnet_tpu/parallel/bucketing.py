"""Gradient bucketing: deterministic coalescing of many small allreduce
payloads into few size-capped fused ones.

Reference analog: the dist kvstore's bigarray split (SURVEY.md §4.4 —
``MXNET_KVSTORE_BIGARRAY_BOUND`` decides per-key vs server-sharded traffic)
and PyTorch DDP's gradient buckets (PAPERS.md): K per-parameter collectives
become ``ceil(total_bytes / cap)`` fused ones, so per-collective launch
latency stops dominating when parameters are small.

Determinism contract: bucket assignment is a **pure function of the ordered
``(key, shape, dtype)`` entry list and the byte cap** — no hashing, no
wall-clock, no dict iteration order.  Every process of an SPMD job walks its
parameters in the same construction order, therefore computes the *same*
buckets and issues the *same* collective sequence; the assignment doubles as
part of the collective contract the same way NCCL ring order does in the
reference.  Plans are computed once and cached against the entry-list
signature, so steady-state steps pay two tuple compares, not a re-plan.

Buckets are dtype-segregated (a flat buffer has one dtype; mixing would
silently upcast) and size-capped at ``MXNET_ALLREDUCE_BUCKET_MB`` (default
32 MiB; ``0`` disables fusion entirely).  A single value larger than the cap
gets its own bucket — it is already big enough to saturate the interconnect.
Row-sparse and host-promoted keys never enter a bucket (their payload is
rows, not a stable flat span); callers route them per-key and count them via
:func:`record_bypass`.
"""
from __future__ import annotations

import numpy as _np

from .. import env as _env
from .. import telemetry as _telemetry

__all__ = ["bucket_cap_bytes", "Bucket", "BucketPlan", "assign_buckets",
           "Bucketer", "pack", "unpack", "record_fused", "record_bypass",
           "shard_layout", "float_kind"]

_BUCKETS_TOTAL = _telemetry.counter(
    "mxnet_allreduce_buckets_total",
    "fused (bucketed) gradient collectives issued")
_BUCKET_BYTES = _telemetry.counter(
    "mxnet_allreduce_bucket_bytes_total",
    "flat-buffer bytes moved through fused collectives (counted once per "
    "bucket, never per member)")
_BUCKET_COUNT = _telemetry.gauge(
    "mxnet_allreduce_bucket_count",
    "buckets in the current (most recently planned) assignment")
_BYPASS_TOTAL = _telemetry.counter(
    "mxnet_allreduce_bucket_bypass_total",
    "values routed per-key around the buckets (sparse/host-promoted/"
    "oversized-disabled)")


def bucket_cap_bytes():
    """Fused-bucket size cap in bytes — resolved through the tuning
    funnel (``MXNET_ALLREDUCE_BUCKET_MB`` pin > ``MXNET_TUNE=1``
    stored winner > default 32 MiB; 0 disables fusion).  Import is
    lazy so the tuning tier stays optional on this hot-ish path; with
    tuning off the funnel is an env read, exactly what
    ``_env.allreduce_bucket_mb`` was."""
    try:
        from .. import tuning as _tuning

        return max(0, int(_tuning.resolve("allreduce_bucket_mb"))) << 20
    except Exception:
        return _env.allreduce_bucket_mb() << 20


class Bucket:
    """One flat-buffer assignment: members share a dtype; their ravel'd
    payloads occupy consecutive ``[offset, offset+size)`` spans."""

    __slots__ = ("index", "dtype", "keys", "shapes", "sizes", "offsets",
                 "nbytes")

    def __init__(self, index, dtype):
        self.index = index
        self.dtype = dtype
        self.keys = []
        self.shapes = []
        self.sizes = []
        self.offsets = []
        self.nbytes = 0

    def add(self, key, shape, size, nbytes):
        self.offsets.append(sum(self.sizes))
        self.keys.append(key)
        self.shapes.append(tuple(shape))
        self.sizes.append(int(size))
        self.nbytes += int(nbytes)

    @property
    def fused(self):
        """Whether packing actually coalesces anything (>1 member)."""
        return len(self.keys) > 1

    @property
    def size(self):
        """Total flat elements across members."""
        return sum(self.sizes)

    def __repr__(self):  # pragma: no cover - debugging aid
        return (f"Bucket(#{self.index} dtype={self.dtype} "
                f"n={len(self.keys)} bytes={self.nbytes})")


class BucketPlan:
    """Immutable assignment of an ordered entry list into buckets."""

    def __init__(self, signature, buckets):
        self.signature = signature
        self.buckets = buckets
        self.by_key = {}
        for b in buckets:
            for k, off, size in zip(b.keys, b.offsets, b.sizes):
                self.by_key[k] = (b.index, off, size)


def _entry_signature(entries, cap_bytes):
    return (int(cap_bytes),
            tuple((k, tuple(s), str(d)) for k, s, d in entries))


def assign_buckets(entries, cap_bytes=None):
    """Assign ordered ``(key, shape, dtype)`` entries to dtype-segregated,
    size-capped buckets.  Pure and deterministic: same entries + cap →
    identical plan, across processes and restarts."""
    if cap_bytes is None:
        cap_bytes = bucket_cap_bytes()
    buckets = []
    open_by_dtype = {}
    for key, shape, dtype in entries:
        dtype = str(dtype)
        size = int(_np.prod(shape)) if len(tuple(shape)) else 1
        nbytes = size * _np.dtype(dtype).itemsize
        if nbytes > cap_bytes:
            # already interconnect-saturating: dedicated bucket, and the
            # open one stays open for the next small value
            b = Bucket(len(buckets), dtype)
            buckets.append(b)
            b.add(key, shape, size, nbytes)
            continue
        b = open_by_dtype.get(dtype)
        if b is None or b.nbytes + nbytes > cap_bytes:
            b = Bucket(len(buckets), dtype)
            buckets.append(b)
            open_by_dtype[dtype] = b
        b.add(key, shape, size, nbytes)
    return BucketPlan(_entry_signature(entries, cap_bytes), buckets)


class Bucketer:
    """Plan cache: recomputes only when the entry signature (or cap)
    changes, so the steady-state step pays a tuple compare."""

    def __init__(self, cap_bytes=None):
        self._cap = cap_bytes
        self._plan = None
        # bumped on every replan; deterministic across SPMD processes
        # (replans are driven by the same model state on every peer).
        # Callers that derive kvstore keys / compression-residual keys
        # from a bucket MUST include this, so state keyed per bucket
        # (e.g. error-feedback residuals) never leaks across plans with
        # different bucket composition.
        self.generation = 0

    def plan_for(self, entries):
        cap = self._cap if self._cap is not None else bucket_cap_bytes()
        sig = _entry_signature(entries, cap)
        if self._plan is None or self._plan.signature != sig:
            self._plan = assign_buckets(entries, cap)
            self.generation += 1
            _BUCKET_COUNT.set(len(self._plan.buckets))
        return self._plan


def float_kind(dtype):
    """True for float-family dtypes — the buckets ZeRO can shard (an
    integer bucket has no meaningful optimizer update)."""
    return _np.dtype(dtype).kind == "f"


def shard_layout(size, dp):
    """ZeRO shard layout for a flat buffer of ``size`` elements over
    ``dp`` ranks: ``(padded_size, shard_size, pad)`` with ``padded_size``
    the smallest dp-divisible size ≥ ``size``.  Deterministic and pure —
    the reduce-scatter/all-gather pair and the persistent sharded
    optimizer state both key off this layout, so it must be identical on
    every peer (and is recomputed, never stored, so a checkpoint can be
    restored onto a different dp)."""
    dp = max(1, int(dp))
    pad = (-int(size)) % dp
    padded = int(size) + pad
    return padded, padded // dp, pad


def pack(values):
    """Concatenate jax/numpy arrays into one flat buffer (members of a
    bucket, in bucket order)."""
    import jax.numpy as jnp

    if len(values) == 1:
        return jnp.asarray(values[0]).ravel()
    return jnp.concatenate([jnp.asarray(v).ravel() for v in values])


def unpack(bucket, flat):
    """Slice a (reduced) flat buffer back into per-member arrays."""
    out = []
    for off, size, shape in zip(bucket.offsets, bucket.sizes, bucket.shapes):
        out.append(flat[off:off + size].reshape(shape))
    return out


def record_fused(nbytes):
    """Count one fused collective of ``nbytes`` flat-buffer bytes.  Called
    exactly once per bucket at the site that issues the collective — NOT
    per member — so byte telemetry never double-reports under bucketing."""
    _BUCKETS_TOTAL.inc()
    _BUCKET_BYTES.inc(nbytes)


def record_bypass(n=1):
    """Count values that skipped the buckets (sparse/host-promoted keys)."""
    _BYPASS_TOTAL.inc(n)
