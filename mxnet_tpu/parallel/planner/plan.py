"""The sharding plan: one audited object every sharding consumer reads.

``plan_sharding(config, signature, device_count)`` is a **pure
function** — no wall clock, no device queries, no dict-order hazards —
so every SPMD peer and every restart computes the identical plan from
the identical inputs (the same contract :mod:`..bucketing` establishes
for grad buckets).  The plan is JSON-serializable; its ``digest()`` is
the cross-process determinism fingerprint the CI smoke compares.

Consumers:

- :class:`~mxnet_tpu.parallel.data_parallel.TrainStep` — param specs,
  batch spec, mesh, the pipeline in-jit-sharding flag;
- :func:`~mxnet_tpu.parallel.pipeline_parallel.pipeline_apply` — stage
  specs + the GSPMD-workaround flag (``pipeline_in_jit_sharding``);
- :class:`~mxnet_tpu.parallel.zero.ZeroBucketEngine` — the shard count
  and flat-bucket layout of the sharded optimizer state;
- :class:`~mxnet_tpu.serving.engine.ServingEngine` /
  :func:`~mxnet_tpu.serving.artifact.load_artifact` — parameter
  shardings for the AOT-compiled prefill/decode executables.
"""
from __future__ import annotations

import json
from collections import OrderedDict

from ... import env as _env
from ... import telemetry as _telemetry
from ...base import MXNetError
from .. import bucketing as _bucketing
from . import hbm as _hbm
from . import rules as _rules

__all__ = ["PlannerConfig", "ShardingPlan", "plan_sharding",
           "signature_of", "plan_for", "set_default_plan",
           "get_default_plan", "report_from_snapshot"]

# the planner's mesh axes: the four auto-selection explores plus ep
# (expert parallelism — explicit-config only; MoE capacity factors are
# outside the HBM model, so auto never picks it)
_MESH_AXES = ("dp", "fsdp", "tp", "pp", "ep")

# telemetry families: the visualize_sharding report round-trips through
# snapshot() (the CI smoke asserts report_from_snapshot == plan.report())
_G_AXIS = _telemetry.gauge(
    "mxnet_planner_mesh_axis", "chosen mesh axis sizes of the published "
    "sharding plan", labelnames=("axis",))
_G_BYTES = _telemetry.gauge(
    "mxnet_planner_bytes_per_device", "HBM-model per-device byte "
    "estimate of the published plan", labelnames=("component",))
_G_PARAM = _telemetry.gauge(
    "mxnet_planner_param_bytes", "per-device bytes of one parameter "
    "under the published plan", labelnames=("param", "spec"))
_G_FEASIBLE = _telemetry.gauge(
    "mxnet_planner_feasible", "1 when the published plan fits the HBM "
    "budget (0 = over budget)")
_G_BUDGET = _telemetry.gauge(
    "mxnet_planner_budget_bytes", "per-device HBM budget the published "
    "plan was selected against")

_DEFAULT = None
# (param, spec) label tuples of the most recent publish() — removed
# before the next publish so the snapshot never carries stale rows
_PUBLISHED_PARAM_LABELS: set = set()


def set_default_plan(plan):
    """Install (or clear, with None) the session default plan — the one
    plan-unaware layers consult: the Trainer's ZeRO engine derives its
    shard count from it."""
    global _DEFAULT
    _DEFAULT = plan


def get_default_plan():
    return _DEFAULT


def _parse_mesh_str(s):
    """``"dp=4,tp=2"`` → axes dict; ``"auto"`` passes through."""
    s = (s or "").strip()
    if not s or s == "auto":
        return "auto"
    axes = {}
    for part in s.split(","):
        k, _, v = part.partition("=")
        k = k.strip()
        if k not in _MESH_AXES:
            raise MXNetError(f"bad mesh axis {k!r} in {s!r} "
                             f"(axes: {_MESH_AXES})")
        try:
            axes[k] = int(v)
        except ValueError:
            raise MXNetError(f"bad mesh size {v!r} in {s!r}") from None
    _check_axis_sizes(axes)
    return axes


def _check_axis_sizes(axes):
    for k, v in axes.items():
        if v < 1:
            raise MXNetError(
                f"bad mesh size {k}={v}: axis sizes must be >= 1")


class PlannerConfig:
    """Declarative planner input.  ``mesh``: ``'auto'``, an axes dict
    (missing axes default to 1; ``dp`` absorbs the remainder when
    absent), or None = the ``MXNET_PLANNER_MESH`` knob (default
    ``auto``).  ``rules``: a named rule set (``replicated`` / ``fsdp`` /
    ``megatron`` / ``megatron+fsdp``) or a :class:`rules.RuleSet`.
    ``overrides``: exact param name → logical template.  ``optimizer``:
    ``sgd`` / ``sgd_momentum`` / ``adam`` (HBM-model slots).  ``zero``:
    ZeRO-1 state sharding assumed (default: the ``MXNET_ZERO`` knob).
    ``hbm_gb``: per-device budget (default: ``MXNET_PLANNER_HBM_GB``).
    ``pipeline``: the model streams its trunk over pp — lets auto
    selection consider pp>1 and sizes the activation term by
    ``microbatches``.  ``pipeline_in_jit_sharding``: use P(pp) in_specs
    for traced stage params instead of the jax-0.4.37 GSPMD replicated
    workaround (default: ``MXNET_PLANNER_PIPELINE_IN_JIT``)."""

    def __init__(self, mesh=None, rules="replicated", overrides=None,
                 batch_axes=("dp", "fsdp"), optimizer="sgd", zero=None,
                 batch_rows=0, microbatches=1, hbm_gb=None,
                 pipeline=False, max_tp=None, max_fsdp=None,
                 pipeline_in_jit_sharding=None):
        if mesh is None:
            mesh = _parse_mesh_str(_env.planner_mesh())
        elif isinstance(mesh, str):
            mesh = _parse_mesh_str(mesh)
        else:
            mesh = {k: int(v) for k, v in mesh.items()}
            for k in mesh:
                if k not in _MESH_AXES:
                    raise MXNetError(f"bad mesh axis {k!r} "
                                     f"(axes: {_MESH_AXES})")
            _check_axis_sizes(mesh)
        self.mesh = mesh
        self.ruleset = rules if isinstance(rules, _rules.RuleSet) \
            else _rules.named_rule_set(rules)
        if overrides:
            self.ruleset = self.ruleset.with_overrides(overrides)
        self.batch_axes = tuple(batch_axes)
        self.optimizer = optimizer
        self.zero = _env.zero_enabled() if zero is None else bool(zero)
        self.batch_rows = int(batch_rows)
        self.microbatches = max(1, int(microbatches))
        self.hbm_gb = float(hbm_gb) if hbm_gb is not None \
            else _env.planner_hbm_gb()
        self.pipeline = bool(pipeline)
        self.max_tp = max_tp
        self.max_fsdp = max_fsdp
        self.pipeline_in_jit_sharding = (
            _env.planner_pipeline_in_jit()
            if pipeline_in_jit_sharding is None
            else bool(pipeline_in_jit_sharding))

    def key(self):
        mesh = self.mesh if isinstance(self.mesh, str) \
            else tuple(sorted(self.mesh.items()))
        return (mesh, self.ruleset.key(), self.batch_axes,
                self.optimizer, self.zero, self.batch_rows,
                self.microbatches, round(self.hbm_gb, 6), self.pipeline,
                self.max_tp, self.max_fsdp,
                self.pipeline_in_jit_sharding)


class ShardingPlan:
    """Immutable result of :func:`plan_sharding`."""

    def __init__(self, axes, specs, batch_axes, hbm_est, signature,
                 chosen_by, budget_bytes, candidates,
                 pipeline_in_jit_sharding):
        self.axes = OrderedDict((a, int(axes.get(a, 1)))
                                for a in _MESH_AXES)
        self.specs = OrderedDict(specs)
        # stored verbatim: batch_spec() must equal P(batch_axes) exactly
        # (bit-compat with the hand-wired TrainStep spec) — do NOT
        # filter size-1 axes here
        self.batch_axes = tuple(batch_axes)
        self.hbm = dict(hbm_est)
        self.signature = tuple(signature)
        self.chosen_by = chosen_by          # "auto" | "explicit"
        self.budget_bytes = int(budget_bytes)
        self.candidates = list(candidates)  # auto-selection audit trail
        self.pipeline_in_jit_sharding = bool(pipeline_in_jit_sharding)

    @classmethod
    def from_specs(cls, axes, specs, batch_axes, signature=(),
                   optimizer="sgd", zero=False,
                   pipeline_in_jit_sharding=None):
        """Wrap pre-resolved specs (legacy TrainStep string modes, an
        explicit param_sharding dict) as a plan, so every sharding
        consumer reads one object regardless of how the layout was
        decided.  Specs pass through untouched — bit-compat by
        construction."""
        signature = tuple(signature)
        norm = OrderedDict(
            (k, _rules.spec_tuple(v)) for k, v in specs.items())
        est = _hbm.estimate(signature, norm, axes, optimizer=optimizer,
                            zero=zero) if signature else \
            {"params": 0, "grads": 0, "optimizer": 0, "activations": 0,
             "total": 0, "zero_shards": 1, "data_parallel": 1}
        budget = int(_env.planner_hbm_gb() * (1 << 30))
        est["feasible"] = est["total"] <= budget
        return cls(axes, norm, batch_axes, est, signature, "explicit",
                   budget, [{"axes": dict(axes), "total": est["total"],
                             "feasible": est["feasible"]}],
                   _env.planner_pipeline_in_jit()
                   if pipeline_in_jit_sharding is None
                   else pipeline_in_jit_sharding)

    # -- consumption --------------------------------------------------------
    def device_count(self):
        n = 1
        for v in self.axes.values():
            n *= v
        return n

    def spec(self, name):
        """The ``PartitionSpec`` for one parameter (replicated when the
        plan has never seen the name — a late-added buffer must not
        crash the step)."""
        from jax.sharding import PartitionSpec

        return PartitionSpec(*self.specs.get(name, ()))

    def partition_specs(self, names=None):
        """OrderedDict name → PartitionSpec (optionally restricted to
        ``names``, in that order)."""
        keys = self.specs.keys() if names is None else names
        return OrderedDict((k, self.spec(k)) for k in keys)

    def sharding(self, name, mesh):
        """``NamedSharding`` for one parameter on ``mesh`` — the helper
        plan consumers outside ``mxnet_tpu/parallel/`` use instead of
        constructing shardings themselves (MXT060)."""
        from jax.sharding import NamedSharding

        return NamedSharding(mesh, self.spec(name))

    def replicated(self, mesh):
        """The replicated ``NamedSharding`` on ``mesh`` (for operands a
        plan consumer keeps whole: KV pools, dynamic serving inputs)."""
        from jax.sharding import NamedSharding, PartitionSpec

        return NamedSharding(mesh, PartitionSpec())

    def batch_spec(self):
        """Batch-dim spec — dim 0 over the data axes, exactly the
        ``P(batch_axes)`` TrainStep hand-wired (bit-compat)."""
        from jax.sharding import PartitionSpec

        return PartitionSpec(tuple(self.batch_axes))

    def build_mesh(self, devices=None):
        """The jax Mesh this plan was sized for (all six repo axes, the
        planned four carrying their chosen sizes).  A plan smaller than
        the live device count takes the leading devices — the elastic
        sub-mesh convention the ZeRO restore tests established."""
        from ..mesh import make_mesh

        if devices is None:
            import jax

            devices = jax.devices()[:self.device_count()]
        return make_mesh(dp=self.axes["dp"], fsdp=self.axes["fsdp"],
                         tp=self.axes["tp"], pp=self.axes["pp"],
                         ep=self.axes["ep"], devices=devices)

    @property
    def zero_shards(self):
        """Ranks the flat-bucket optimizer state shards over under
        ZeRO-1: the data-parallel replica count (dp×fsdp)."""
        return self.axes["dp"] * self.axes["fsdp"]

    def shard_layout(self, size):
        """ZeRO flat-bucket layout under this plan (pure, like
        :func:`bucketing.shard_layout`)."""
        return _bucketing.shard_layout(size, self.zero_shards)

    def transfer_plan_to(self, tgt_plan, signature=None,
                         zero_buckets=()):
        """The slice-move schedule from THIS plan's layout to
        ``tgt_plan``'s — the elastic-resize entry point
        (:func:`~mxnet_tpu.parallel.resharding.compute_transfer_plan`;
        pure and digest-stable like the plans themselves).  Defaults to
        this plan's own parameter signature.  MXT080 applies to the
        result: apply it or explicitly ``discard()`` it, at uniform
        SPMD level."""
        from .. import resharding as _resharding

        return _resharding.compute_transfer_plan(
            self, tgt_plan,
            self.signature if signature is None else signature,
            zero_buckets=zero_buckets)

    # -- identity / serialization ------------------------------------------
    def to_json(self):
        return json.dumps({
            "axes": dict(self.axes),
            "batch_axes": list(self.batch_axes),
            "specs": {k: [list(e) if isinstance(e, tuple) else e
                          for e in v] for k, v in self.specs.items()},
            "hbm": self.hbm,
            "chosen_by": self.chosen_by,
            "budget_bytes": self.budget_bytes,
            "pipeline_in_jit_sharding": self.pipeline_in_jit_sharding,
            "signature": [[n, list(s), str(d)]
                          for n, s, d in self.signature],
        }, sort_keys=True)

    def digest(self):
        """Stable fingerprint — equal across processes iff the plans are
        byte-identical (the CI determinism check)."""
        import hashlib

        return hashlib.sha256(self.to_json().encode()).hexdigest()

    # -- report -------------------------------------------------------------
    def report(self):
        """Structured ``visualize_sharding`` payload (what the telemetry
        gauges publish and :func:`report_from_snapshot` reconstructs)."""
        import numpy as _np

        rows = []
        for name, shape, dtype in self.signature:
            size = 1
            for s in shape:
                size *= int(s)
            nbytes = size * _np.dtype(dtype).itemsize
            spec = self.specs.get(name, ())
            f = _hbm._shard_factor(spec, self.axes)
            rows.append({"param": name, "spec": self._spec_str(spec),
                         "bytes_per_device": int(nbytes / f)})
        return {
            "axes": dict(self.axes),
            "chosen_by": self.chosen_by,
            "budget_bytes": int(self.budget_bytes),
            "feasible": bool(self.hbm["total"] <= self.budget_bytes),
            "components": {k: int(self.hbm[k]) for k in
                           ("params", "grads", "optimizer",
                            "activations", "total")},
            "params": rows,
        }

    @staticmethod
    def _spec_str(spec):
        if not spec:
            return "replicated"
        return "P(" + ", ".join(
            "None" if e is None else
            ("+".join(e) if isinstance(e, tuple) else str(e))
            for e in spec) + ")"

    def visualize_sharding(self):
        """Human-readable plan dump (T5X ``visualize_sharding`` style)."""
        rep = self.report()
        mesh = " ".join(f"{a}={n}" for a, n in self.axes.items()
                        if a in _MESH_AXES)
        lines = [f"sharding plan — mesh [{mesh}] "
                 f"({self.device_count()} devices, {self.chosen_by})"]
        w = max([len(r["param"]) for r in rep["params"]] + [5])
        ws = max([len(r["spec"]) for r in rep["params"]] + [4])
        lines.append(f"{'param':<{w}}  {'spec':<{ws}}  bytes/device")
        for r in rep["params"]:
            lines.append(f"{r['param']:<{w}}  {r['spec']:<{ws}}  "
                         f"{_fmt_bytes(r['bytes_per_device'])}")
        c = rep["components"]
        lines.append(
            "per-device: params %s · grads %s · optimizer %s · "
            "activations/µbatch %s · total %s (budget %s) %s" % (
                _fmt_bytes(c["params"]), _fmt_bytes(c["grads"]),
                _fmt_bytes(c["optimizer"]), _fmt_bytes(c["activations"]),
                _fmt_bytes(c["total"]), _fmt_bytes(rep["budget_bytes"]),
                "FEASIBLE" if rep["feasible"] else "OVER BUDGET"))
        return "\n".join(lines)

    def publish(self):
        """Write the report into the telemetry registry (labeled gauges)
        so it rides ``telemetry.snapshot()`` / the Prometheus endpoint;
        the snapshot round-trips via :func:`report_from_snapshot`.
        Re-publishing (a new plan, a different net) first removes the
        previous publish's per-param rows — stale series would break the
        round trip and serve dead numbers (the zero.py labeled-gauge
        retire discipline)."""
        global _PUBLISHED_PARAM_LABELS
        rep = self.report()
        new_labels = {(r["param"], r["spec"]) for r in rep["params"]}
        for param, spec in _PUBLISHED_PARAM_LABELS - new_labels:
            _G_PARAM.remove(param=param, spec=spec)
        _PUBLISHED_PARAM_LABELS = new_labels
        for a, n in rep["axes"].items():
            _G_AXIS.labels(axis=a).set(n)
        for comp, v in rep["components"].items():
            _G_BYTES.labels(component=comp).set(v)
        for r in rep["params"]:
            _G_PARAM.labels(param=r["param"], spec=r["spec"]).set(
                r["bytes_per_device"])
        _G_FEASIBLE.set(1 if rep["feasible"] else 0)
        _G_BUDGET.set(rep["budget_bytes"])
        return rep


def _fmt_bytes(n):
    for unit in ("B", "KiB", "MiB", "GiB"):
        if n < 1024 or unit == "GiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024.0


def report_from_snapshot(snap):
    """Reconstruct the published plan report from a
    ``telemetry.snapshot()`` payload (None when no plan was published).
    The round trip ``report_from_snapshot(snapshot()) ==
    plan.report()`` is asserted by ``ci/planner_smoke.py``."""
    metrics = snap.get("metrics", {})
    axis_fam = metrics.get("mxnet_planner_mesh_axis")
    if not axis_fam or not axis_fam.get("samples"):
        return None
    axes = {s["labels"]["axis"]: int(s["value"])
            for s in axis_fam["samples"]}
    comps = {s["labels"]["component"]: int(s["value"])
             for s in metrics.get("mxnet_planner_bytes_per_device",
                                  {}).get("samples", [])}
    rows = [{"param": s["labels"]["param"], "spec": s["labels"]["spec"],
             "bytes_per_device": int(s["value"])}
            for s in metrics.get("mxnet_planner_param_bytes",
                                 {}).get("samples", [])]
    feas = metrics.get("mxnet_planner_feasible", {}).get("samples", [])
    budget = metrics.get("mxnet_planner_budget_bytes",
                         {}).get("samples", [])
    return {
        "axes": axes,
        "components": comps,
        "params": rows,
        "feasible": bool(feas and feas[0]["value"]),
        "budget_bytes": int(budget[0]["value"]) if budget else 0,
    }


# --------------------------------------------------------------------------
# planning
# --------------------------------------------------------------------------
def signature_of(params):
    """Ordered ``(name, shape, dtype)`` signature from a params mapping
    (values: anything with ``.shape``/``.dtype``), a Gluon net
    (``collect_params`` order), or an existing signature."""
    if hasattr(params, "collect_params"):
        from ..functional import functionalize

        _, tree = functionalize(params)
        params = tree
    if isinstance(params, (list, tuple)):
        return tuple((str(n), tuple(int(x) for x in s), str(d))
                     for n, s, d in params)
    return tuple((str(k), tuple(int(x) for x in v.shape),
                  str(getattr(v, "dtype", "float32")))
                 for k, v in params.items())


def plan_sharding(config, signature, device_count):
    """config × parameter signature × device count → ShardingPlan.

    Pure and deterministic: identical inputs produce plans with
    identical :meth:`ShardingPlan.digest` on every process."""
    signature = tuple(signature)
    n = int(device_count)
    if n < 1:
        raise MXNetError(f"device_count must be >= 1, got {n}")
    budget = int(config.hbm_gb * (1 << 30))
    rs = config.ruleset
    if config.mesh == "auto":
        axes, est, trail = _hbm.choose_mesh(
            signature, rs, n, budget_bytes=budget,
            optimizer=config.optimizer, zero=config.zero,
            batch_rows=config.batch_rows,
            microbatches=config.microbatches,
            allow_pp=config.pipeline, max_tp=config.max_tp,
            max_fsdp=config.max_fsdp)
        chosen_by = "auto"
    else:
        axes = dict(config.mesh)
        fixed = 1
        for a in _MESH_AXES:
            if a != "dp":
                fixed *= axes.get(a, 1)
        if "dp" not in axes:
            if n % fixed:
                raise MXNetError(f"{n} devices not divisible by "
                                 f"fsdp*tp*pp={fixed}")
            axes["dp"] = n // fixed
        total = axes["dp"] * fixed
        if total > n:
            raise MXNetError(f"mesh {axes} covers {total} devices, "
                             f"only {n} available")
        # total < n is the elastic sub-mesh convention: the plan takes
        # the leading devices (build_mesh slices; the ZeRO elastic
        # restore tests drive exactly this)
        est = _hbm.estimate(signature, rs, axes,
                            optimizer=config.optimizer, zero=config.zero,
                            batch_rows=config.batch_rows,
                            microbatches=config.microbatches)
        est["feasible"] = est["total"] <= budget
        trail = [{"axes": dict(axes), "total": est["total"],
                  "feasible": est["feasible"]}]
        chosen_by = "explicit"
    specs = _rules.resolve_specs(rs, signature, axes)
    plan = ShardingPlan(axes, specs, config.batch_axes, est, signature,
                        chosen_by, budget, trail,
                        config.pipeline_in_jit_sharding)
    if _env.planner_report():
        print(plan.visualize_sharding())
    return plan


def plan_for(net_or_params, config=None, devices=None):
    """Convenience wrapper: plan for a Gluon net / params mapping on the
    live device count (or an explicit ``devices`` int/list)."""
    import jax

    if config is None:
        config = PlannerConfig()
    if devices is None:
        n = len(jax.devices())
    elif isinstance(devices, int):
        n = devices
    else:
        n = len(devices)
    return plan_sharding(config, signature_of(net_or_params), n)
