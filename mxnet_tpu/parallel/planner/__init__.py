"""Sharding planner: declarative mesh config + logical-axis rules →
per-parameter PartitionSpecs, with HBM-model mesh auto-selection.

The one audited place sharding decisions are made (ROADMAP "a real
partitioner"; SNIPPETS.md [2]/[3] T5X ``Partitioner`` shape).  Before
this subsystem, layout intent was hand-wired across TrainStep,
``pipeline_apply``, per-model code, the ZeRO engine and the serving AOT
signatures; now each of those *consumes* a :class:`ShardingPlan`:

    cfg  = planner.PlannerConfig(mesh="auto", rules="megatron+fsdp",
                                 optimizer="adam", batch_rows=512,
                                 hbm_gb=16)
    plan = planner.plan_for(net, cfg)          # pure + deterministic
    print(plan.visualize_sharding())           # per-param table + HBM
    step = TrainStep(net, loss_fn, plan=plan)  # specs + mesh + batch
    eng  = ServingEngine(net, plan=plan)       # sharded AOT executables

Plans are pure functions of (config, parameter signature, device
count): every SPMD peer and every restart computes the same plan
(``plan.digest()`` is compared across processes in CI), and with rules
equivalent to a hand-wired layout the resulting specs are identical —
trajectories do not move by a bit.

Knobs: ``MXNET_PLANNER_MESH``, ``MXNET_PLANNER_HBM_GB``,
``MXNET_PLANNER_PIPELINE_IN_JIT``, ``MXNET_PLANNER_REPORT`` (env.py).
"""
from . import hbm
from . import rules
from .hbm import choose_mesh, enumerate_meshes, estimate
from .plan import (PlannerConfig, ShardingPlan, get_default_plan,
                   plan_for, plan_sharding, report_from_snapshot,
                   set_default_plan, signature_of)
from .rules import LLAMA_LOGICAL_RULES, MEGATRON_BINDING, RuleSet, \
    named_rule_set

__all__ = ["PlannerConfig", "ShardingPlan", "plan_sharding", "plan_for",
           "signature_of", "set_default_plan", "get_default_plan",
           "report_from_snapshot", "RuleSet", "named_rule_set",
           "LLAMA_LOGICAL_RULES", "MEGATRON_BINDING", "estimate",
           "enumerate_meshes", "choose_mesh", "rules", "hbm"]
