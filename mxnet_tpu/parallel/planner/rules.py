"""Logical-axis rule engine: parameter names/shapes → PartitionSpecs.

The T5X shape (SNIPPETS.md [2]/[3]): sharding intent is declared twice,
once per *parameter family* (a name-regex rule assigns each weight dim a
**logical axis** — ``model``, ``embed``, ``vocab``, ``expert`` …) and
once per *deployment* (a **binding** maps logical axes to mesh axes —
``model → tp``, ``expert → ep`` …).  The same model rules therefore
serve every mesh: flip the binding and a column-parallel weight moves
from tp to replicated without touching model code.

Resolution order for one parameter (first hit wins):

1. **override** — exact-name entry in ``RuleSet.overrides`` (the escape
   hatch for the one weird tensor);
2. **name rule** — first ``(regex, template)`` whose pattern ``search``es
   the name.  A template whose logical axes all bind to ``None`` *pins*
   the spec verbatim (force-replicate), matching any rank; a template
   with bound axes applies only at the exact rank and when every bound
   dim divides evenly (GSPMD's requirement) — otherwise the parameter
   falls through replicated, the same warning-free degrade
   :func:`tensor_parallel.specs_from_rules` ships;
3. **shape heuristic** — when the spec is still fully replicated and the
   rule set names a ``heuristic_axis`` (the FSDP case, where intent is
   "shard *something*", not a specific dim): the first dim divisible by
   (and at least as large as) the axis size is sharded.  This reproduces
   :func:`data_parallel.fsdp_specs` bit-for-bit — the planner replacing
   the hand-wired layouts must not move a single byte.

Everything here is pure: specs come out as plain tuples of
axis-name-or-None (hashable, picklable, JSON-able) and are converted to
``jax.sharding.PartitionSpec`` only at the plan boundary — rule
evaluation itself never imports jax.
"""
from __future__ import annotations

import re
from collections import OrderedDict

from ...base import MXNetError

__all__ = ["RuleSet", "LLAMA_LOGICAL_RULES", "MEGATRON_BINDING",
           "named_rule_set", "resolve_specs", "spec_tuple"]

# Logical-axis rules for the model-zoo transformer naming convention
# (llama/bert produce `q_proj_weight`-style global names; serving params
# use `q_proj.weight` block paths — the separator class covers both).
# Dim order follows the weights: Dense stores (out, in).
LLAMA_LOGICAL_RULES = (
    # column-parallel: out dim carries heads/intermediate ("model")
    (r"(q_proj|k_proj|v_proj|gate_proj|up_proj|lm_head)[._]weight$",
     ("model", "embed")),
    # row-parallel: in dim carries the model-parallel partial sums
    (r"(o_proj|down_proj)[._]weight$", ("embed", "model")),
    # token embedding (vocab, hidden): shard the hidden dim
    (r"embed_tokens[._]weight$", ("vocab", "model")),
    # biases of column-parallel layers live on the sharded out dim
    (r"(q_proj|k_proj|v_proj|gate_proj|up_proj|lm_head)[._]bias$",
     ("model",)),
    # stacked-expert MoE weights (E, ...): shard the expert dim
    (r"(gate_proj|up_proj|down_proj)[._]weight$",
     ("expert", None, None)),
    (r"router[._]weight$", (None, None)),      # pinned: routers replicate
    # norms/scales replicate (pinned, any rank)
    (r"(norm|layernorm|ln)[0-9_.]*[._](weight|gamma|beta|bias)$",
     (None,)),
)

# the Megatron deployment of those rules: the model dim goes to tp,
# everything else replicates — exactly tensor_parallel.MEGATRON_RULES
MEGATRON_BINDING = {"model": "tp", "embed": None, "vocab": None,
                    "expert": "ep"}


class RuleSet:
    """One deployment's sharding policy: name rules + logical→mesh
    binding + optional shape heuristic + per-param overrides.

    ``rules``: ordered ``(regex, template)`` pairs; template entries are
    logical-axis names (strings) or ``None``.  ``binding``: logical name
    → mesh axis name (or None = replicate that logical axis).  A logical
    name absent from the binding binds to None.  ``heuristic_axis``:
    mesh axis for the first-divisible-dim fallback (FSDP), or None.
    ``overrides``: exact param name → template (same binding applies).
    """

    def __init__(self, rules=(), binding=None, heuristic_axis=None,
                 overrides=None, name="custom"):
        self.rules = tuple((pat, tuple(tpl)) for pat, tpl in rules)
        self.binding = dict(binding or {})
        self.heuristic_axis = heuristic_axis
        self.overrides = dict(overrides or {})
        self.name = name
        self._compiled = [(re.compile(pat), tpl)
                          for pat, tpl in self.rules]

    def key(self):
        """Hashable identity — part of the plan's determinism contract."""
        return (self.name, self.rules,
                tuple(sorted(self.binding.items(),
                             key=lambda kv: kv[0])),
                self.heuristic_axis,
                tuple(sorted((k, tuple(v))
                             for k, v in self.overrides.items())))

    def with_overrides(self, overrides):
        merged = dict(self.overrides)
        merged.update(overrides or {})
        return RuleSet(self.rules, self.binding, self.heuristic_axis,
                       merged, name=self.name)

    # -- resolution ---------------------------------------------------------
    def _apply_template(self, tpl, shape, axis_sizes):
        """Bound spec for one template at one shape, or None when the
        template does not apply here (rank mismatch / indivisible /
        every bound axis degenerated to size 1)."""
        bound = tuple(None if t is None else self.binding.get(t)
                      for t in tpl)
        if all(a is None for a in bound):
            # pinned replicate: applies at any rank (the force-replicate
            # semantics of tensor_parallel's no-"tp" templates) and is
            # FINAL — the heuristic never reshards a pinned parameter.
            # () is the canonical replicated form (== PartitionSpec()),
            # matching what the hand-wired builders emit
            return ()
        if len(tpl) != len(shape):
            return None       # exact-rank match only (3-D MoE vs 2-D)
        out = []
        for d, a in enumerate(bound):
            n = axis_sizes.get(a, 1) if a is not None else 1
            if n <= 1:
                # a bound axis of size 1 shards nothing: drop it so the
                # heuristic below can still claim the parameter (a
                # megatron+fsdp plan at tp=1 degrades to pure fsdp)
                out.append(None)
                continue
            if shape[d] % n != 0 or shape[d] < n:
                return None   # indivisible: warning-free replicated fall
            out.append(a)
        if all(a is None for a in out):
            return None       # vacuous at this mesh: fall through
        return tuple(out)

    def spec_for(self, name, shape, axis_sizes):
        """The spec tuple for one parameter under ``axis_sizes``
        (mesh axis name → size).  Pure; deterministic; first template
        that *applies* wins (overrides before rules)."""
        shape = tuple(int(s) for s in shape)
        tpl = self.overrides.get(name)
        if tpl is not None:
            out = self._apply_template(tuple(tpl), shape, axis_sizes)
            if out is not None:
                return out
        for pat, rtpl in self._compiled:
            if pat.search(name):
                out = self._apply_template(rtpl, shape, axis_sizes)
                if out is not None:
                    return out
        ax = self.heuristic_axis
        n = axis_sizes.get(ax, 1) if ax else 1
        if ax and n > 1:
            # fsdp_specs bit-compat: FIRST dim divisible by and >= n,
            # emitted in fsdp_specs' own trimmed form (no trailing Nones)
            for d, size in enumerate(shape):
                if size % n == 0 and size >= n:
                    return tuple([None] * d + [ax])
        return ()


# the named deployments `PlannerConfig(rules=...)` accepts
_NAMED = {
    "replicated": lambda: RuleSet(name="replicated"),
    "fsdp": lambda: RuleSet(heuristic_axis="fsdp", name="fsdp"),
    "megatron": lambda: RuleSet(LLAMA_LOGICAL_RULES, MEGATRON_BINDING,
                                name="megatron"),
    "megatron+fsdp": lambda: RuleSet(LLAMA_LOGICAL_RULES,
                                     MEGATRON_BINDING,
                                     heuristic_axis="fsdp",
                                     name="megatron+fsdp"),
}


def named_rule_set(name):
    """Look up a predefined rule set (``replicated`` / ``fsdp`` /
    ``megatron`` / ``megatron+fsdp``)."""
    try:
        return _NAMED[name]()
    except KeyError:
        raise MXNetError(
            f"unknown planner rule set {name!r} "
            f"(known: {sorted(_NAMED)})") from None


def resolve_specs(ruleset, signature, axis_sizes):
    """Spec tuples for an ordered ``(name, shape, dtype)`` signature."""
    return OrderedDict(
        (name, ruleset.spec_for(name, shape, axis_sizes))
        for name, shape, _dtype in signature)


def stage_spec(ndim, axis="pp"):
    """The structural spec of a stacked pipeline-stage leaf: leading
    stage dim over the pp axis, everything else replicated.  Stage
    params are positional (stacked trees), so this is the one spec the
    name-rule engine cannot express — ``pipeline_apply`` reads it from
    here so stage sharding intent still lives in the planner."""
    return tuple([axis] + [None] * (int(ndim) - 1))


def spec_tuple(spec):
    """Normalize a PartitionSpec-or-tuple to the planner's plain-tuple
    form (sub-tuples kept for multi-axis dims)."""
    out = []
    for a in tuple(spec):
        out.append(tuple(a) if isinstance(a, (list, tuple)) else a)
    return tuple(out)
