"""HBM cost model + automatic mesh selection.

Grounded in the weight-update-sharding accounting of arXiv:2004.13336
(PAPERS.md): per-device HBM at a training step is

    params/dev + grads/dev + optimizer-state/dev + activations/microbatch

where params and grads shard over the *model* axes a parameter's spec
names (``fsdp``/``tp``/``pp``…), optimizer state additionally shards
1/dp under ZeRO-1 (the PR 7 flat-bucket layout,
:func:`bucketing.shard_layout`), and activations scale with the
per-device microbatch.  The activation term is deliberately a coarse,
*documented* model — one output tensor of ``(microbatch_rows,
out_features)`` per ≥2-D weight, out dim sharded as the weight's dim 0
is — because the planner must stay a pure function of the parameter
signature (no tracing); ``bench.py extra.planner`` measures
estimated-vs-actual so the error stays visible.

Mesh auto-selection (``mesh='auto'``) enumerates every divisor
factorization ``dp×fsdp×tp×pp == device_count`` in strict preference
order — maximize dp first (data parallelism needs no model cooperation),
then fsdp (shards memory without changing math), then tp (needs logical
rules), then pp (needs ``pipeline_decompose`` support, so it only enters
the search when the config asks for a pipeline) — and picks the FIRST
candidate whose estimate fits the per-device budget
(``MXNET_PLANNER_HBM_GB``).  Enumeration order is a pure function of
the device count, so every SPMD peer and every restart selects the same
mesh.
"""
from __future__ import annotations

from ...base import MXNetError

__all__ = ["OPTIMIZER_SLOTS", "estimate", "enumerate_meshes",
           "choose_mesh"]

# optimizer-state slots per parameter element (fp32 each), mirroring
# parallel/zero.py's supported set
OPTIMIZER_SLOTS = {"sgd": 0, "sgd_momentum": 1, "adam": 2}

_GiB = float(1 << 30)


def _shard_factor(spec, axis_sizes):
    n = 1
    for entry in spec:
        axes = entry if isinstance(entry, tuple) else (entry,)
        for a in axes:
            if a is not None:
                n *= int(axis_sizes.get(a, 1))
    return n


def estimate(signature, ruleset, axis_sizes, *, optimizer="sgd",
             zero=False, batch_rows=0, microbatches=1, training=True,
             itemsize=4):
    """Per-device HBM estimate (bytes) for one candidate mesh.

    ``signature``: ordered ``(name, shape, dtype)``; ``axis_sizes``:
    mesh axis name → size; ``batch_rows``: GLOBAL batch rows (divided by
    the data axes and ``microbatches`` for the activation term).
    Returns a dict with the per-component and total byte counts plus the
    resolved dp/zero factors — everything the report prints.
    """
    import numpy as _np

    slots = OPTIMIZER_SLOTS.get(optimizer)
    if slots is None:
        raise MXNetError(f"unknown optimizer kind {optimizer!r} for the "
                         f"HBM model (known: {sorted(OPTIMIZER_SLOTS)})")
    if hasattr(ruleset, "spec_for"):
        spec_of = ruleset.spec_for
    else:
        # pre-resolved specs (a hand-built plan): name -> spec tuple
        resolved = dict(ruleset)
        spec_of = lambda name, shape, sizes: \
            tuple(resolved.get(name, ()))  # noqa: E731
    data_par = int(axis_sizes.get("dp", 1)) * int(axis_sizes.get("fsdp", 1))
    zero_shards = max(1, data_par) if zero else 1
    p_bytes = g_bytes = o_bytes = a_bytes = 0
    mb_rows = 0
    if batch_rows:
        denom = max(1, data_par) * max(1, int(microbatches))
        mb_rows = max(1, -(-int(batch_rows) // denom))
    for name, shape, dtype in signature:
        shape = tuple(int(s) for s in shape)
        size = 1
        for s in shape:
            size *= s
        isz = _np.dtype(dtype).itemsize
        spec = spec_of(name, shape, axis_sizes)
        f = _shard_factor(spec, axis_sizes)
        per_dev = (size * isz) / f
        p_bytes += per_dev
        if training:
            g_bytes += per_dev
            # fp32 optimizer slots.  State is sharded EITHER like the
            # param (GSPMD/fsdp specs) OR 1/(dp*fsdp) by ZeRO's flat
            # buckets — the two mechanisms do not compose, so take the
            # larger factor, never the product (dividing by both would
            # claim more shards than data ranks exist and steer auto
            # selection toward an OOM mesh)
            o_bytes += slots * (size * 4) / max(f, zero_shards)
        if mb_rows and len(shape) >= 2:
            out_f = shape[0]
            out_shard = spec[0] if spec else None
            a_bytes += (mb_rows * out_f * itemsize) \
                / _shard_factor((out_shard,), axis_sizes)
    total = p_bytes + g_bytes + o_bytes + a_bytes
    return {"params": int(p_bytes), "grads": int(g_bytes),
            "optimizer": int(o_bytes), "activations": int(a_bytes),
            "total": int(total), "zero_shards": zero_shards,
            "data_parallel": data_par}


def _divisors(n):
    return [d for d in range(1, n + 1) if n % d == 0]


def enumerate_meshes(device_count, *, allow_pp=False, max_tp=None,
                     max_fsdp=None):
    """Every ``dp×fsdp×tp×pp == device_count`` factorization, in the
    deterministic preference order the auto-selector walks: dp
    descending, then fsdp, tp, pp descending within a dp."""
    n = int(device_count)
    out = []
    for dp in _divisors(n):
        rest = n // dp
        for fsdp in _divisors(rest):
            if max_fsdp and fsdp > max_fsdp:
                continue
            rest2 = rest // fsdp
            for tp in _divisors(rest2):
                if max_tp and tp > max_tp:
                    continue
                pp = rest2 // tp
                if pp > 1 and not allow_pp:
                    continue
                out.append({"dp": dp, "fsdp": fsdp, "tp": tp, "pp": pp})
    out.sort(key=lambda m: (-m["dp"], -m["fsdp"], -m["tp"], -m["pp"]))
    return out


def choose_mesh(signature, ruleset, device_count, *, budget_bytes,
                optimizer="sgd", zero=False, batch_rows=0,
                microbatches=1, allow_pp=False, max_tp=None,
                max_fsdp=None, strict=True):
    """First feasible factorization under ``budget_bytes`` per device.

    Returns ``(axes_dict, estimate_dict, candidates)`` where
    ``candidates`` is the examined prefix (each with its total) — the
    report's audit trail.  With ``strict=True`` an infeasible budget
    raises; otherwise the minimum-footprint candidate is returned with
    ``feasible=False`` in its estimate.
    """
    cands = enumerate_meshes(device_count, allow_pp=allow_pp,
                             max_tp=max_tp, max_fsdp=max_fsdp)
    trail, best = [], None
    for axes in cands:
        est = estimate(signature, ruleset, axes, optimizer=optimizer,
                       zero=zero, batch_rows=batch_rows,
                       microbatches=microbatches)
        est["feasible"] = est["total"] <= budget_bytes
        trail.append({"axes": dict(axes), "total": est["total"],
                      "feasible": est["feasible"]})
        if best is None or est["total"] < best[1]["total"]:
            best = (axes, est)
        if est["feasible"]:
            return axes, est, trail
    if strict:
        axes, est = best
        raise MXNetError(
            f"no dp*fsdp*tp*pp mesh over {device_count} devices fits "
            f"the {budget_bytes / _GiB:.2f} GiB HBM budget — smallest "
            f"candidate {axes} still needs {est['total'] / _GiB:.2f} "
            f"GiB/device (raise MXNET_PLANNER_HBM_GB, shrink the model/"
            f"batch, or enable ZeRO/fsdp rules)")
    return best[0], best[1], trail
