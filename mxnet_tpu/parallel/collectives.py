"""Collective primitives over the device mesh.

Reference mapping (SURVEY.md §6.8): these replace the reference's reducers —
``CommCPU/CommDevice`` (src/kvstore/comm.h), tree allreduce (comm_tree.h),
NCCL (kvstore_nccl.h) and the ps-lite push/pull — with XLA collectives that
ride ICI/DCN.  Inside ``shard_map`` use the ``p*`` wrappers; at the array
level use the host-sharding helpers.
"""
from __future__ import annotations

from functools import partial

__all__ = ["psum", "pmean", "all_gather", "reduce_scatter", "ppermute",
           "all_to_all", "allreduce_hosts", "barrier"]


def psum(x, axis_name="dp"):
    import jax

    return jax.lax.psum(x, axis_name)


def pmean(x, axis_name="dp"):
    import jax

    return jax.lax.pmean(x, axis_name)


def all_gather(x, axis_name="dp", axis=0, tiled=True):
    import jax

    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name="dp", scatter_dimension=0):
    import jax

    return jax.lax.psum_scatter(x, axis_name, scatter_dimension=scatter_dimension,
                                tiled=True)


def ppermute(x, perm, axis_name="sp"):
    import jax

    return jax.lax.ppermute(x, axis_name, perm)


def all_to_all(x, axis_name="sp", split_axis=0, concat_axis=0, tiled=True):
    import jax

    return jax.lax.all_to_all(x, axis_name, split_axis, concat_axis, tiled=tiled)


def allreduce_hosts(value):
    """Allreduce a host-local array across all processes' devices: builds a
    global array sharded over processes and psums it.  Used by the
    dist_tpu_sync KVStore (single psum ≙ push+pull, SURVEY.md §4.4)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    if jax.process_count() == 1:
        return value
    mesh = Mesh(jax.devices(), ("w",))
    # each process contributes its local value on its own device shard;
    # stack over a leading axis, psum via sum-reduction of the global array
    g = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("w")),
        value[None].repeat(jax.local_device_count(), axis=0)
        if hasattr(value, "repeat") else jnp.broadcast_to(value[None], (jax.local_device_count(),) + value.shape))

    @partial(jax.jit, out_shardings=NamedSharding(mesh, P()))
    def _sum(a):
        return a.sum(axis=0) / jax.local_device_count()

    return _sum(g)


def barrier():
    import jax

    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("mxnet_tpu_barrier")
