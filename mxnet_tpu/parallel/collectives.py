"""Collective primitives over the device mesh.

Reference mapping (SURVEY.md §6.8): these replace the reference's reducers —
``CommCPU/CommDevice`` (src/kvstore/comm.h), tree allreduce (comm_tree.h),
NCCL (kvstore_nccl.h) and the ps-lite push/pull — with XLA collectives that
ride ICI/DCN.  Inside ``shard_map`` use the ``p*`` wrappers; at the array
level use the host-sharding helpers.
"""
from __future__ import annotations

from functools import partial

__all__ = ["psum", "pmean", "all_gather", "reduce_scatter", "ppermute",
           "all_to_all", "allreduce_hosts", "allreduce_hosts_quantized",
           "barrier"]


def psum(x, axis_name="dp"):
    import jax

    return jax.lax.psum(x, axis_name)


def pmean(x, axis_name="dp"):
    import jax

    return jax.lax.pmean(x, axis_name)


def all_gather(x, axis_name="dp", axis=0, tiled=True):
    import jax

    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name="dp", scatter_dimension=0):
    import jax

    return jax.lax.psum_scatter(x, axis_name, scatter_dimension=scatter_dimension,
                                tiled=True)


def ppermute(x, perm, axis_name="sp"):
    import jax

    return jax.lax.ppermute(x, axis_name, perm)


def all_to_all(x, axis_name="sp", split_axis=0, concat_axis=0, tiled=True):
    import jax

    return jax.lax.all_to_all(x, axis_name, split_axis, concat_axis, tiled=tiled)


def _cross_process_combine(local_leaves, combine_fn):
    """Shared scaffold for host-value collectives: ship each leaf as a
    global array sharded over all devices ('w' axis, one contribution per
    process replicated across its local devices), then jit combine_fn over
    the stacked leaves.  combine_fn sees leaves with a leading axis of
    n_processes*n_local and must normalize by n_local itself via the
    provided count (it receives (leaves..., n_local))."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(jax.devices(), ("w",))
    n_local = jax.local_device_count()

    def rep(a):
        a = jnp.asarray(a)
        return jnp.broadcast_to(a[None], (n_local,) + a.shape)

    globals_ = [jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("w")), rep(leaf)) for leaf in local_leaves]

    @partial(jax.jit, static_argnums=(len(globals_),),
             out_shardings=NamedSharding(mesh, P()))
    def _combine(*args):
        leaves, nl = args[:-1], args[-1]
        return combine_fn(*leaves, nl)

    return _combine(*globals_, n_local)


def allreduce_hosts(value):
    """Allreduce a host-local array across all processes' devices: builds a
    global array sharded over processes and psums it.  Used by the
    dist_tpu_sync KVStore (single psum ≙ push+pull, SURVEY.md §4.4)."""
    import jax

    if jax.process_count() == 1:
        return value
    return _cross_process_combine(
        (value,), lambda a, nl: a.sum(axis=0) / nl)


def barrier():
    import jax

    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("mxnet_tpu_barrier")


def _int8_quantize(v):
    """Per-tensor symmetric int8 quantization (scale, payload)."""
    import jax.numpy as jnp

    scale = jnp.maximum(jnp.max(jnp.abs(v)), 1e-30) / 127.0
    q = jnp.clip(jnp.round(v / scale), -127, 127).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def allreduce_hosts_quantized(value, _testing_force=False):
    """Bandwidth-compressed cross-process allreduce: each process ships an
    int8 payload + fp32 scale instead of fp32 (~4x less DCN/ICI traffic),
    dequantize-sum on receipt.

    Inspired by EQuARX (PAPERS.md: "Efficient Quantized AllReduce in XLA")
    — the XLA-native take on the reference's 2-bit kvstore compression,
    applied inside the collective rather than before it.  Max error per
    contribution is scale/2 = max|v|/254.
    """
    import jax
    import jax.numpy as jnp

    if jax.process_count() == 1 and not _testing_force:
        return value
    q, scale = _int8_quantize(value)

    def combine(qa, sa, nl):
        # dequantize each contribution with its own scale, then sum;
        # the int8 payload is what crossed the network
        deq = qa.astype(jnp.float32) * sa.reshape(
            (-1,) + (1,) * (qa.ndim - 1))
        return deq.sum(axis=0) / nl

    return _cross_process_combine((q, scale), combine)
