"""Collective primitives over the device mesh.

Reference mapping (SURVEY.md §6.8): these replace the reference's reducers —
``CommCPU/CommDevice`` (src/kvstore/comm.h), tree allreduce (comm_tree.h),
NCCL (kvstore_nccl.h) and the ps-lite push/pull — with XLA collectives that
ride ICI/DCN.  Inside ``shard_map`` use the ``p*`` wrappers; at the array
level use the host-sharding helpers.

The equal-call-count contract
-----------------------------
Every SPMD peer must issue the SAME collectives in the SAME program
order — XLA collectives rendezvous by issue order, not by name, so a
rank that issues one extra (or one fewer) collective pairs every later
collective with the wrong peer op and the mesh hangs or computes
garbage.  Machine-enforced by ``python -m tools.check`` (pass
``collective-safety``, codes MXT001-MXT003; see README "Static
analysis").  Concretely:

- never issue a collective under a rank-conditional branch
  (``jax.process_index()``, ``kv.rank``, launcher-rank env vars).
  Uniform guards — ``jax.process_count()``, configuration every process
  constructs identically — are fine: all ranks take the same arm.
- never retry a collective unilaterally (PR 2): the peers never issue
  the matching re-run.  A transient interconnect failure escalates to
  ``checkpoint.run_with_recovery``'s whole-job restart; only
  single-process paths retry locally (see ``_combine_with_seam``).
- branches whose arms issue different collective counts must derive
  their condition from rank-uniform state.  Audited examples of the
  uniform kind: ``lifecycle.check_stop``'s agreement stride is a pure
  function of the per-process call COUNT (never of the local stop
  flag), and both of its loop call sites (``TrainStep.run``,
  ``Estimator.fit``) poll it exactly once per step on every rank;
  kvstore fusion plans are a deterministic function of the push-order
  (key, shape, dtype) signature, identical on every peer (PR 4).

Because issue order IS the rendezvous key, every Python-level issue
site here also stamps the distributed flight recorder
(:mod:`mxnet_tpu.flight_recorder`): a monotonic per-rank sequence
number + a digest of (op, shape, dtype, axis, generation), so a hang
or desync is blamable post-mortem from the per-rank black-box rings
(machine-enforced by mxtpu-check pass ``ledger-discipline``, MXT100).
"""
from __future__ import annotations

from functools import partial

__all__ = ["psum", "pmean", "all_gather", "reduce_scatter", "ppermute",
           "all_to_all", "allreduce_hosts", "allreduce_hosts_quantized",
           "allreduce_hosts_quantized_multi", "allreduce_any",
           "barrier", "shard_map", "place_global", "fetch_global"]


def place_global(host, sharding):
    """Place a host array as a global array with ``sharding`` without
    cross-host transfers.

    ``jax.device_put(x, sharding)`` raises in a multi-process job when
    the sharding spans non-addressable devices; build the global array
    from each process's addressable shards instead (every process holds
    the full value, the callback slices out the local shards).  Shared
    by every sharded-state owner (ShardedOptimizerUpdater,
    ZeroBucketEngine) so the multi-process placement workaround lives in
    exactly one place.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    if jax.process_count() == 1:
        return jax.device_put(jnp.asarray(host), sharding)
    host = np.asarray(host)
    return jax.make_array_from_callback(
        host.shape, sharding, lambda idx: host[idx])


def fetch_global(arr):
    """Host copy of a global array — the inverse of :func:`place_global`.

    ``np.asarray`` on an array whose sharding spans non-addressable
    devices raises in a multi-process job; gather the full value to
    every host first.  The gather is itself a collective, so callers
    must reach this uniformly on every process (harvest/save points
    already are: replans are deterministic plan functions and
    checkpoint saves happen at the same step on every peer).
    """
    import jax
    import numpy as np

    if jax.process_count() == 1:
        return np.asarray(arr)
    from jax.experimental import multihost_utils

    from .. import flight_recorder as _flight

    with _flight.collective("fetch_global",
                            shape=getattr(arr, "shape", None),
                            dtype=getattr(arr, "dtype", None)):
        return np.asarray(multihost_utils.process_allgather(arr,
                                                            tiled=True))


def shard_map(fn, mesh, in_specs, out_specs):
    """Version-compat ``shard_map`` with replication checking off.

    jax >= 0.6 exposes ``jax.shard_map`` (``check_vma=``) and deprecates
    ``jax.experimental.shard_map`` (``check_rep=``); older jax only has the
    experimental one.  Every shard_map in this repo wants the check off
    (collectives make replication explicit), so one helper owns the
    divergence instead of each call site pinning an API generation.
    """
    import inspect

    import jax

    if hasattr(jax, "shard_map"):
        impl = jax.shard_map
    else:
        from jax.experimental.shard_map import shard_map as impl
    # pick the check kwarg by signature, not API location: the 0.6-era
    # promotion window had jax.shard_map still spelling it check_rep
    params = inspect.signature(impl).parameters
    check = {"check_vma": False} if "check_vma" in params else \
        {"check_rep": False}
    return impl(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                **check)


def psum(x, axis_name="dp"):
    import jax

    return jax.lax.psum(x, axis_name)


def pmean(x, axis_name="dp"):
    import jax

    return jax.lax.pmean(x, axis_name)


def all_gather(x, axis_name="dp", axis=0, tiled=True):
    import jax

    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name="dp", scatter_dimension=0):
    import jax

    return jax.lax.psum_scatter(x, axis_name, scatter_dimension=scatter_dimension,
                                tiled=True)


def ppermute(x, perm, axis_name="sp"):
    import jax

    return jax.lax.ppermute(x, axis_name, perm)


def all_to_all(x, axis_name="sp", split_axis=0, concat_axis=0, tiled=True):
    import jax

    return jax.lax.all_to_all(x, axis_name, split_axis, concat_axis, tiled=tiled)


import functools


@functools.lru_cache(maxsize=64)
def _jitted_combine(combine_fn, mesh, n_local, static_args):
    """One jit per (combine_fn identity, mesh, n_local, static args) —
    host collectives sit on the training hot path, so per-call retracing
    (a fresh closure each push) must not happen."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    return jax.jit(lambda *leaves: combine_fn(*leaves, n_local,
                                              *static_args),
                   out_shardings=NamedSharding(mesh, P()))


def _cross_process_combine(local_leaves, combine_fn, static_args=()):
    """Shared scaffold for host-value collectives: ship each leaf as a
    global array sharded over all devices ('w' axis, one contribution per
    process replicated across its local devices), then run the cached
    jitted combine_fn over the stacked leaves.  combine_fn must be a
    MODULE-LEVEL function (stable identity for the jit cache) with
    signature (leaves..., n_local, *static_args)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(jax.devices(), ("w",))
    n_local = jax.local_device_count()

    def rep(a):
        a = jnp.asarray(a)
        return jnp.broadcast_to(a[None], (n_local,) + a.shape)

    globals_ = [jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("w")), rep(leaf)) for leaf in local_leaves]
    fn = _jitted_combine(combine_fn, mesh, n_local, tuple(static_args))
    return fn(*globals_)


def _sum_combine(a, nl):
    return a.sum(axis=0) / nl


def _combine_with_seam(local_leaves, combine_fn, static_args=(),
                       op="allreduce"):
    """Route a host-value collective through the ``collectives.allreduce``
    fault seam.  Single-process (tests, _testing_force paths): the full
    retry policy applies, so injected transient faults are absorbed end
    to end.  Multi-process SPMD: seam check only, NO local retry — a
    unilateral re-issue desyncs the peers' collective issue counts (they
    never issue the matching one, so the retry hangs the mesh); a real
    transient interconnect failure instead escalates to
    checkpoint.run_with_recovery, which restarts every process together —
    bounded backoff at the scope where retry is actually safe.

    Flight-recorder stamp: this is the single funnel every host-value
    collective flows through, so the ledger entry (``op`` + the lead
    leaf's shape/dtype) is stamped HERE — seam trip included, so a
    failed issue shows in the ring with its error."""
    import jax

    from .. import fault
    from .. import flight_recorder as _flight

    lead = local_leaves[0] if local_leaves else None
    with _flight.collective(op, shape=getattr(lead, "shape", None),
                            dtype=getattr(lead, "dtype", None),
                            axis="world"):
        if jax.process_count() == 1:
            return fault.call_with_retries(
                "collectives.allreduce", _cross_process_combine,
                local_leaves, combine_fn, static_args=static_args)
        fault.check("collectives.allreduce")
        return _cross_process_combine(local_leaves, combine_fn,
                                      static_args=static_args)


def allreduce_hosts(value, _testing_force=False):
    """Allreduce a host-local array across all processes' devices: builds a
    global array sharded over processes and psums it.  Used by the
    dist_tpu_sync KVStore (single psum ≙ push+pull, SURVEY.md §4.4), and
    by the numerical-integrity guard as its verdict-agreement primitive
    (one summed sentinel vector / one-hot canary-digest table per check;
    mxnet_tpu/guard.py — call-count-uniform like every collective here).

    Fault seam ``collectives.allreduce``; see ``_combine_with_seam`` for
    why transient-error retry happens here only single-process (SPMD
    retry is run_with_recovery's whole-job restart).  ``_testing_force``
    runs the real combine path on one process (tests and the bench's
    fused-vs-per-key curve, like the quantized variants)."""
    import jax

    from .. import fault

    if jax.process_count() == 1 and not _testing_force:
        fault.guard("collectives.allreduce")
        return value
    return _combine_with_seam((value,), _sum_combine, op="allreduce")


def allreduce_any(flag, _testing_force=False):
    """Cross-process logical-OR of a host-local bool in ONE collective —
    the agreement primitive for coordinated preemption stops
    (``lifecycle.check_stop``): every SPMD peer must call it at the same
    step boundary, and every peer sees the same verdict, so they all
    exit at the same step.  Single-process it is just the local flag
    (seam-guarded like its siblings)."""
    import jax

    from .. import fault

    if jax.process_count() == 1 and not _testing_force:
        fault.guard("collectives.allreduce")
        return bool(flag)
    import numpy as np
    import jax.numpy as jnp

    out = allreduce_hosts(jnp.asarray(bool(flag), jnp.float32),
                          _testing_force=_testing_force)
    return bool(np.asarray(out) > 0)


def barrier():
    import jax

    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        from .. import flight_recorder as _flight

        with _flight.collective("barrier"):
            multihost_utils.sync_global_devices("mxnet_tpu_barrier")


def _int8_quantize(v):
    """Per-tensor symmetric int8 quantization (scale, payload)."""
    import jax.numpy as jnp

    scale = jnp.maximum(jnp.max(jnp.abs(v)), 1e-30) / 127.0
    q = jnp.clip(jnp.round(v / scale), -127, 127).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def _dequant_sum_combine(qa, sa, nl, out_dtype):
    import jax.numpy as jnp

    # dequantize each contribution with its own scale, then sum;
    # the int8 payload is what crossed the network
    deq = qa.astype(jnp.float32) * sa.reshape(
        (-1,) + (1,) * (qa.ndim - 1))
    return (deq.sum(axis=0) / nl).astype(out_dtype)


def allreduce_hosts_quantized(value, _testing_force=False):
    """Bandwidth-compressed cross-process allreduce: each process ships an
    int8 payload + fp32 scale instead of fp32 (~4x less DCN/ICI traffic),
    dequantize-sum on receipt; result keeps the input dtype.

    Inspired by EQuARX (PAPERS.md: "Efficient Quantized AllReduce in XLA")
    — the XLA-native take on the reference's 2-bit kvstore compression,
    applied inside the collective rather than before it.  Max error per
    contribution is scale/2 = max|v|/254.
    """
    import jax

    from .. import fault

    if jax.process_count() == 1 and not _testing_force:
        fault.guard("collectives.allreduce")
        return value
    q, scale = _int8_quantize(value)
    return _combine_with_seam((q, scale), _dequant_sum_combine,
                              static_args=(value.dtype,),
                              op="allreduce_q8")


def _dequant_multi_combine(qa, sa, nl, sizes):
    import jax.numpy as jnp

    # per-segment scales: repeat each tensor's scale across its payload
    reps = jnp.repeat(sa, jnp.asarray(sizes), axis=1,
                      total_repeat_length=int(sum(sizes)))
    deq = qa.astype(jnp.float32) * reps
    return deq.sum(axis=0) / nl


def allreduce_hosts_quantized_multi(values, _testing_force=False):
    """Fused int8 allreduce of several tensors in ONE collective, with a
    PER-TENSOR scale — small-magnitude gradients bucketed next to a large
    one keep their own resolution (a single bucket-wide scale would round
    them to zero)."""
    import jax
    import jax.numpy as jnp

    from .. import fault

    if jax.process_count() == 1 and not _testing_force:
        fault.guard("collectives.allreduce")
        return list(values)
    qs, scales = zip(*[_int8_quantize(v.ravel()) for v in values])
    sizes = tuple(int(v.size) for v in values)
    flat_q = jnp.concatenate(qs)
    summed = _combine_with_seam((flat_q, jnp.stack(scales)),
                                _dequant_multi_combine,
                                static_args=(sizes,),
                                op="allreduce_q8_multi")
    out, off = [], 0
    for v, n in zip(values, sizes):
        out.append(summed[off:off + n].reshape(v.shape).astype(v.dtype))
        off += n
    return out
