"""Subgraph / graph-partitioning API.

Reference: ``src/operator/subgraph/`` + ``Symbol.optimize_for`` +
``MXNET_SUBGRAPH_BACKEND`` (SURVEY.md §3.2 "Subgraph/partitioning API").
The reference lets a backend (MKLDNN, TensorRT, …) register a
SubgraphProperty that pattern-matches regions of the NNVM graph and
replaces them with fused backend nodes; users trigger it with
``sym.optimize_for(backend)`` or globally via the env var at bind time.

TPU-native scope: XLA already owns low-level fusion, so the interesting
passes here operate at the *operator graph* level — collapsing op chains
into single registered ops (fewer dispatches in the eager Executor, one
tape entry under autograd) and giving users the same extension point the
reference exposes: register a backend, attach passes, call
``optimize_for``.

Since ISSUE 11 the backends are sugar over the graph-compiler tier
(:mod:`mxnet_tpu.graph`): every registered ``Symbol -> Symbol`` pass is
wrapped as a registered graph pass, and ``optimize_for(backend)``
resolves to a :class:`~mxnet_tpu.graph.PassPipeline` selection — ONE
pass mechanism, one telemetry stream (``kind="graph_pass"`` compile
events), one purity contract.  A legacy pass receives a freshly
converted Symbol it may mutate; the caller's Symbol is never touched.
"""
from __future__ import annotations

import os
import threading

from .base import MXNetError
from .ops.registry import OP_TABLE, register
from .symbol.symbol import Symbol, _Node, _topo

__all__ = ["register_backend", "register_pass", "list_backends",
           "optimize_for", "clone", "fuse_linear_chain",
           "SubgraphProperty", "partition_graph"]

_BACKENDS = {}          # backend name -> [registered graph-pass name, ...]

# kwargs channel for optimize_for(sym, backend, **kwargs): the pipeline
# API is Graph -> Graph, so per-invocation kwargs ride a thread-local
# the adapters read (set only for the duration of one optimize_for)
_PASS_KWARGS = threading.local()


def _wrap_symbol_pass(backend, fn):
    """Register a legacy ``Symbol -> Symbol`` pass as a graph pass."""
    from . import graph as _graph

    existing = getattr(fn, "graph_pass_name", None)
    if existing is not None:
        return existing
    base = f"subgraph:{backend}:{getattr(fn, '__name__', 'pass')}"
    name = base
    k = 1
    while name in _graph.pipeline.PASS_REGISTRY:
        k += 1
        name = f"{base}:{k}"

    def adapter(g):
        in_names = [g.nodes[i].name for i in g.inputs]
        sym = g.to_symbol()          # fresh nodes — fn may mutate freely
        kwargs = getattr(_PASS_KWARGS, "value", None) or {}
        out = fn(sym, **kwargs) if _accepts_kwargs(fn) else fn(sym)
        return _graph.Graph.from_symbol(out, input_names=in_names)

    adapter.__name__ = name
    adapter.__doc__ = fn.__doc__
    _graph.graph_pass(name, default=False)(adapter)
    # memoize on the ORIGINAL callable: re-registering the same pass
    # (notebook re-runs, a backend aliased under two names) reuses the
    # registration instead of growing PASS_REGISTRY with :N suffixes
    fn.graph_pass_name = name
    return name


def register_backend(name, passes=None):
    """Register (or extend) a partitioning backend — ≙ the reference's
    SubgraphProperty registration (subgraph_property.h).  ``passes`` may
    be legacy ``Symbol -> Symbol`` callables (wrapped and registered
    into the graph-pass registry) or already-registered graph-pass
    names."""
    _BACKENDS.setdefault(name, [])
    for p in passes or ():
        _BACKENDS[name].append(
            p if isinstance(p, str) else _wrap_symbol_pass(name, p))
    return _BACKENDS[name]


def register_pass(backend):
    """Decorator: append a ``Symbol -> Symbol`` pass to a backend."""

    def _do(fn):
        register_backend(backend, [fn])
        return fn

    return _do


def list_backends():
    return sorted(_BACKENDS)


def clone(sym):
    """Deep-copy the reachable graph (variables keep identity semantics by
    name; they are cloned too so passes can rewire them safely)."""
    mapping = {}
    for n in _topo(sym._heads):
        c = _Node(n.op, n.name, dict(n.attrs),
                  [(mapping[id(i)], idx) for i, idx in n.inputs],
                  n.nout, n.value)
        mapping[id(n)] = c
    return Symbol([(mapping[id(n)], i) for n, i in sym._heads]), mapping


def optimize_for(sym, backend, **kwargs):
    """Apply a backend's passes; returns a new Symbol
    (reference: Symbol.optimize_for).  Sugar for a graph-tier
    ``PassPipeline`` over the backend's registered pass names."""
    from . import graph as _graph

    if backend not in _BACKENDS:
        raise MXNetError(
            f"unknown subgraph backend {backend!r}; registered: "
            f"{list_backends()}")
    pipeline = _graph.PassPipeline(_BACKENDS[backend], fixed_point=False)
    prev = getattr(_PASS_KWARGS, "value", None)
    _PASS_KWARGS.value = kwargs
    try:
        return pipeline.run_symbol(sym)
    finally:
        _PASS_KWARGS.value = prev


def _accepts_kwargs(fn):
    import inspect

    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return False
    return any(p.kind == inspect.Parameter.VAR_KEYWORD
               for p in sig.parameters.values())


def env_backend():
    """MXNET_SUBGRAPH_BACKEND: backend applied automatically at bind time
    (reference: executor attach-time partitioning)."""
    return os.environ.get("MXNET_SUBGRAPH_BACKEND") or None


def apply_env_backend(sym):
    b = env_backend()
    if b and b in _BACKENDS:
        return optimize_for(sym, b)
    return sym


# --------------------------------------------------------------------------
# generic chain-fusion helper for pass authors
# --------------------------------------------------------------------------
def fuse_linear_chain(sym, pattern, fused_op, make_attrs=None):
    """Fuse every producer->consumer chain matching ``pattern`` into one
    ``fused_op`` node.

    pattern: list of predicates ``fn(node) -> bool`` (length >= 2); node i+1
    must consume node i's output as its FIRST input, node i must have a
    single consumer and one output.  The fused node takes the first node's
    inputs plus every later node's non-chain inputs, in order.  attrs come
    from ``make_attrs(nodes) -> dict`` (default: merged attrs).

    Mutates ``sym`` in place — call on a :func:`clone` (optimize_for does).
    """
    nodes = _topo(sym._heads)
    consumers = {}
    for n in nodes:
        for inp, _ in n.inputs:
            consumers[id(inp)] = consumers.get(id(inp), 0) + 1
    for n, _ in sym._heads:
        consumers[id(n)] = consumers.get(id(n), 0) + 1000  # heads stay live

    def chain_at(last):
        chain = [last]
        cur = last
        for pred in reversed(pattern[:-1]):
            if not cur.inputs:
                return None
            prev = cur.inputs[0][0]
            if prev.op is None or not pred(prev) or prev.nout != 1 or \
                    consumers.get(id(prev), 0) != 1:
                return None
            chain.insert(0, prev)
            cur = prev
        return chain

    fused_count = 0
    replaced = {}  # id(old tail) -> fused node
    for n in nodes:
        if n.op is None or not pattern[-1](n):
            continue
        chain = chain_at(n)
        if chain is None:
            continue
        inputs = list(chain[0].inputs)
        for later in chain[1:]:
            inputs.extend(later.inputs[1:])
        attrs = {}
        if make_attrs is not None:
            attrs = make_attrs(chain)
        else:
            for c in chain:
                attrs.update(c.attrs)
        fused = _Node(fused_op, f"{chain[0].name}_{fused_op.lstrip('_')}",
                      attrs, inputs, 1, None)
        replaced[id(chain[-1])] = fused
        fused_count += 1
    if not replaced:
        return sym
    # rewire every consumer + head referencing a replaced tail (fused nodes
    # included: a fused chain may consume another chain's output)
    for n in list(_topo(sym._heads)) + list(replaced.values()):
        n.inputs = [(replaced.get(id(i), i), idx) for i, idx in n.inputs]
    sym._heads = [(replaced.get(id(n), n), i) for n, i in sym._heads]
    return sym


# --------------------------------------------------------------------------
# property-based partitioning over typed selectors
# (reference: subgraph_property.h SubgraphProperty/SubgraphSelector —
# Select/SelectInput/SelectOutput growing arbitrary connected regions,
# not just linear chains)
# --------------------------------------------------------------------------
class SubgraphProperty:
    """Typed-selector partitioning rules.  Subclass and override:

    - ``select(node)``: may this node SEED a region?
    - ``select_input(node, producer)``: grow the region upstream from
      ``node`` to ``producer``?
    - ``select_output(node, consumer)``: grow downstream?
    - ``min_size``: discard regions smaller than this (default 2 — a
      1-node region is not worth a dispatch).

    ``partition_graph(sym, prop)`` greedily grows maximal regions, then
    replaces each with ONE dynamically-registered op that interprets the
    captured region through the same registry kernels (one dispatch per
    region on the eager Executor, one tape entry under autograd; XLA sees
    the identical fused computation under jit)."""

    min_size = 2

    def select(self, node):
        return False

    def select_input(self, node, producer):
        return self.select(producer)

    def select_output(self, node, consumer):
        return self.select(consumer)

    def op_name(self, nodes):
        return "_sg_region"


_REGION_COUNTER = [0]


_REGION_CACHE = {}


def _make_region_op(region_nodes, ext_inputs, out_node, name_hint):
    """Register (or reuse) an op executing the captured region: inputs
    are the region's external feeds — (producer, out_idx) EDGES, already
    indexed by the executor — output the region's single result.  The op
    body re-runs each captured node's registry kernel: pure, traceable,
    differentiable.  Structurally identical regions share one registered
    op (repeated bind-time partitioning must not grow OP_TABLE)."""
    plan = []  # (op_name, attrs, [(src_kind, key, out_idx|None), ...])
    index_of = {id(n): i for i, n in enumerate(region_nodes)}
    ext_index = {(id(n), idx): i for i, (n, idx) in enumerate(ext_inputs)}
    from .symbol.symbol import _clean_attrs

    for n in region_nodes:
        srcs = []
        for inp, idx in n.inputs:
            if id(inp) in index_of:
                srcs.append(("node", index_of[id(inp)], idx))
            else:
                srcs.append(("ext", ext_index[(id(inp), idx)], None))
        plan.append((n.op, _clean_attrs(n.attrs), srcs))
    out_pos = index_of[id(out_node)]

    sig = (name_hint, out_pos,
           tuple((op, tuple(sorted((k, repr(v)) for k, v in at.items())),
                  tuple(srcs)) for op, at, srcs in plan))
    if sig in _REGION_CACHE:
        return _REGION_CACHE[sig]
    _REGION_COUNTER[0] += 1
    opname = f"{name_hint}{_REGION_COUNTER[0]}"
    fns = [OP_TABLE[op].fn for op, _, _ in plan]

    def region_fn(*ext_vals):
        vals = []
        for fn, (_, attrs, srcs) in zip(fns, plan):
            args = []
            for kind, key, idx in srcs:
                if kind == "ext":
                    args.append(ext_vals[key])  # executor pre-indexed
                else:
                    v = vals[key]
                    args.append(v[idx] if isinstance(v, (tuple, list))
                                else v)
            vals.append(fn(*args, **attrs))
        out = vals[out_pos]
        return out[0] if isinstance(out, (tuple, list)) else out

    region_fn.__name__ = opname
    register(opname)(region_fn)
    _REGION_CACHE[sig] = opname
    return opname


def partition_graph(sym, prop):
    """Partition ``sym`` with a :class:`SubgraphProperty`; returns a new
    Symbol with each maximal selected region collapsed to one node
    (reference: BuildSubgraph over SubgraphSelector decisions).  Regions
    are constrained to a single output node (multi-consumer interior
    nodes stay internal only if every consumer is in the region)."""
    out_sym, _ = clone(sym)
    nodes = _topo(out_sym._heads)
    order = {id(n): i for i, n in enumerate(nodes)}
    consumers = {}
    for n in nodes:
        for inp, _ in n.inputs:
            consumers.setdefault(id(inp), []).append(n)
    head_ids = {id(n) for n, _ in out_sym._heads}

    def _fusable(n):
        # rng-consuming ops take an injected key the region replay cannot
        # thread, and the executor's training/state injection (BatchNorm
        # moving stats, Dropout train flag, RNN) keys on the ORIGINAL op
        # name — fusing them would silently freeze training semantics.
        # Multi-output ops are fine in the region INTERIOR (indexed
        # positionally); the single-output boundary is enforced below.
        od = OP_TABLE.get(n.op)
        return od is not None and not od.needs_rng and \
            n.op not in ("BatchNorm", "Dropout", "RNN")

    assigned = set()
    regions = []
    for seed in nodes:
        if seed.op is None or id(seed) in assigned or \
                not _fusable(seed) or not prop.select(seed):
            continue
        region = {id(seed): seed}
        frontier = [seed]
        while frontier:
            cur = frontier.pop()
            for inp, _ in cur.inputs:
                if inp.op is None or id(inp) in assigned or \
                        id(inp) in region:
                    continue
                if _fusable(inp) and prop.select_input(cur, inp):
                    region[id(inp)] = inp
                    frontier.append(inp)
            for con in consumers.get(id(cur), []):
                if con.op is None or id(con) in assigned or \
                        id(con) in region:
                    continue
                if _fusable(con) and prop.select_output(cur, con):
                    region[id(con)] = con
                    frontier.append(con)
        # shrink until the region has exactly ONE single-output output
        # node (a node with a consumer outside the region, or a head).
        # The single-output constraint also guarantees acyclicity of the
        # collapsed graph: every region node feeds (transitively) into
        # the unique output, so an external path re-entering the region
        # would have to both depend on and feed the output — a cycle in
        # the ORIGINAL DAG, which cannot exist.
        while True:
            outs = [n for n in region.values()
                    if id(n) in head_ids or any(
                        id(c) not in region
                        for c in consumers.get(id(n), []))]
            multi = [n for n in outs if n.nout != 1]
            if multi:
                # a multi-output boundary cannot collapse to a 1-output
                # fused node; push it (and its extra outputs) outside
                del region[id(multi[0])]
            elif len(outs) > 1:
                # drop the topologically-earliest extra output
                drop = min(outs, key=lambda n: order[id(n)])
                del region[id(drop)]
            else:
                break
        if len(region) < prop.min_size or not region:
            continue
        ordered = [n for n in nodes if id(n) in region]
        out_node = [n for n in ordered
                    if id(n) in head_ids or any(
                        id(c) not in region
                        for c in consumers.get(id(n), []))]
        out_node = out_node[0] if out_node else ordered[-1]
        regions.append((ordered, region, out_node))
        assigned.update(region)

    if not regions:
        return out_sym
    for ordered, region, out_node in regions:
        # external feeds are EDGES (producer, out_idx): two consumptions
        # of different outputs of one producer are distinct inputs
        ext = []
        seen_ext = set()
        for n in ordered:
            for inp, idx in n.inputs:
                if id(inp) not in region and (id(inp), idx) not in seen_ext:
                    seen_ext.add((id(inp), idx))
                    ext.append((inp, idx))
        opname = _make_region_op(ordered, ext, out_node,
                                 prop.op_name(ordered))
        fused = _Node(opname, f"{out_node.name}_region",
                      {"__n_fused__": len(ordered)},
                      list(ext), 1, None)
        replaced = {id(out_node): fused}
        for n in _topo(out_sym._heads):
            if id(n) not in region:
                n.inputs = [(replaced.get(id(i), i), idx)
                            for i, idx in n.inputs]
        out_sym._heads = [(replaced.get(id(n), n), i)
                          for n, i in out_sym._heads]
    return out_sym


# --------------------------------------------------------------------------
# built-in backend: operator-level fusions useful on the eager Executor
# --------------------------------------------------------------------------
@register("_sg_fused_dense_act")
def _sg_fused_dense_act(x, weight, *maybe_bias, num_hidden=None,
                        no_bias=False, flatten=True, act_type="relu"):
    """FullyConnected+Activation as one op (subgraph 'default' backend)."""
    fc = OP_TABLE["FullyConnected"].fn
    act = OP_TABLE["Activation"].fn
    return act(fc(x, weight, *maybe_bias, num_hidden=num_hidden,
                  no_bias=no_bias, flatten=flatten), act_type=act_type)


@register("_sg_fused_conv_act")
def _sg_fused_conv_act(x, weight, *maybe_bias, kernel=None, stride=None,
                       dilate=None, pad=None, num_filter=None, num_group=1,
                       no_bias=False, layout=None, cudnn_tune=None,
                       cudnn_off=None, workspace=None, act_type="relu"):
    """Convolution+Activation as one op (subgraph 'default' backend)."""
    conv = OP_TABLE["Convolution"].fn
    act = OP_TABLE["Activation"].fn
    return act(conv(x, weight, *maybe_bias, kernel=kernel, stride=stride,
                    dilate=dilate, pad=pad, num_filter=num_filter,
                    num_group=num_group, no_bias=no_bias, layout=layout),
               act_type=act_type)


def _is_op(*names):
    s = set(names)
    return lambda n: n.op in s


@register_pass("default")
def fuse_dense_activation(sym):
    return fuse_linear_chain(
        sym, [_is_op("FullyConnected"), _is_op("Activation", "activation")],
        "_sg_fused_dense_act")


@register_pass("default")
def fuse_conv_activation(sym):
    return fuse_linear_chain(
        sym, [_is_op("Convolution"), _is_op("Activation", "activation")],
        "_sg_fused_conv_act")


# shape inference for the fused nodes reuses the base op's param rules
from .symbol import symbol as _symbol_mod  # noqa: E402

_symbol_mod._OP_SHAPE_HINT_ALIASES["_sg_fused_dense_act"] = "FullyConnected"
_symbol_mod._OP_SHAPE_HINT_ALIASES["_sg_fused_conv_act"] = "Convolution"
_symbol_mod._OP_PARAM_VARS["_sg_fused_dense_act"] = \
    _symbol_mod._OP_PARAM_VARS["FullyConnected"]
_symbol_mod._OP_PARAM_VARS["_sg_fused_conv_act"] = \
    _symbol_mod._OP_PARAM_VARS["Convolution"]

# the reference ships MKLDNN as its always-available backend; ours is the
# XLA-oriented 'default' — register the reference names as aliases so
# scripts that say optimize_for('MKLDNN') keep working
register_backend("MKLDNN", _BACKENDS["default"])
register_backend("ONEDNN", _BACKENDS["default"])


class _ElemwiseIslands(SubgraphProperty):
    """Built-in property: collapse connected elementwise islands into one
    dispatch each (the op-graph-level analog of XLA's own elementwise
    fusion, useful on the eager Executor where each node costs a Python
    dispatch)."""

    _OPS = {"Activation", "activation", "relu", "sigmoid", "tanh",
            "softsign", "gelu", "exp", "log", "sqrt", "square", "abs",
            "negative", "broadcast_add", "broadcast_sub", "broadcast_mul",
            "broadcast_div", "broadcast_maximum", "broadcast_minimum",
            "elemwise_add", "elemwise_sub", "elemwise_mul", "elemwise_div",
            # Symbol operator sugar emits *_scalar for python-number
            # operands (x * 0.5 etc.) — without these every scalar op
            # would split an island
            "broadcast_add_scalar", "broadcast_sub_scalar",
            "broadcast_mul_scalar", "broadcast_div_scalar",
            "broadcast_maximum_scalar", "broadcast_minimum_scalar",
            "broadcast_power_scalar", "clip"}

    def select(self, node):
        return node.op in self._OPS


@register_pass("islands")
def fuse_elemwise_islands(sym):
    return partition_graph(sym, _ElemwiseIslands())
