"""Subgraph / graph-partitioning API.

Reference: ``src/operator/subgraph/`` + ``Symbol.optimize_for`` +
``MXNET_SUBGRAPH_BACKEND`` (SURVEY.md §3.2 "Subgraph/partitioning API").
The reference lets a backend (MKLDNN, TensorRT, …) register a
SubgraphProperty that pattern-matches regions of the NNVM graph and
replaces them with fused backend nodes; users trigger it with
``sym.optimize_for(backend)`` or globally via the env var at bind time.

TPU-native scope: XLA already owns low-level fusion, so the interesting
passes here operate at the *operator graph* level — collapsing op chains
into single registered ops (fewer dispatches in the eager Executor, one
tape entry under autograd) and giving users the same extension point the
reference exposes: register a backend, attach passes, call
``optimize_for``.  Passes are pure ``Symbol -> Symbol`` functions over a
cloned graph (the input Symbol is never mutated).
"""
from __future__ import annotations

import os

from .base import MXNetError
from .ops.registry import OP_TABLE, register
from .symbol.symbol import Symbol, _Node, _topo

__all__ = ["register_backend", "register_pass", "list_backends",
           "optimize_for", "clone", "fuse_linear_chain"]

_BACKENDS = {}


def register_backend(name, passes=None):
    """Register (or extend) a partitioning backend — ≙ the reference's
    SubgraphProperty registration (subgraph_property.h)."""
    _BACKENDS.setdefault(name, [])
    if passes:
        _BACKENDS[name].extend(passes)
    return _BACKENDS[name]


def register_pass(backend):
    """Decorator: append a ``Symbol -> Symbol`` pass to a backend."""

    def _do(fn):
        register_backend(backend, [fn])
        return fn

    return _do


def list_backends():
    return sorted(_BACKENDS)


def clone(sym):
    """Deep-copy the reachable graph (variables keep identity semantics by
    name; they are cloned too so passes can rewire them safely)."""
    mapping = {}
    for n in _topo(sym._heads):
        c = _Node(n.op, n.name, dict(n.attrs),
                  [(mapping[id(i)], idx) for i, idx in n.inputs],
                  n.nout, n.value)
        mapping[id(n)] = c
    return Symbol([(mapping[id(n)], i) for n, i in sym._heads]), mapping


def optimize_for(sym, backend, **kwargs):
    """Apply a backend's passes; returns a new Symbol
    (reference: Symbol.optimize_for)."""
    if backend not in _BACKENDS:
        raise MXNetError(
            f"unknown subgraph backend {backend!r}; registered: "
            f"{list_backends()}")
    out, _ = clone(sym)
    for p in _BACKENDS[backend]:
        out = p(out, **kwargs) if _accepts_kwargs(p) else p(out)
    return out


def _accepts_kwargs(fn):
    import inspect

    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return False
    return any(p.kind == inspect.Parameter.VAR_KEYWORD
               for p in sig.parameters.values())


def env_backend():
    """MXNET_SUBGRAPH_BACKEND: backend applied automatically at bind time
    (reference: executor attach-time partitioning)."""
    return os.environ.get("MXNET_SUBGRAPH_BACKEND") or None


def apply_env_backend(sym):
    b = env_backend()
    if b and b in _BACKENDS:
        return optimize_for(sym, b)
    return sym


# --------------------------------------------------------------------------
# generic chain-fusion helper for pass authors
# --------------------------------------------------------------------------
def fuse_linear_chain(sym, pattern, fused_op, make_attrs=None):
    """Fuse every producer->consumer chain matching ``pattern`` into one
    ``fused_op`` node.

    pattern: list of predicates ``fn(node) -> bool`` (length >= 2); node i+1
    must consume node i's output as its FIRST input, node i must have a
    single consumer and one output.  The fused node takes the first node's
    inputs plus every later node's non-chain inputs, in order.  attrs come
    from ``make_attrs(nodes) -> dict`` (default: merged attrs).

    Mutates ``sym`` in place — call on a :func:`clone` (optimize_for does).
    """
    nodes = _topo(sym._heads)
    consumers = {}
    for n in nodes:
        for inp, _ in n.inputs:
            consumers[id(inp)] = consumers.get(id(inp), 0) + 1
    for n, _ in sym._heads:
        consumers[id(n)] = consumers.get(id(n), 0) + 1000  # heads stay live

    def chain_at(last):
        chain = [last]
        cur = last
        for pred in reversed(pattern[:-1]):
            if not cur.inputs:
                return None
            prev = cur.inputs[0][0]
            if prev.op is None or not pred(prev) or prev.nout != 1 or \
                    consumers.get(id(prev), 0) != 1:
                return None
            chain.insert(0, prev)
            cur = prev
        return chain

    fused_count = 0
    replaced = {}  # id(old tail) -> fused node
    for n in nodes:
        if n.op is None or not pattern[-1](n):
            continue
        chain = chain_at(n)
        if chain is None:
            continue
        inputs = list(chain[0].inputs)
        for later in chain[1:]:
            inputs.extend(later.inputs[1:])
        attrs = {}
        if make_attrs is not None:
            attrs = make_attrs(chain)
        else:
            for c in chain:
                attrs.update(c.attrs)
        fused = _Node(fused_op, f"{chain[0].name}_{fused_op.lstrip('_')}",
                      attrs, inputs, 1, None)
        replaced[id(chain[-1])] = fused
        fused_count += 1
    if not replaced:
        return sym
    # rewire every consumer + head referencing a replaced tail (fused nodes
    # included: a fused chain may consume another chain's output)
    for n in list(_topo(sym._heads)) + list(replaced.values()):
        n.inputs = [(replaced.get(id(i), i), idx) for i, idx in n.inputs]
    sym._heads = [(replaced.get(id(n), n), i) for n, i in sym._heads]
    return sym


# --------------------------------------------------------------------------
# built-in backend: operator-level fusions useful on the eager Executor
# --------------------------------------------------------------------------
@register("_sg_fused_dense_act")
def _sg_fused_dense_act(x, weight, *maybe_bias, num_hidden=None,
                        no_bias=False, flatten=True, act_type="relu"):
    """FullyConnected+Activation as one op (subgraph 'default' backend)."""
    fc = OP_TABLE["FullyConnected"].fn
    act = OP_TABLE["Activation"].fn
    return act(fc(x, weight, *maybe_bias, num_hidden=num_hidden,
                  no_bias=no_bias, flatten=flatten), act_type=act_type)


@register("_sg_fused_conv_act")
def _sg_fused_conv_act(x, weight, *maybe_bias, kernel=None, stride=None,
                       dilate=None, pad=None, num_filter=None, num_group=1,
                       no_bias=False, layout=None, cudnn_tune=None,
                       cudnn_off=None, workspace=None, act_type="relu"):
    """Convolution+Activation as one op (subgraph 'default' backend)."""
    conv = OP_TABLE["Convolution"].fn
    act = OP_TABLE["Activation"].fn
    return act(conv(x, weight, *maybe_bias, kernel=kernel, stride=stride,
                    dilate=dilate, pad=pad, num_filter=num_filter,
                    num_group=num_group, no_bias=no_bias, layout=layout),
               act_type=act_type)


def _is_op(*names):
    s = set(names)
    return lambda n: n.op in s


@register_pass("default")
def fuse_dense_activation(sym):
    return fuse_linear_chain(
        sym, [_is_op("FullyConnected"), _is_op("Activation", "activation")],
        "_sg_fused_dense_act")


@register_pass("default")
def fuse_conv_activation(sym):
    return fuse_linear_chain(
        sym, [_is_op("Convolution"), _is_op("Activation", "activation")],
        "_sg_fused_conv_act")


# shape inference for the fused nodes reuses the base op's param rules
from .symbol import symbol as _symbol_mod  # noqa: E402

_symbol_mod._OP_SHAPE_HINT_ALIASES["_sg_fused_dense_act"] = "FullyConnected"
_symbol_mod._OP_SHAPE_HINT_ALIASES["_sg_fused_conv_act"] = "Convolution"
_symbol_mod._OP_PARAM_VARS["_sg_fused_dense_act"] = \
    _symbol_mod._OP_PARAM_VARS["FullyConnected"]
_symbol_mod._OP_PARAM_VARS["_sg_fused_conv_act"] = \
    _symbol_mod._OP_PARAM_VARS["Convolution"]

# the reference ships MKLDNN as its always-available backend; ours is the
# XLA-oriented 'default' — register the reference names as aliases so
# scripts that say optimize_for('MKLDNN') keep working
register_backend("MKLDNN", _BACKENDS["default"])
register_backend("ONEDNN", _BACKENDS["default"])
