"""Deterministic fault injection + transient-error retry policy.

Reference scope: MXNet 1.x's production fault story is ps-lite's
supervised worker restart (SURVEY.md §6.3) — failures are absorbed by an
external scheduler and never exercised in-tree.  The TPU reproduction
replaces that with in-process failure domains (CheckpointManager,
run_with_recovery, the DataLoader process pool, jax.distributed), which
means the failure paths live HERE and must be testable HERE.  This module
is the single seam through which every failure domain can be (a) tripped
deterministically in tests/CI and (b) retried with one shared backoff
policy, the failure classes preemptible multi-slice TPU jobs see
constantly (PAPERS.md: EQuARX-style multi-slice training assumes the
framework absorbs transient interconnect errors).

Seams (each named check-point is called on the real code path):

==========================  =================================================
``checkpoint.write``        payload file writing inside CheckpointManager.save
``checkpoint.fsync``        per-file durability fsync before the commit marker
``checkpoint.publish``      the atomic tmp -> step_N rename
``dataloader.worker``       inside a DataLoader process worker, per batch
``kvstore.push``            KVStore.push entry (host-side transport seam)
``kvstore.pull``            KVStore.pull entry (host-side transport seam)
``collectives.allreduce``   host-value cross-process collectives
``distributed.init``        jax.distributed coordinator rendezvous
``lifecycle.sigterm``       step-boundary stop poll (an armed fault is
                            treated as a delivered preemption signal)
``watchdog.stall``          watchdog poll (an armed fault is treated as an
                            expired step deadline)
``serving.admit``           serving-engine request admission (a tripped
                            admit requeues the request; nothing is lost)
``serving.decode_step``     serving-engine batched decode step, checked
                            BEFORE any KV/sequence mutation (the loop
                            absorbs the failure and retries the step)
``resharding.transfer``     live-resharding transfer execution (the
                            transfer is pure w.r.t. its inputs, so a trip
                            costs one supervised retry, never torn state)
``router.dispatch``         fleet router -> replica request transport (a
                            trip looks like a replica-side network error;
                            the dispatch retry/hedge/resubmit machinery
                            absorbs it)
``router.health_probe``     fleet router health poll of a replica (a trip
                            counts as a missed heartbeat and drives the
                            HEALTHY -> SUSPECT -> EJECTED state machine)
``fleet.spawn``             replacement-replica spawn inside the fleet
                            manager (a trip fails the spawn attempt; the
                            manager retries under the shared policy)
``replica.crash``           replica-side crash point checked in the fleet
                            request loop (an armed trip kills the replica
                            mid-request, exercising detect + resubmit)
``guard.check``             numerical-integrity sentinel check (a trip
                            surfaces before the verdict collective, so no
                            peer is left waiting on a half-issued
                            agreement)
``guard.rewind``            guard remediation rewind to the latest valid
                            checkpoint (a trip leaves the run on its
                            current state; the next anomalous verdict
                            re-triggers)
``guard.canary``            deterministic canary-microbatch recompute +
                            cross-rank digest vote (checked before the
                            recompute — a trip skips this vote round
                            uniformly)
==========================  =================================================

Arming faults:

- env spec (survives process boundaries — spawn'd DataLoader workers
  inherit it): ``MXNET_FAULT_SPEC=checkpoint.write:fail:2`` fails the
  first 2 calls with OSError.  Comma-separate multiple entries; an
  optional 4th field names the exception class
  (``kvstore.push:fail:1:TimeoutError``).
- test context manager::

      with fault.inject("kvstore.push", error=OSError, times=1):
          kv.push(...)   # first call trips, retry absorbs it

Observability: ``fault.stats()`` returns per-seam
``{"calls", "trips", "retries"}`` counters; the profiler surfaces the
same table (``profiler.dumps()`` "Fault seams" section and the trace
file's otherData).

Retry policy: ``call_with_retries(seam, fn, ...)`` retries *transient*
errors (OSError and the jax/gRPC unavailable family) with exponential
backoff + full jitter, bounded by ``MXNET_FAULT_MAX_RETRIES`` (default 3)
and seeded at ``MXNET_FAULT_BACKOFF_MS`` (default 100); exhaustion raises
``MXNetError`` naming the seam and the knobs.
"""
from __future__ import annotations

import contextlib
import logging
import random as _random
import threading
import time

from . import env
from .base import MXNetError

__all__ = ["SEAMS", "check", "guard", "inject", "stats", "reset_stats",
           "reload_spec", "call_with_retries", "is_transient",
           "max_retries", "backoff_ms", "backoff_delay"]

SEAMS = ("checkpoint.write", "checkpoint.fsync", "checkpoint.publish",
         "dataloader.worker", "kvstore.push", "kvstore.pull",
         "collectives.allreduce", "distributed.init",
         "lifecycle.sigterm", "watchdog.stall",
         "serving.admit", "serving.decode_step", "resharding.transfer",
         "router.dispatch", "router.health_probe", "fleet.spawn",
         "replica.crash", "guard.check", "guard.rewind", "guard.canary")

_LOGGER = logging.getLogger(__name__)
_LOCK = threading.Lock()

# seam -> list of armed plans, consumed front-first.  A plan is a dict
# {"remaining": int, "error": type, "message": str}; env-spec plans and
# inject() plans share the list (inject pushes, env spec seeds).
_PLANS: dict = {}
_STATS = {s: {"calls": 0, "trips": 0, "retries": 0} for s in SEAMS}
_SPEC_LOADED = False

_ERROR_NAMES = {
    "OSError": OSError, "IOError": OSError, "ConnectionError":
    ConnectionError, "ConnectionResetError": ConnectionResetError,
    "TimeoutError": TimeoutError, "RuntimeError": RuntimeError,
    "ValueError": ValueError, "MXNetError": MXNetError,
}


def max_retries():
    """Bounded retry budget for transient errors
    (MXNET_FAULT_MAX_RETRIES, default 3)."""
    return max(0, env.get_int("MXNET_FAULT_MAX_RETRIES", 3))


def backoff_ms():
    """First-retry backoff in milliseconds; doubles per retry with full
    jitter (MXNET_FAULT_BACKOFF_MS, default 100)."""
    return max(0, env.get_int("MXNET_FAULT_BACKOFF_MS", 100))


def _parse_spec(spec):
    """``seam:mode:times[:Error][,...]`` -> {seam: [plan, ...]}.

    Unknown seams/modes/error names warn and are skipped — a typo'd spec
    must not silently disable the run's intended chaos NOR crash it."""
    plans: dict = {}
    for entry in (spec or "").split(","):
        entry = entry.strip()
        if not entry:
            continue
        parts = entry.split(":")
        if len(parts) < 2 or parts[0] not in SEAMS or parts[1] != "fail":
            _LOGGER.warning("MXNET_FAULT_SPEC entry %r ignored (want "
                            "<seam>:fail[:times[:Error]] with seam in %s)",
                            entry, "/".join(SEAMS))
            continue
        try:
            times = int(parts[2]) if len(parts) > 2 and parts[2] else 1
        except ValueError:
            _LOGGER.warning("MXNET_FAULT_SPEC entry %r ignored (bad count)",
                            entry)
            continue
        error = _ERROR_NAMES.get(parts[3]) if len(parts) > 3 else OSError
        if error is None:
            _LOGGER.warning("MXNET_FAULT_SPEC entry %r ignored (unknown "
                            "error %r; known: %s)", entry, parts[3],
                            "/".join(sorted(_ERROR_NAMES)))
            continue
        plans.setdefault(parts[0], []).append(
            {"remaining": times, "error": error,
             "message": f"injected fault ({entry})"})
    return plans


def _ensure_spec_loaded():
    global _SPEC_LOADED
    if _SPEC_LOADED:
        return
    with _LOCK:
        if _SPEC_LOADED:
            return
        spec = env.get_str("MXNET_FAULT_SPEC")
        if spec:
            for seam, plans in _parse_spec(spec).items():
                _PLANS.setdefault(seam, []).extend(plans)
        _SPEC_LOADED = True


def reload_spec():
    """Drop all armed plans (env + inject) and re-read MXNET_FAULT_SPEC.
    Tests use this after monkeypatching the env var."""
    global _SPEC_LOADED
    with _LOCK:
        _PLANS.clear()
        _SPEC_LOADED = False
    _ensure_spec_loaded()


def check(seam):
    """The seam hook: called on the real code path.  Counts the call and
    raises the armed error while a plan has trips remaining."""
    if seam not in _STATS:
        raise MXNetError(f"unknown fault seam {seam!r}; known: "
                         f"{', '.join(SEAMS)}")
    _ensure_spec_loaded()
    with _LOCK:
        _STATS[seam]["calls"] += 1
        plans = _PLANS.get(seam)
        while plans:
            if plans[0]["remaining"] <= 0:
                plans.pop(0)
                continue
            plans[0]["remaining"] -= 1
            _STATS[seam]["trips"] += 1
            plan = plans[0]
            break
        else:
            return
    _flight_trip(seam, plan["message"])
    raise plan["error"](plan["message"])


def _flight_trip(seam, message):
    """Seam trip → flight-recorder context event (lazy + tolerant: the
    recorder is observability, a broken import must not change what the
    seam raises)."""
    try:
        from . import flight_recorder as _flight

        _flight.record_event("fault", seam=seam, message=str(message))
    except Exception:
        pass


@contextlib.contextmanager
def inject(seam, error=OSError, times=1, message=None):
    """Arm ``seam`` to raise ``error`` for the next ``times`` calls
    (within this process).  Disarms on exit even if untripped."""
    if seam not in _STATS:
        raise MXNetError(f"unknown fault seam {seam!r}; known: "
                         f"{', '.join(SEAMS)}")
    plan = {"remaining": times, "error": error,
            "message": message or f"injected fault at {seam}"}
    with _LOCK:
        _PLANS.setdefault(seam, []).append(plan)
    try:
        yield plan
    finally:
        with _LOCK:
            plans = _PLANS.get(seam, [])
            if plan in plans:
                plans.remove(plan)


def stats():
    """Per-seam counters: ``{seam: {"calls", "trips", "retries"}}``."""
    with _LOCK:
        return {s: dict(c) for s, c in _STATS.items()}


def reset_stats():
    with _LOCK:
        for c in _STATS.values():
            c.update(calls=0, trips=0, retries=0)


# -- transient-error retry policy ------------------------------------------
_TRANSIENT_MARKERS = ("unavailable", "deadline exceeded", "deadline_exceeded",
                      "connection reset", "connection refused",
                      "failed to connect", "socket closed", "broken pipe",
                      "preempt")


def is_transient(exc):
    """Errors worth retrying: host/network OSErrors and the jax/gRPC
    unavailable family, matched by MESSAGE — jaxlib's XlaRuntimeError
    carries the gRPC status in the text, and the same class also wraps
    permanent failures (INVALID_ARGUMENT, compile errors) that a retry
    can never fix.  MXNetError is never transient: it is this layer's
    own verdict."""
    if isinstance(exc, MXNetError):
        return False
    if isinstance(exc, (OSError, ConnectionError, TimeoutError)):
        return True
    msg = str(exc).lower()
    return any(m in msg for m in _TRANSIENT_MARKERS)


def backoff_delay(attempt, base_ms):
    """Delay in seconds for retry/restart number ``attempt`` (0-based):
    exponential with FULL jitter (uniform in [0, cap], cap doubling from
    ``base_ms`` up to 30s) — thundering herds of restarting workers must
    not re-synchronize on the coordinator.  Shared by the seam retries
    here and by checkpoint.run_with_recovery's restart pacing."""
    cap = min(base_ms * (2 ** attempt), 30_000) / 1000.0
    return _random.uniform(0.0, cap) if cap > 0 else 0.0


def _sleep_backoff(seam, attempt, base_ms, logger, exc):
    delay = backoff_delay(attempt, base_ms)
    (logger or _LOGGER).warning(
        "%s: transient failure (%r), retry %d in %.3fs",
        seam, exc, attempt + 1, delay)
    if delay > 0:
        time.sleep(delay)


def call_with_retries(seam, fn, *args, retries=None, base_ms=None,
                      logger=None, **kwargs):
    """Run ``fn(*args, **kwargs)`` through seam ``seam`` with bounded
    retries of transient errors (is_transient); injection at the seam is
    part of the retried region, so an armed transient fault is absorbed
    exactly like a real one.  Exhaustion raises MXNetError naming the
    seam; non-transient errors propagate immediately."""
    attempt = 0
    while True:
        try:
            check(seam)
            return fn(*args, **kwargs)
        except BaseException as e:
            if not is_transient(e):
                raise
            # knobs resolve lazily, on the FIRST failure: the happy path
            # (every production call with no fault) pays no environ reads
            if retries is None:
                retries = max_retries()
            if base_ms is None:
                base_ms = backoff_ms()
            if attempt >= retries:
                raise MXNetError(
                    f"{seam}: giving up after {retries} retries "
                    f"(last error: {e!r}); tune MXNET_FAULT_MAX_RETRIES / "
                    f"MXNET_FAULT_BACKOFF_MS") from e
            with _LOCK:
                _STATS[seam]["retries"] += 1
            _sleep_backoff(seam, attempt, base_ms, logger, e)
            attempt += 1


def _noop():
    return None


def guard(seam, **kwargs):
    """Pure seam guard: no payload function, just the injection point run
    under the retry policy.  Code paths whose real transport retry lives
    at a lower layer (e.g. kvstore push/pull over the collectives seam)
    use this so the harness can still trip and exercise them.

    Sits on hot paths (every kvstore push/pull), so the disarmed case is
    just a counter bump — no retry scaffolding, no environ reads."""
    _ensure_spec_loaded()
    if not _PLANS.get(seam):
        if seam not in _STATS:
            raise MXNetError(f"unknown fault seam {seam!r}; known: "
                             f"{', '.join(SEAMS)}")
        with _LOCK:
            _STATS[seam]["calls"] += 1
        return
    call_with_retries(seam, _noop, **kwargs)
