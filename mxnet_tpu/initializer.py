"""Weight initializers.

Reference: ``python/mxnet/initializer.py`` (Xavier, MSRAPrelu, Uniform,
Normal, Orthogonal, Bilinear, Constant, Mixed, registry + name-pattern
dispatch).  Draws use the global RNG (mxnet_tpu.random).
"""
from __future__ import annotations

import math
import re

import numpy as _np

from .base import Registry

__all__ = ["Initializer", "Zero", "One", "Constant", "Uniform", "Normal",
           "Orthogonal", "Xavier", "MSRAPrelu", "Bilinear", "LSTMBias",
           "Mixed", "register", "create"]

_REG = Registry("initializer")


def register(cls):
    _REG.register(cls)
    return cls


def create(name, **kwargs):
    if isinstance(name, Initializer):
        return name
    return _REG.create(name, **kwargs)


def _create_from_dumps(s):
    """Rebuild an initializer from ``Initializer.dumps()`` JSON (the string
    form a variable's ``__init__`` attr serializes to) or a bare name."""
    import json

    try:
        payload = json.loads(s)
    except (TypeError, ValueError):
        return create(str(s))
    if isinstance(payload, list) and payload:
        return create(payload[0], **(payload[1] if len(payload) > 1 else {}))
    return create(str(payload))


class InitDesc(str):
    """Parameter name + attrs hint (reference: mxnet.initializer.InitDesc)."""

    def __new__(cls, name, attrs=None, global_init=None):
        s = super().__new__(cls, name)
        s.attrs = attrs or {}
        s.global_init = global_init
        return s


class Initializer:
    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def __call__(self, desc, arr):
        # per-variable override: sym.var(..., init=...) lands in the
        # variable's attrs as "__init__" (reference: Initializer.__call__
        # honoring InitDesc.attrs['__init__'], initializer.py upstream)
        override = getattr(desc, "attrs", None)
        override = override.get("__init__") if override else None
        if override is not None and override is not self:
            init = override if isinstance(override, Initializer) else \
                _create_from_dumps(override)
            # call the payload directly — re-dispatching by name suffix
            # would send e.g. an LSTMBias'd *_bias var back to _init_zero
            init._init_weight(str(desc), arr)
            return
        if not isinstance(desc, str):
            desc = str(desc)
        name = desc.lower()
        if name.endswith("weight"):
            self._init_weight(desc, arr)
        elif name.endswith("bias"):
            self._init_zero(desc, arr)
        elif name.endswith("gamma"):
            self._init_one(desc, arr)
        elif name.endswith("beta"):
            self._init_zero(desc, arr)
        elif name.endswith("running_mean") or name.endswith("moving_mean"):
            self._init_zero(desc, arr)
        elif name.endswith("running_var") or name.endswith("moving_var"):
            self._init_one(desc, arr)
        else:
            self._init_default(desc, arr)

    def init_weight(self, name, arr):
        self._init_weight(name, arr)

    def _init_zero(self, name, arr):
        arr[:] = 0.0

    def _init_one(self, name, arr):
        arr[:] = 1.0

    def _init_weight(self, name, arr):
        raise NotImplementedError

    def _init_default(self, name, arr):
        self._init_weight(name, arr)

    def __repr__(self):
        return f"{type(self).__name__}({self._kwargs})"

    def dumps(self):
        import json

        return json.dumps([type(self).__name__.lower(), self._kwargs])


def _rand_uniform(shape, scale, dtype):
    from . import random as _rnd
    from jax import random as jr

    return jr.uniform(_rnd._next_key(), shape, minval=-scale, maxval=scale
                      ).astype(dtype)


def _rand_normal(shape, sigma, dtype):
    from . import random as _rnd
    from jax import random as jr

    return jr.normal(_rnd._next_key(), shape).astype(dtype) * sigma


class Zero(Initializer):
    def _init_weight(self, name, arr):
        arr[:] = 0.0


class One(Initializer):
    def _init_weight(self, name, arr):
        arr[:] = 1.0


_REG.register(Zero, aliases=("zeros",))
_REG.register(One, aliases=("ones",))


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, name, arr):
        arr[:] = self.value


@register
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, name, arr):
        arr._set(_rand_uniform(arr.shape, self.scale, arr.dtype))


@register
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, name, arr):
        arr._set(_rand_normal(arr.shape, self.sigma, arr.dtype))


@register
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale

    def _init_weight(self, name, arr):
        shape = arr.shape
        flat = (shape[0], int(_np.prod(shape[1:])) if len(shape) > 1 else 1)
        a = _np.random.normal(0.0, 1.0, flat)
        u, _, vt = _np.linalg.svd(a, full_matrices=False)
        q = u if u.shape == flat else vt
        arr[:] = (self.scale * q.reshape(shape)).astype(arr.dtype)


def _fan(shape):
    hw = int(_np.prod(shape[2:])) if len(shape) > 2 else 1
    fan_in = shape[1] * hw if len(shape) > 1 else shape[0]
    fan_out = shape[0] * hw
    return fan_in, fan_out


@register
class Xavier(Initializer):
    """Reference: mxnet.initializer.Xavier (gaussian/uniform, avg/in/out)."""

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type,
                         magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, name, arr):
        fan_in, fan_out = _fan(arr.shape)
        if self.factor_type == "avg":
            factor = (fan_in + fan_out) / 2.0
        elif self.factor_type == "in":
            factor = fan_in
        else:
            factor = fan_out
        scale = math.sqrt(self.magnitude / max(factor, 1.0))
        if self.rnd_type == "uniform":
            arr._set(_rand_uniform(arr.shape, scale, arr.dtype))
        else:
            arr._set(_rand_normal(arr.shape, scale, arr.dtype))


@register
class MSRAPrelu(Xavier):
    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__(rnd_type="gaussian", factor_type=factor_type,
                         magnitude=magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Bilinear(Initializer):
    def _init_weight(self, name, arr):
        weight = _np.zeros(arr.shape, dtype="float32")
        shape = arr.shape
        f = math.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(_np.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight.flat[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        arr[:] = weight


@register
class LSTMBias(Initializer):
    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, name, arr):
        import numpy as np

        a = np.zeros(arr.shape, dtype="float32")
        n = arr.shape[0] // 4
        a[n:2 * n] = self.forget_bias
        arr[:] = a


class Mixed:
    def __init__(self, patterns, initializers):
        self.map = list(zip([re.compile(p) for p in patterns], initializers))

    def __call__(self, name, arr):
        for prog, init in self.map:
            if prog.match(str(name)):
                init(name, arr)
                return
        raise ValueError(f"parameter {name} did not match any pattern")
