// Native RecordIO reader + threaded prefetcher.
//
// Reference: the C++ data-input layer of MXNet 1.x —
// dmlc::RecordIOReader (3rdparty/dmlc-core/include/dmlc/recordio.h),
// the shard-partitioned parser (src/io/iter_image_recordio_2.cc) and the
// dmlc::ThreadedIter double-buffering (SURVEY.md §3.4, §4.5).  Rebuilt
// TPU-native rather than translated: this library owns file IO, record
// scanning (magic + length framing), num_parts/part_index sharding,
// epoch shuffling and a background prefetch thread with a bounded batch
// queue; decode/augment stays in Python (PIL/numpy) where the GIL-free
// IO overlap is what matters for feeding a chip.
//
// Exposed as a C ABI for ctypes (the reference's C API pattern, §3.1).
#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <condition_variable>
#include <deque>
#include <mutex>
#include <random>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0xced7230a;

struct RecordRef {
  uint64_t offset;  // payload start
  uint32_t length;  // payload bytes
};

struct Batch {
  std::vector<uint8_t> data;      // concatenated payloads
  std::vector<uint64_t> lengths;  // per-record lengths
};

class Reader {
 public:
  Reader(const char* path, int batch_size, int num_parts, int part_index,
         int shuffle, uint64_t seed, int queue_depth)
      : path_(path),
        batch_size_(batch_size),
        shuffle_(shuffle),
        seed_(seed),
        queue_depth_(queue_depth < 1 ? 2 : queue_depth) {
    ScanOffsets();
    // shard: contiguous range per part (reference: num_parts/part_index)
    size_t n = records_.size();
    size_t per = (n + num_parts - 1) / num_parts;
    size_t begin = per * part_index;
    size_t end = begin + per < n ? begin + per : n;
    if (begin > n) begin = n;
    shard_.assign(records_.begin() + begin, records_.begin() + end);
    order_.resize(shard_.size());
    for (size_t i = 0; i < shard_.size(); ++i) order_[i] = i;
    StartEpoch(0);
    worker_ = std::thread([this] { this->WorkerLoop(); });
  }

  ~Reader() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      stop_ = true;
    }
    cv_space_.notify_all();
    cv_data_.notify_all();
    if (worker_.joinable()) worker_.join();
  }

  int64_t num_records() const { return static_cast<int64_t>(shard_.size()); }
  int64_t read_errors() const {
    return read_errors_.load(std::memory_order_relaxed);
  }

  bool open_ok() const { return open_ok_; }

  void Reset(uint64_t epoch) {
    {
      std::lock_guard<std::mutex> lk(mu_);
      pending_reset_ = true;
      reset_epoch_ = epoch;
      queue_.clear();
      // clear immediately so a next_batch racing the worker blocks for the
      // new epoch instead of reporting a stale end-of-epoch
      epoch_done_in_queue_ = false;
    }
    cv_space_.notify_all();
  }

  // Returns 0 on success, 1 on end-of-epoch. Caller frees nothing; the
  // returned pointers are valid until the next NextBatch/Reset call on the
  // SAME handle (data is moved into current_).
  int NextBatch(const uint8_t** data, const uint64_t** lengths,
                uint64_t* n_records, uint64_t* total_bytes) {
    std::unique_lock<std::mutex> lk(mu_);
    cv_data_.wait(lk, [this] {
      return stop_ || !queue_.empty() || epoch_done_in_queue_;
    });
    if (queue_.empty()) {
      // epoch exhausted; flag stays set until Reset so repeated calls
      // keep returning end-of-epoch instead of blocking
      return 1;
    }
    current_ = std::move(queue_.front());
    queue_.pop_front();
    lk.unlock();
    cv_space_.notify_one();
    *data = current_.data.data();
    *lengths = current_.lengths.data();
    *n_records = current_.lengths.size();
    *total_bytes = current_.data.size();
    return 0;
  }

 private:
  void ScanOffsets() {
    FILE* f = std::fopen(path_.c_str(), "rb");
    if (!f) return;
    open_ok_ = true;
    uint32_t header[2];
    uint64_t pos = 0;
    while (std::fread(header, sizeof(uint32_t), 2, f) == 2) {
      pos += 8;
      if (header[0] != kMagic) break;  // corrupt / unsupported framing
      uint32_t len = header[1] & ((1u << 29) - 1);
      // cflag (upper 3 bits) nonzero = multi-chunk; single-chunk records
      // only (what our writer and the common im2rec output produce)
      records_.push_back({pos, len});
      uint64_t padded = (len + 3u) & ~3u;
      if (std::fseek(f, static_cast<long>(padded), SEEK_CUR) != 0) break;
      pos += padded;
    }
    std::fclose(f);
  }

  void StartEpoch(uint64_t epoch) {
    cursor_ = 0;
    if (shuffle_) {
      std::mt19937_64 rng(seed_ + epoch);
      for (size_t i = order_.size(); i > 1; --i) {
        std::swap(order_[i - 1], order_[rng() % i]);
      }
    }
  }

  void WorkerLoop() {
    FILE* f = std::fopen(path_.c_str(), "rb");
    if (!f) return;
    for (;;) {
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_space_.wait(lk, [this] {
          return stop_ || pending_reset_ ||
                 queue_.size() < static_cast<size_t>(queue_depth_);
        });
        if (stop_) break;
        if (pending_reset_) {
          pending_reset_ = false;
          epoch_done_in_queue_ = false;
          StartEpoch(reset_epoch_);
        }
        if (cursor_ >= order_.size()) {
          // nothing left this epoch; signal and wait for reset
          epoch_done_in_queue_ = true;
          cv_data_.notify_all();
          cv_space_.wait(lk, [this] { return stop_ || pending_reset_; });
          continue;
        }
      }
      // assemble one batch outside the lock
      Batch b;
      size_t take;
      std::vector<RecordRef> refs;
      {
        std::lock_guard<std::mutex> lk(mu_);
        take = std::min<size_t>(batch_size_, order_.size() - cursor_);
        for (size_t i = 0; i < take; ++i) {
          refs.push_back(shard_[order_[cursor_ + i]]);
        }
        cursor_ += take;
      }
      for (const auto& r : refs) {
        size_t old = b.data.size();
        b.data.resize(old + r.length);
        if (std::fseek(f, static_cast<long>(r.offset), SEEK_SET) != 0 ||
            std::fread(b.data.data() + old, 1, r.length, f) != r.length) {
          // truncated/unreadable record: drop the partial bytes so the
          // batch stays self-consistent, and count the error so the Python
          // side can surface it instead of silently losing records
          b.data.resize(old);
          read_errors_.fetch_add(1, std::memory_order_relaxed);
          break;
        }
        b.lengths.push_back(r.length);
      }
      {
        std::lock_guard<std::mutex> lk(mu_);
        if (stop_) break;
        // a Reset may have raced the assembly above: this batch belongs to
        // the old epoch — drop it rather than leak it into the new one
        if (pending_reset_) continue;
        queue_.push_back(std::move(b));
      }
      cv_data_.notify_one();
    }
    std::fclose(f);
  }

  std::string path_;
  size_t batch_size_;
  int shuffle_;
  uint64_t seed_;
  int queue_depth_;
  bool open_ok_ = false;
  std::vector<RecordRef> records_;
  std::vector<RecordRef> shard_;
  std::vector<size_t> order_;
  size_t cursor_ = 0;

  std::atomic<int64_t> read_errors_{0};

  std::mutex mu_;
  std::condition_variable cv_data_, cv_space_;
  std::deque<Batch> queue_;
  Batch current_;
  bool stop_ = false;
  bool pending_reset_ = false;
  bool epoch_done_in_queue_ = false;
  uint64_t reset_epoch_ = 0;
  std::thread worker_;
};

}  // namespace

extern "C" {

void* mxtpu_reader_create(const char* path, int batch_size, int num_parts,
                          int part_index, int shuffle, uint64_t seed,
                          int queue_depth) {
  Reader* r = new Reader(path, batch_size, num_parts, part_index, shuffle,
                         seed, queue_depth);
  if (!r->open_ok()) {
    delete r;
    return nullptr;
  }
  return r;
}

void mxtpu_reader_free(void* handle) { delete static_cast<Reader*>(handle); }

int64_t mxtpu_reader_num_records(void* handle) {
  return static_cast<Reader*>(handle)->num_records();
}

void mxtpu_reader_reset(void* handle, uint64_t epoch) {
  static_cast<Reader*>(handle)->Reset(epoch);
}

int mxtpu_reader_next_batch(void* handle, const uint8_t** data,
                            const uint64_t** lengths, uint64_t* n_records,
                            uint64_t* total_bytes) {
  return static_cast<Reader*>(handle)->NextBatch(data, lengths, n_records,
                                                 total_bytes);
}

int64_t mxtpu_reader_read_errors(void* handle) {
  return static_cast<Reader*>(handle)->read_errors();
}

}  // extern "C"
