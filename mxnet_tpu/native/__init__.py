"""Native (C++) runtime components, loaded via ctypes.

Reference: MXNet's C++ data-input layer (src/io/, dmlc ThreadedIter —
SURVEY.md §3.4).  The shared library is compiled on first use with the
system toolchain and cached next to the source; `ctypes` is the binding
layer (no pybind11 in this environment).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

from ..base import MXNetError

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "recordio_reader.cc")
_LIB_PATH = os.path.join(_HERE, "libmxtpu_io.so")
_lock = threading.Lock()
_lib = None


def _build():
    cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
           _SRC, "-o", _LIB_PATH]
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
    except (subprocess.CalledProcessError, FileNotFoundError) as e:
        err = getattr(e, "stderr", str(e))
        raise MXNetError(f"failed to build native IO library: {err}") from e


def get_lib():
    """Load (building if needed) the native IO library."""
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        if (not os.path.exists(_LIB_PATH)
                or os.path.getmtime(_LIB_PATH) < os.path.getmtime(_SRC)):
            _build()
        lib = ctypes.CDLL(_LIB_PATH)
        lib.mxtpu_reader_create.restype = ctypes.c_void_p
        lib.mxtpu_reader_create.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_uint64, ctypes.c_int]
        lib.mxtpu_reader_free.argtypes = [ctypes.c_void_p]
        lib.mxtpu_reader_num_records.restype = ctypes.c_int64
        lib.mxtpu_reader_num_records.argtypes = [ctypes.c_void_p]
        lib.mxtpu_reader_reset.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.mxtpu_reader_next_batch.restype = ctypes.c_int
        lib.mxtpu_reader_next_batch.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint64)),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64)]
        lib.mxtpu_reader_read_errors.restype = ctypes.c_int64
        lib.mxtpu_reader_read_errors.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


class NativeRecordReader:
    """Threaded, sharded, shuffling RecordIO batch reader (C++ backend)."""

    def __init__(self, path, batch_size, num_parts=1, part_index=0,
                 shuffle=False, seed=0, queue_depth=4):
        self._lib = get_lib()
        self._handle = self._lib.mxtpu_reader_create(
            path.encode(), int(batch_size), int(num_parts), int(part_index),
            1 if shuffle else 0, int(seed), int(queue_depth))
        if not self._handle:
            raise MXNetError(f"cannot open record file {path}")
        self._epoch = 0

    @property
    def num_records(self):
        return self._lib.mxtpu_reader_num_records(self._handle)

    @property
    def read_errors(self):
        """Count of records dropped due to truncated/unreadable file data."""
        return self._lib.mxtpu_reader_read_errors(self._handle)

    def reset(self):
        self._epoch += 1
        if self.read_errors:
            raise MXNetError(
                f"{self.read_errors} record(s) could not be read (truncated "
                "or corrupt record file)")
        self._lib.mxtpu_reader_reset(self._handle, self._epoch)

    def next_batch(self):
        """Returns a list of bytes payloads, or None at end of epoch."""
        data = ctypes.POINTER(ctypes.c_uint8)()
        lengths = ctypes.POINTER(ctypes.c_uint64)()
        n = ctypes.c_uint64()
        total = ctypes.c_uint64()
        rc = self._lib.mxtpu_reader_next_batch(
            self._handle, ctypes.byref(data), ctypes.byref(lengths),
            ctypes.byref(n), ctypes.byref(total))
        if rc != 0:
            return None
        out = []
        buf = ctypes.cast(data,
                          ctypes.POINTER(ctypes.c_uint8 * total.value))
        raw = bytes(buf.contents) if total.value else b""
        off = 0
        for i in range(n.value):
            ln = lengths[i]
            out.append(raw[off:off + ln])
            off += ln
        return out

    def __del__(self):
        try:
            if getattr(self, "_handle", None):
                self._lib.mxtpu_reader_free(self._handle)
                self._handle = None
        except Exception:
            pass
