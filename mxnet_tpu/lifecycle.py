"""Preemption-safe training lifecycle: graceful shutdown, exact-resume
training state, and a stall watchdog.

Production TPU jobs run on preemptible pods: the scheduler delivers
SIGTERM, waits a grace period, then SIGKILLs.  The recovery story built
so far (fault seams, sha256 checkpoints, ``run_with_recovery``) resumes
*approximately* — a restart replays or skips data because iterator
position, shuffle RNG, loss-scaler state and step counters were not
checkpointed, and a hung SPMD collective (one peer re-issues, the mesh
deadlocks — see parallel/collectives.py) stalls the job silently.  This
module closes the three lifecycle gaps so a preempted or stalled job
costs bounded wall-time and resumes bit-identically:

- **Graceful preemption** — ``install_signal_handlers()`` (SIGTERM/
  SIGINT) or programmatic ``request_stop(reason)`` set a stop flag that
  training loops (``Estimator.fit``, ``TrainStep.run``, and any
  ``run_with_recovery`` train_fn) poll at step boundaries via
  ``check_stop()``.  In a multi-process job the flag is *agreed* through
  a one-scalar all-reduce so every SPMD peer exits at the same step — a
  unilateral exit would strand the peers in their next collective.  The
  loop then publishes a final synchronous checkpoint (unless
  ``MXNET_PREEMPTION_CHECKPOINT=0``) and raises :class:`GracefulExit`,
  which ``run_with_recovery`` re-raises WITHOUT counting it against the
  restart budget; callers exit with :data:`EXIT_PREEMPTED` so the
  supervisor can tell "preempted clean" from "crashed".  A configured
  ``MXNET_GRACE_PERIOD_S`` arms a deadline: if the loop has not honored
  the stop when it expires, the process force-exits (the scheduler's
  SIGKILL would land mid-write otherwise).
- **Exact-resume training state** — ``capture_train_state()`` bundles
  the DataLoader/sampler position (epoch, batch index, shuffle seed —
  restored with a decode-free fast-forward), the ``mx.random`` global
  RNG state, ``LossScaler`` scale/skip counters, and Estimator/Trainer
  step counters; ``CheckpointManager.save(..., train_state=...)``
  persists it (sha256-summed like every payload) and
  ``restore_train_state()`` re-applies it, making a resumed run's batch
  sequence and loss trajectory bit-identical to an uninterrupted run.
- **Stall watchdog** — :class:`Watchdog` is a daemon thread fed by the
  telemetry step heartbeat (``telemetry.heartbeat()``, beaten by
  ``step_begin``/``step_end`` and by every ``check_stop()``).  When no
  heartbeat lands within ``MXNET_WATCHDOG_TIMEOUT_S`` (default off) it
  dumps all-thread stacks + a telemetry snapshot to a diagnosis file,
  increments ``mxnet_watchdog_stalls_total``, and (configurably,
  ``MXNET_WATCHDOG_ABORT``) aborts the process so the external
  supervisor restarts from the last valid checkpoint instead of hanging
  until an external timeout.

Chaos seams: arming ``lifecycle.sigterm`` (``MXNET_FAULT_SPEC`` or
``fault.inject``) makes the next ``check_stop()`` behave as if a SIGTERM
arrived; arming ``watchdog.stall`` makes the watchdog treat its next
poll as an expired deadline — both paths are deterministically testable
without real signals or real wall-clock stalls.
"""
from __future__ import annotations

import json
import logging
import os
import sys
import threading
import time
import traceback

from . import env as _env
from . import fault
from . import flight_recorder as _flight
from . import telemetry

__all__ = ["GracefulExit", "EXIT_PREEMPTED", "EXIT_FORCED", "EXIT_STALLED",
           "request_stop", "stop_requested", "stop_reason", "check_stop",
           "coordinate_stops", "install_signal_handlers",
           "uninstall_signal_handlers", "cancel_grace_deadline",
           "publish_final_checkpoint", "note_goodput_slo_breach",
           "note_ledger_skew", "register_goodput_breach_hook",
           "unregister_goodput_breach_hook", "note_fleet_queue_slo_breach",
           "capture_train_state", "restore_train_state",
           "elastic_resharder",
           "Watchdog", "start_watchdog", "stop_watchdog", "reset"]

_LOGGER = logging.getLogger(__name__)
# REENTRANT: the signal handler runs ON the main thread between
# bytecodes, so it can interrupt a critical section this module itself
# holds (e.g. request_stop via the fault seam, or a second SIGTERM while
# the first handler is still inside its locked section).  A plain Lock
# would self-deadlock the process right when it is trying to stop; an
# RLock re-acquires on the same thread, and every critical section here
# is a simple dict update, so re-entry is benign.
_LOCK = threading.RLock()

# exit-status contract with the external supervisor (documented in the
# README preemption flow): distinct codes so "preempted clean" is never
# confused with "crashed" and never burns a restart budget
EXIT_PREEMPTED = 43   # stop honored: final checkpoint published, clean exit
EXIT_FORCED = 44      # MXNET_GRACE_PERIOD_S expired before the loop stopped
EXIT_STALLED = 45     # watchdog abort: step deadline expired

_STOP = {"requested": False, "reason": None, "time": None}
# peer agreement for the stop flag: "enabled" turns the per-boundary
# collective on, "calls" counts sync-eligible check_stop() calls so the
# MXNET_STOP_SYNC_EVERY stride stays aligned across SPMD peers, and
# "agreed" is the last COLLECTIVE verdict — the only thing a coordinated
# loop may act on (a locally-set flag acted on off-cycle would exit one
# rank without its peers and deadlock the mesh)
_SYNC = {"enabled": False, "calls": 0, "agreed": False}
_HANDLERS = {"installed": False, "prev": {}, "deliveries": 0}

_STOPS_TOTAL = telemetry.counter(
    "mxnet_lifecycle_stops_total", "stop requests (signals + programmatic)")
_STOP_GAUGE = telemetry.gauge(
    "mxnet_lifecycle_stop_requested", "1 while a stop is pending")
_STALLS_TOTAL = telemetry.counter(
    "mxnet_watchdog_stalls_total", "watchdog step-deadline expiries")


class GracefulExit(Exception):
    """Raised by a training loop that honored a preemption stop: the final
    checkpoint (if enabled) is already published.  ``run_with_recovery``
    re-raises it WITHOUT counting a restart; callers translate it to
    ``sys.exit(EXIT_PREEMPTED)``."""

    def __init__(self, reason="preempted", step=None):
        self.reason = reason
        self.step = step
        at = f" at step {step}" if step is not None else ""
        super().__init__(f"graceful preemption exit{at}: {reason}")
        # constructing this exception IS the loop honoring the stop (the
        # final checkpoint write already finished, or was skipped by the
        # knob): disarm the grace-period force-exit so a caller that
        # catches GracefulExit and lives on (notebook, embedder doing
        # post-stop uploads) is not os._exit'd later for a stop that WAS
        # honored.  A final save that wedges never reaches this line, so
        # the deadline still bounds it.
        _flight.record_event("lifecycle", event="graceful_exit",
                             reason=str(reason), step=step)
        cancel_grace_deadline()


# --------------------------------------------------------------------------
# stop flag + peer agreement
# --------------------------------------------------------------------------
def request_stop(reason="programmatic"):
    """Ask the training loop to exit at the next step boundary.  Safe from
    signal handlers and any thread; idempotent (first reason wins)."""
    with _LOCK:
        if _STOP["requested"]:
            return
        _STOP["requested"] = True
        _STOP["reason"] = str(reason)
        _STOP["time"] = time.time()
    _STOPS_TOTAL.inc()
    _STOP_GAUGE.set(1)
    _flight.record_event("lifecycle", event="stop_requested",
                         reason=str(reason))
    # every stop (signal or programmatic) gets the same wall-time bound:
    # no-op when MXNET_GRACE_PERIOD_S is unset
    _arm_grace_deadline()
    _LOGGER.warning("stop requested (%s); training will exit at the next "
                    "step boundary", reason)


def stop_requested():
    """True once a stop was requested locally (signal, programmatic, or
    learned from a peer through ``check_stop``)."""
    return _STOP["requested"]


def stop_reason():
    return _STOP["reason"]


def coordinate_stops(enabled=True):
    """Turn on per-step peer agreement: every ``check_stop()`` becomes a
    one-scalar all-reduce in a multi-process job so all SPMD peers see
    the stop at the SAME step.  Enabled automatically by
    ``install_signal_handlers`` and ``parallel.distributed.init``;
    single-process jobs never pay a collective either way."""
    _SYNC["enabled"] = bool(enabled)


def check_stop(sync=None):
    """The step-boundary poll: returns True when the loop should stop.

    Also beats the watchdog heartbeat — a loop that polls for preemption
    is by definition not stalled.  ``sync`` overrides the peer-agreement
    default (see :func:`coordinate_stops`).

    Agreement contract: when peer coordination is on, the collective is
    issued every ``MXNET_STOP_SYNC_EVERY``-th call (default 1 — agree at
    every boundary; raise it to amortize the one-scalar all-reduce on
    jobs with very short steps, at the cost of up to N steps of stop
    latency).  The stride is counted per process, so EVERY process must
    call ``check_stop`` once per step boundary, in the same program
    order as its other collectives — per-rank iterators that yield
    UNEQUAL step counts already desync SPMD training collectives, and
    they desync this one the same way.

    Chaos seam ``lifecycle.sigterm``: an armed fault here is treated as
    a delivered preemption signal, so the whole graceful-shutdown path
    is testable without a real SIGTERM."""
    telemetry.heartbeat()
    # cross-rank telemetry aggregation rides this same uniform step
    # boundary (host-side file IO only — never a collective, so it
    # composes with the MXNET_STOP_SYNC_EVERY stride below freely)
    telemetry._agg_tick()
    try:
        fault.check("lifecycle.sigterm")
    except Exception as e:
        request_stop(f"fault-injected preemption ({e})")
    local = _STOP["requested"]
    if sync is None:
        sync = _SYNC["enabled"]
    # the agreement collective's issue count is a pure function of the
    # per-process call count (the documented stride contract above) —
    # `sync` is process-lifetime config, not per-step state:
    # mxtpu: noqa[MXT003]
    if sync:
        import jax

        if jax.process_count() > 1:
            # the stride must be a pure function of the per-process call
            # COUNT (never of the local flag): a flag-conditional extra
            # collective on one rank would desync the mesh.  Off-cycle
            # calls return the last AGREED verdict — never the local
            # flag, which would let a locally-signaled rank exit alone
            # and strand its peers in their next collective.
            with _LOCK:
                _SYNC["calls"] += 1
                due = _SYNC["calls"] % _env.stop_sync_every() == 0
            if not due:
                return _SYNC["agreed"]
            from .parallel.collectives import allreduce_any

            agreed = allreduce_any(local)
            _SYNC["agreed"] = agreed
            if agreed and not local:
                request_stop("stop agreed from a peer process")
            return agreed
    return local


# --------------------------------------------------------------------------
# signal handlers + grace period
# --------------------------------------------------------------------------
def _on_signal(signum, frame):
    import signal as _signal

    with _LOCK:
        _HANDLERS["deliveries"] += 1
        repeat = _HANDLERS["deliveries"] > 1
    if repeat:
        # second delivery: the operator (or scheduler) wants out NOW —
        # restore the previous disposition and re-deliver
        uninstall_signal_handlers()
        os.kill(os.getpid(), signum)
        return
    try:
        name = _signal.Signals(signum).name
    except ValueError:  # pragma: no cover
        name = str(signum)
    request_stop(f"signal {name}")   # arms the grace deadline too


def _grace_expired(grace_s):
    _LOGGER.critical(
        "grace period of %.1fs expired before the training loop honored "
        "the stop; force-exiting (status %d) so the scheduler's SIGKILL "
        "does not land mid-checkpoint", grace_s, EXIT_FORCED)
    # the forced exit is an abnormal end: the ring is the only record
    # of WHERE the loop was wedged when the deadline landed
    _flight.record_event("lifecycle", event="grace_deadline_expired",
                         grace_s=grace_s)
    _flight.dump_blackbox("grace_deadline_forced_exit")
    logging.shutdown()
    os._exit(EXIT_FORCED)


_GRACE = {"timer": None}


def _arm_grace_deadline():
    grace = _env.grace_period_s()
    if grace <= 0:
        return
    t = threading.Timer(grace, _grace_expired, args=(grace,))
    t.daemon = True
    with _LOCK:
        _GRACE["timer"] = t
    t.start()


def cancel_grace_deadline():
    """Disarm the force-exit deadline (idempotent).  Called automatically
    when a GracefulExit is constructed — i.e. the stop was honored."""
    with _LOCK:
        t, _GRACE["timer"] = _GRACE["timer"], None
    if t is not None:
        t.cancel()


def install_signal_handlers(signals=None):
    """Install graceful-preemption handlers (default SIGTERM + SIGINT):
    the first delivery requests a stop (and arms the
    ``MXNET_GRACE_PERIOD_S`` force-exit deadline), a second delivery
    restores the previous disposition and re-raises.  Also enables
    multi-process stop agreement.  Idempotent; main thread only (signal
    module contract) — a non-main-thread call is a logged no-op."""
    import signal as _signal

    sigs = tuple(signals or (_signal.SIGTERM, _signal.SIGINT))
    with _LOCK:
        if _HANDLERS["installed"]:
            _SYNC["enabled"] = True
            return True
    try:
        prev = {}
        for s in sigs:
            prev[s] = _signal.signal(s, _on_signal)
    except ValueError:  # not the main thread
        _LOGGER.warning("install_signal_handlers: not on the main thread; "
                        "preemption signals will not be caught here")
        return False
    with _LOCK:
        _HANDLERS["installed"] = True
        _HANDLERS["prev"] = prev
        _HANDLERS["deliveries"] = 0
    _SYNC["enabled"] = True
    return True


def uninstall_signal_handlers():
    """Restore the dispositions ``install_signal_handlers`` replaced."""
    import signal as _signal

    with _LOCK:
        prev = _HANDLERS["prev"]
        _HANDLERS["installed"] = False
        _HANDLERS["prev"] = {}
    for s, h in prev.items():
        try:
            _signal.signal(s, h)
        except ValueError:  # pragma: no cover - non-main thread
            pass


def reset():
    """Clear the stop flag + handler bookkeeping (test isolation)."""
    uninstall_signal_handlers()
    cancel_grace_deadline()
    with _LOCK:
        _STOP.update(requested=False, reason=None, time=None)
        _HANDLERS["deliveries"] = 0
        _SYNC.update(enabled=False, calls=0, agreed=False)
    _STOP_GAUGE.set(0)


def note_goodput_slo_breach(ratio, slo, windows):
    """The goodput-SLO alert hook (called by ``telemetry`` when the
    productive ratio stayed below ``MXNET_GOODPUT_SLO`` for
    ``MXNET_GOODPUT_SLO_WINDOWS`` consecutive windows): a lifecycle
    event — logged loudly + recorded in the flight-recorder ring so a
    later crash dump shows the degradation preceded it.  Deliberately
    NOT a stop: an SLO breach is an operator page, not a reason to
    strand the mesh."""
    _LOGGER.warning(
        "goodput SLO breach: productive ratio %.3f below SLO %.3f for "
        "%d consecutive windows (mxnet_goodput_slo_breaches_total "
        "incremented)", ratio, slo, windows)
    _flight.record_event("lifecycle", event="goodput_slo_breach",
                         ratio=float(ratio), slo=float(slo),
                         windows=int(windows))
    for hook in list(_GOODPUT_HOOKS):
        try:
            hook(ratio, slo, windows)
        except Exception:   # an observer must not break the alert path
            _LOGGER.exception("goodput-breach hook %r failed", hook)


# breach observers (the serving-fleet autoscaler wires scale-up here);
# hooks run on the alerting thread and must be cheap + non-raising
_GOODPUT_HOOKS: list = []


def register_goodput_breach_hook(fn):
    """Subscribe ``fn(ratio, slo, windows)`` to goodput-SLO breach
    alerts.  The fleet autoscaler's scale-up trigger is the canonical
    consumer — the alert stays an operator page (never a stop), and
    hooks piggyback on it rather than re-deriving the breach."""
    if fn not in _GOODPUT_HOOKS:
        _GOODPUT_HOOKS.append(fn)
    return fn


def unregister_goodput_breach_hook(fn):
    """Remove a breach hook (idempotent)."""
    if fn in _GOODPUT_HOOKS:
        _GOODPUT_HOOKS.remove(fn)


def note_fleet_queue_slo_breach(depth, threshold, shed):
    """Fleet-wide queue-SLO breach (the router's deadline-aware
    shedding tripped): same contract as the goodput breach — loud log
    + flight-recorder context event, deliberately NOT a stop.  ``shed``
    counts the requests 429'd in this episode."""
    _LOGGER.warning(
        "fleet queue SLO breach: fleet-wide depth %d above threshold %d "
        "— shedding with Retry-After (%d shed this episode)",
        depth, threshold, shed)
    _flight.record_event("lifecycle", event="fleet_queue_slo_breach",
                         depth=int(depth), threshold=int(threshold),
                         shed=int(shed))


def note_ledger_skew(skew, threshold, windows, laggards):
    """The ledger-skew pre-hang alert hook (called by
    ``telemetry_agg`` when the cross-rank collective-ledger position
    spread stayed above ``MXNET_LEDGER_SKEW_THRESHOLD`` for
    ``MXNET_LEDGER_SKEW_WINDOWS`` consecutive merges): some rank has
    stopped issuing collectives while its peers run ahead — the
    pre-image of the hang the watchdog/black-box machinery will blame
    *after* the wedge.  Logged loudly + recorded in the flight ring so
    a later crash dump shows the divergence preceded it.  Deliberately
    NOT a stop — same contract as the goodput breach."""
    _LOGGER.warning(
        "collective-ledger skew alert: cross-rank position spread %d "
        "above threshold %d for %d consecutive merges; lagging "
        "rank(s) %s (mxnet_ledger_skew_alerts_total incremented)",
        skew, threshold, windows, list(laggards))
    _flight.record_event("lifecycle", event="ledger_skew_alert",
                         skew=int(skew), threshold=int(threshold),
                         windows=int(windows),
                         laggards=[int(r) for r in laggards])


# --------------------------------------------------------------------------
# exact-resume training state
# --------------------------------------------------------------------------
def publish_final_checkpoint(manager, step, net=None, trainer=None,
                             train_state=None):
    """The stop-path save: a SYNCHRONOUS checkpoint at ``step`` (an async
    write could still be staging when the grace period ends).  Honors
    ``MXNET_PREEMPTION_CHECKPOINT`` (default on); returns the checkpoint
    directory, or None when disabled."""
    if not _env.preemption_checkpoint_default():
        _LOGGER.warning("MXNET_PREEMPTION_CHECKPOINT=0: exiting WITHOUT a "
                        "final checkpoint (step %s)", step)
        return None
    return manager.save(step, net, trainer, train_state=train_state,
                        async_=False)


def capture_train_state(step=None, dataloader=None, scaler=None,
                        trainer=None, extra=None, guard=None):
    """Bundle everything beyond weights/optimizer-state that a
    bit-identical resume needs, as a JSON-able dict for
    ``CheckpointManager.save(..., train_state=...)``:

    - ``mx.random`` global RNG state (always),
    - DataLoader/sampler position — epoch, batches consumed, shuffle
      seed (``dataloader.state_dict()``),
    - LossScaler scale + clean-step counter (``scaler``),
    - the Trainer's optimizer update count (``trainer`` — redundant with
      the pickled optimizer in trainer.states, kept as a cross-check),
    - the numerical-integrity guard's trailing window + ladder state
      (``guard`` — a resumed run classifies its next step exactly as
      the original would have; mxnet_tpu/guard.py),
    - caller extras (``extra``; must be JSON-able).

    Capture at a step boundary, on the training thread (the RNG state is
    thread-local), AFTER the step's checkpointable effects."""
    from . import random as _random

    st = {"format": 1, "rng": _random.get_state()}
    if step is not None:
        st["step"] = int(step)
    if dataloader is not None:
        sd = getattr(dataloader, "state_dict", None)
        if sd is not None:
            st["dataloader"] = sd()
    if scaler is not None:
        st["loss_scaler"] = scaler.state_dict()
    if trainer is not None:
        st["trainer"] = {"num_update": int(trainer.step_count)}
    if guard is not None:
        st["guard"] = guard.state_dict()
    if extra:
        st["extra"] = extra
    return st


def restore_train_state(state, dataloader=None, scaler=None, guard=None):
    """Re-apply a ``capture_train_state`` dict (RNG always; DataLoader /
    LossScaler / guard when passed).  Returns the recorded step (or
    None).  The DataLoader fast-forwards decode-free on its next
    ``__iter__`` — skipped batches never touch the dataset."""
    if not state:
        return None
    from . import random as _random

    if "rng" in state:
        _random.set_state(state["rng"])
    if dataloader is not None and state.get("dataloader") is not None:
        dataloader.load_state_dict(state["dataloader"])
    if scaler is not None and state.get("loss_scaler") is not None:
        scaler.load_state_dict(state["loss_scaler"])
    if guard is not None and state.get("guard") is not None:
        guard.load_state_dict(state["guard"])
    return state.get("step")


# --------------------------------------------------------------------------
# zero-downtime elasticity: the live-reshard recovery hook
# --------------------------------------------------------------------------
def elastic_resharder(check_fn, reshard_fn, logger=None):
    """Build a ``run_with_recovery(resharder=...)`` callback from two
    caller pieces:

    - ``check_fn(exc) -> (ok, step)`` — is THIS process's surviving
      in-memory state intact, and which step does it correspond to?
      Pure local verdict: no collectives, no device work.
    - ``reshard_fn(step) -> step`` — move the surviving state to the
      (possibly resized) mesh, normally via
      :mod:`~mxnet_tpu.parallel.resharding` (``apply_transfer`` /
      ``ZeroBucketEngine.reshard``); returns the resume step.

    The glue this helper owns is the SPMD agreement: exactly ONE
    collective (``resharding.peers_agree_intact``) decides whether
    every peer's state survived — issued unconditionally on every
    process, so collective counts stay uniform no matter which peers
    are damaged.  A ``check_fn`` that RAISES (probing torn state is
    exactly when it might) is treated as a not-intact vote with the
    collective still issued — letting the exception skip it would
    strand every other peer inside the agreement.  Only a unanimous
    yes takes the live path; any veto falls back to the checkpoint
    restore.  A ``reshard_fn`` failure AFTER unanimous agreement
    propagates to run_with_recovery's fallback; multi-process, a
    mid-transfer failure there is the PR 2 escalation class (the
    transfer's own collectives desync) and resolves through the
    whole-job restart, exactly like any other torn collective.
    Single-process jobs skip the collective and the local verdict
    decides."""
    log = logger or _LOGGER

    def _resharder(exc):
        try:
            ok, step = check_fn(exc)
        except Exception as ce:
            # the peers are (or will be) blocked in the agreement
            # collective: vote not-intact rather than skip the vote
            log.warning("elastic check_fn raised (%r); voting "
                        "not-intact", ce)
            ok, step = False, None
        from .parallel.resharding import peers_agree_intact

        agreed = peers_agree_intact(bool(ok))
        if not agreed:
            log.info("live reshard declined: surviving state not "
                     "intact on every peer (local ok=%s)", bool(ok))
            return None
        return reshard_fn(step)

    return _resharder


# --------------------------------------------------------------------------
# stall watchdog
# --------------------------------------------------------------------------
class Watchdog:
    """Daemon thread enforcing a per-step deadline from the telemetry
    heartbeat (``step_begin``/``step_end``/``check_stop`` all beat it).

    On expiry: write a diagnosis file (all-thread stacks + telemetry
    snapshot), bump ``mxnet_watchdog_stalls_total``, and — when ``abort``
    (default ``MXNET_WATCHDOG_ABORT``, on) — exit the process with
    :data:`EXIT_STALLED` so the external supervisor restarts from the
    last valid checkpoint.  With ``abort=False`` it fires once per
    distinct stall (re-arms only after the heartbeat advances).

    A hung XLA collective cannot be un-wedged from inside the process
    (the main thread is blocked in the runtime), which is why the abort
    is a process exit, not an exception: restart-from-checkpoint is the
    recovery path, the dump file is the diagnosis.

    Two deliberate non-firing windows: (1) before the FIRST heartbeat
    the job is still initializing — the first step's XLA compile can
    dwarf the steady-state deadline — so a 10x startup allowance
    applies; (2) while a stop is pending AND a ``MXNET_GRACE_PERIOD_S``
    deadline is armed, that deadline owns termination (the final
    synchronous checkpoint legitimately exceeds a per-step deadline on
    large models), so the watchdog stands down instead of killing the
    stop path it exists to protect — with no grace configured it keeps
    enforcing, so a final save wedged on a dead peer still gets
    diagnosed and aborted.

    Chaos seam ``watchdog.stall``: an armed fault makes the next poll
    behave as an expired deadline (even in the non-firing windows, so
    tests stay deterministic)."""

    def __init__(self, timeout_s=None, abort=None, dump_dir=None,
                 poll_s=None, logger=None):
        if timeout_s is None:
            timeout_s = _env.watchdog_timeout_s()
        self.timeout_s = float(timeout_s)
        if abort is None:
            abort = _env.get_bool("MXNET_WATCHDOG_ABORT", True)
        self.abort = bool(abort)
        self.dump_dir = dump_dir or _env.get_str("MXNET_WATCHDOG_DIR") or "."
        self.poll_s = float(poll_s) if poll_s else \
            max(0.05, min(self.timeout_s / 4.0, 1.0))
        self.logger = logger or _LOGGER
        self.last_dump = None
        self.last_blackbox = None
        self.stall_count = 0
        self._stop_evt = threading.Event()
        self._thread = None
        self._fired_base = None   # heartbeat value the last dump fired on

    def start(self):
        """Start polling; no-op (returns self) when the timeout is off."""
        if self.timeout_s <= 0:
            self.logger.info("watchdog disabled "
                             "(MXNET_WATCHDOG_TIMEOUT_S unset/0)")
            return self
        if self._thread is not None:
            return self
        self._started = time.monotonic()
        self._stop_evt.clear()
        self._thread = threading.Thread(
            target=self._run, name="mxnet-watchdog", daemon=True)
        self._thread.start()
        return self

    def stop(self):
        t = self._thread
        self._thread = None
        if t is not None:
            self._stop_evt.set()
            t.join(timeout=5)

    # -- internals ---------------------------------------------------------
    def _run(self):
        while not self._stop_evt.wait(self.poll_s):
            injected = None
            try:
                fault.check("watchdog.stall")
            except Exception as e:
                injected = e
            last = telemetry.last_heartbeat()
            base = last if last is not None else self._started
            age = time.monotonic() - base
            if injected is None:
                if _STOP["requested"] and _GRACE["timer"] is not None:
                    # stop path WITH a live grace deadline: that deadline
                    # owns termination (the final sync save may
                    # legitimately exceed a per-step timeout).  With no
                    # grace configured the watchdog keeps enforcing —
                    # otherwise a final save wedged on a dead peer's
                    # barrier would hang forever with no diagnosis.
                    continue
                # startup allowance: no heartbeat yet = first step still
                # compiling/warming, not a steady-state stall
                limit = self.timeout_s if last is not None \
                    else self.timeout_s * 10.0
                if age <= limit:
                    continue
                if base == self._fired_base:
                    continue   # same stall: already diagnosed, don't spam
                # only a REAL fire consumes the per-stall one-shot: an
                # injected (chaos) fire must not mask a genuine stall
                # that wedges before the next heartbeat
                self._fired_base = base
            self._fire(age, injected)

    def _fire(self, age, injected):
        self.stall_count += 1
        _STALLS_TOTAL.inc()
        # goodput ledger: the heartbeat gap IS wall time the job lost
        # to the stall (injected chaos fires charge nothing real — age
        # there is just time since the last step boundary)
        if injected is None:
            telemetry.goodput_note("stall", age)
        cause = f"injected fault ({injected})" if injected is not None \
            else (f"no step heartbeat for {age:.1f}s "
                  f"(deadline {self.timeout_s:.1f}s)")
        _flight.record_event("lifecycle", event="watchdog_stall",
                             cause=cause, age_s=float(age))
        # black-box dump FIRST (it is the cross-rank-mergeable artifact
        # and the abort below never returns); falls back to this
        # watchdog's own dump dir when no gather dir is configured, so
        # the ring always lands beside the diagnosis file.  Never a
        # collective — the mesh is presumed wedged.
        self.last_blackbox = _flight.dump_blackbox(
            "watchdog_stall",
            directory=_env.flight_dir() or self.dump_dir)
        try:
            path = self._write_dump(age, cause)
            self.last_dump = path
        except Exception as e:  # the dump must never kill the watchdog
            path = None
            self.logger.error("watchdog: failed to write diagnosis "
                              "file: %r", e)
        self.logger.critical(
            "watchdog stall: %s; diagnosis %s%s", cause, path,
            f"; aborting with status {EXIT_STALLED}" if self.abort else "")
        if self.abort:
            logging.shutdown()
            os._exit(EXIT_STALLED)

    def _thread_stacks(self):
        names = {t.ident: t.name for t in threading.enumerate()}
        out = {}
        for tid, frame in sys._current_frames().items():
            label = f"{names.get(tid, 'unknown')} (tid={tid})"
            out[label] = traceback.format_stack(frame)
        return out

    def _write_dump(self, age, cause):
        """One self-contained JSON diagnosis file per stall: what stalled
        (all-thread stacks — the wedged collective/IO is in there) and
        the job's state when it did (telemetry snapshot)."""
        os.makedirs(self.dump_dir, exist_ok=True)
        path = os.path.join(
            self.dump_dir,
            f"mxnet_watchdog_stall_{os.getpid()}_{self.stall_count}.json")
        doc = {
            "time": time.time(),
            "pid": os.getpid(),
            "cause": cause,
            "timeout_s": self.timeout_s,
            "heartbeat_age_s": age,
            "stacks": self._thread_stacks(),
            "telemetry": telemetry.snapshot(),
            # this rank's collective ledger: which collective the
            # wedged thread last entered (or never entered) — the
            # single-rank half of the cross-rank blame merge
            "flight_recorder": _flight.snapshot_doc(),
            "blackbox": self.last_blackbox,
        }
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1, default=str)
        os.replace(tmp, path)
        return path


_WATCHDOG = None


def start_watchdog(timeout_s=None, **kwargs):
    """Start (or return) the process-wide watchdog.  Called from
    ``env.apply_env`` when ``MXNET_WATCHDOG_TIMEOUT_S`` is set."""
    global _WATCHDOG
    with _LOCK:
        if _WATCHDOG is None:
            _WATCHDOG = Watchdog(timeout_s=timeout_s, **kwargs)
    return _WATCHDOG.start()


def stop_watchdog():
    global _WATCHDOG
    with _LOCK:
        wd, _WATCHDOG = _WATCHDOG, None
    if wd is not None:
        wd.stop()
