"""Runtime feature detection (reference: python/mxnet/runtime.py +
src/libinfo.cc — build-flag capability query, SURVEY.md §6.6)."""
from __future__ import annotations

__all__ = ["Features", "feature_list"]


class Feature:
    def __init__(self, name, enabled):
        self.name = name
        self.enabled = enabled

    def __repr__(self):
        return f"[{'✔' if self.enabled else '✖'} {self.name}]"


class Features(dict):
    def __init__(self):
        super().__init__()
        import jax

        plats = {d.platform for d in jax.devices()}
        feats = {
            "TPU": bool(plats - {"cpu"}),
            "CPU": True,
            "CUDA": False,
            "CUDNN": False,
            "BF16": True,
            "F16C": True,
            "INT64_TENSOR_SIZE": True,
            "JIT": True,
            "PALLAS": _has_pallas(),
            "DIST_KVSTORE": True,
            "SIGNAL_HANDLER": True,
            "MKLDNN": False,
            "OPENCV": False,
            "SPARSE": True,  # ndarray/sparse.py: row_sparse/csr + kvstore path
        }
        for k, v in feats.items():
            self[k] = Feature(k, v)

    def is_enabled(self, name):
        return self[name.upper()].enabled


def _has_pallas():
    try:
        from jax.experimental import pallas  # noqa: F401

        return True
    except ImportError:
        return False


def feature_list():
    return list(Features().values())
