"""Checkpoint/resume + failure recovery.

Reference scope (SURVEY.md §6.3): MXNet 1.x ships Module.save_checkpoint /
load_checkpoint and leaves elastic recovery to the operator; modern TPU
jobs need the full loop — atomic checkpoints, auto-resume from the latest
good step, and a supervised retry wrapper (the moral equivalent of the
ps-lite worker-restart story, redesigned for SPMD jobs where every process
restarts together).

Design:
- ``CheckpointManager``: step-indexed directory layout, ATOMIC publishes
  (write to tmp, fsync, rename — a partially-written checkpoint is never
  visible), per-file sha256 checksums recorded in ``meta.json`` and
  verified on restore (a bit-flipped or truncated file is detected, the
  step is skipped, and restore falls back to the newest VALID older
  step), bounded retention, ``latest_step()`` discovery for resume, and
  orphaned-staging GC (a crash mid-save leaves a ``.tmp_step_*`` dir; the
  next manager construction sweeps them).
  In a multi-process job only process 0 writes (weights are replicated);
  all processes barrier on publish so no one resumes past a checkpoint a
  peer has not finished.
- ``run_with_recovery``: restarts a training function from the latest
  checkpoint after transient failures (preemption, XLA OOM after
  defragmentation, flaky interconnect) with exponential backoff + jitter
  between restarts and a restart budget that RESETS whenever the job made
  checkpoint progress between failures — a job that keeps advancing is
  healthy no matter how often it is preempted, while a crash loop at the
  same step still exhausts the budget.

Failure domains are exercised through :mod:`mxnet_tpu.fault` (seams
``checkpoint.write`` / ``checkpoint.fsync`` / ``checkpoint.publish``);
see tests/test_fault.py for the chaos suite.
"""
from __future__ import annotations

import hashlib
import json
import logging
import os
import shutil
import tempfile
import threading
import time

from . import fault
from . import flight_recorder as _flight
from . import telemetry
from .base import MXNetError

__all__ = ["CheckpointManager", "run_with_recovery"]

_SAVE_HIST = telemetry.histogram(
    "mxnet_checkpoint_save_seconds", "checkpoint save duration (publish)")
_RESTORE_HIST = telemetry.histogram(
    "mxnet_checkpoint_restore_seconds", "checkpoint restore duration")
_SAVES_TOTAL = telemetry.counter(
    "mxnet_checkpoint_saves_total", "published checkpoints")
_RESTORES_TOTAL = telemetry.counter(
    "mxnet_checkpoint_restores_total", "completed checkpoint restores")
_RESTARTS_TOTAL = telemetry.counter(
    "mxnet_recovery_restarts_total", "run_with_recovery restarts")
_INFLIGHT = telemetry.gauge(
    "mxnet_checkpoint_inflight",
    "1 while an async checkpoint write is staging/publishing in background")
_SNAPSHOT_HIST = telemetry.histogram(
    "mxnet_checkpoint_snapshot_seconds",
    "blocking device->host snapshot portion of an async save")

_LOGGER = logging.getLogger(__name__)

_TMP_PREFIX = ".tmp_step_"
# files that never get a checksum: meta.json carries the sums, COMMITTED
# is the marker itself
_UNSUMMED = ("meta.json", "COMMITTED")


def _fsync_file(path):
    # a long synchronous save (the preemption stop path in particular)
    # is progress, not a stall: beat the watchdog per durability step
    telemetry.heartbeat()
    fault.check("checkpoint.fsync")
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _fsync_dir(path):
    try:
        fd = os.open(path, os.O_RDONLY | getattr(os, "O_DIRECTORY", 0))
    except OSError:  # pragma: no cover - platforms without O_DIRECTORY
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _sha256(path):
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _stat_sig(path):
    """(size, mtime_ns) fingerprint, or None when missing — cheap change
    detector for the verify() verdict cache."""
    try:
        st = os.stat(path)
    except OSError:
        return None
    return (st.st_size, st.st_mtime_ns)


class CheckpointManager:
    """Atomic, step-indexed checkpoints for Gluon nets + Trainers.

    Usage::

        mgr = CheckpointManager(dir, max_to_keep=3)
        start = mgr.restore(net, trainer)  # 0 if none yet
        for epoch in range(start, n):
            ...train...
            mgr.save(epoch + 1, net, trainer)
    """

    def __init__(self, directory, max_to_keep=5, logger=None):
        self.directory = directory
        self.max_to_keep = max_to_keep
        self.logger = logger or _LOGGER
        self._compile_cache = None
        # verify() verdict cache: step -> {file: (size, mtime_ns)} at the
        # time the step last hashed clean
        self._valid_steps = {}
        # steps that VERIFIED clean but failed to load (pre-checksum
        # checkpoint with a torn file): latest_valid_step must skip them
        # or the next restart's start step disagrees with the weights
        # restore() actually falls back to
        self._load_failed = set()
        # async-save state: at most ONE background write in flight; the
        # next save()/close()/restore() joins it first
        self._pending = None
        self._pending_step = None
        self._pending_error = None
        os.makedirs(directory, exist_ok=True)
        # only the writing process sweeps: a non-primary peer constructing
        # its manager while process 0 is mid-save must not delete the live
        # staging dir out from under it.  The rank comes from the LAUNCHER
        # env, not jax.process_index(): constructing a manager must not
        # initialize the jax backend (that would break a later
        # jax.distributed.initialize), and before initialization every
        # process would report index 0 anyway.
        if self._launcher_rank() == 0:
            self._gc_orphaned_tmp()

    @staticmethod
    def _launcher_rank():
        """Process rank WITHOUT initializing the jax backend; -1 = multi-
        process job whose rank cannot be proven (callers fail closed)."""
        for var in ("MXNET_WORKER_ID", "DMLC_WORKER_ID", "TPU_WORKER_ID",
                    "CLOUD_TPU_TASK_ID"):
            v = os.environ.get(var)
            if v:
                try:
                    return int(v)
                except ValueError:
                    return -1  # unparseable: cannot prove primary
        from .parallel import distributed as _dist

        if _dist.is_initialized():
            import jax   # already initialized: reading the index is safe

            return jax.process_index()
        if os.environ.get("MXNET_COORDINATOR_ADDRESS") or \
                os.environ.get("DMLC_PS_ROOT_URI"):
            # a coordinator is configured but no rank var and not yet
            # initialized: this IS a multi-process job — fail closed
            # rather than risk every peer sweeping the shared directory
            return -1
        return 0  # single-process / un-launched

    def _gc_orphaned_tmp(self):
        """Sweep ``.tmp_step_*`` staging dirs left by a crash mid-save
        (they were never published, so deleting them is always safe —
        an in-flight save in ANOTHER process is the operator's error:
        two writers on one checkpoint dir corrupt retention anyway)."""
        for name in os.listdir(self.directory):
            path = os.path.join(self.directory, name)
            if name.startswith(_TMP_PREFIX) and os.path.isdir(path):
                shutil.rmtree(path, ignore_errors=True)
                self.logger.warning(
                    "removed orphaned checkpoint staging dir %s "
                    "(crash mid-save)", path)

    # -- discovery ---------------------------------------------------------
    def _step_dir(self, step):
        return os.path.join(self.directory, f"step_{step:08d}")

    def all_steps(self):
        out = []
        for name in os.listdir(self.directory):
            if name.startswith("step_") and os.path.isdir(
                    os.path.join(self.directory, name)) and \
                    os.path.exists(os.path.join(self.directory, name,
                                                "COMMITTED")):
                out.append(int(name[len("step_"):]))
        return sorted(out)

    def latest_step(self):
        steps = self.all_steps()
        return steps[-1] if steps else None

    def latest_valid_step(self):
        """Newest step that passes checksum verification and has not been
        seen to fail a load — the step a restore() will actually serve.
        Resume logic must use THIS, not ``latest_step()``: after
        corruption the two differ, and trusting the unverified number
        silently skips the corrupted step's work."""
        # an in-flight async write may be about to publish (or to mutate
        # the verify cache): join first so the answer is race-free and
        # credits exactly the published steps
        self._join_pending(raise_=False)
        for s in reversed(self.all_steps()):
            if s not in self._load_failed and self.verify(s) is None:
                return s
        return None

    def verify(self, step):
        """Integrity-check checkpoint ``step`` against the checksums in
        its meta.json.  Returns None when valid, else a string naming the
        first problem.  Checkpoints written before checksums existed
        (no "files" key) verify as valid — there is nothing to check.

        A VALID verdict is cached against each file's (size, mtime_ns) —
        resume would otherwise sha256 a multi-GB checkpoint twice
        (latest_valid_step, then restore).  Any stat change voids the
        cache and re-hashes; failures are never cached, so an operator
        who repairs a file in place is believed."""
        d = self._step_dir(step)
        cached = self._valid_steps.get(step)
        if cached is not None:
            if all(_stat_sig(os.path.join(d, n)) == sig
                   for n, sig in cached.items()):
                return None
            del self._valid_steps[step]
        if not os.path.exists(os.path.join(d, "COMMITTED")):
            return f"{d}: no COMMITTED marker"
        try:
            with open(os.path.join(d, "meta.json")) as f:
                meta = json.load(f)
        except (OSError, ValueError) as e:
            return f"{d}/meta.json unreadable: {e}"
        for name, want in (meta.get("files") or {}).items():
            path = os.path.join(d, name)
            if not os.path.exists(path):
                return f"{path}: missing"
            if os.path.getsize(path) != want["size"]:
                return (f"{path}: size {os.path.getsize(path)} != recorded "
                        f"{want['size']} (truncated?)")
            if _sha256(path) != want["sha256"]:
                return f"{path}: sha256 mismatch (corrupt)"
        self._valid_steps[step] = {
            name: _stat_sig(os.path.join(d, name))
            for name in (meta.get("files") or {})}
        return None

    # -- save/restore ------------------------------------------------------
    def _write_step(self, step, write_payloads, extra, primary,
                    barrier=True):
        """Stage, checksum, fsync, and atomically publish checkpoint
        ``step``.  ``write_payloads(tmp_dir)`` writes the payload files;
        everything else (manifest, durability ordering, publish rename,
        retention GC) is identical for the sync and async paths — the
        fault seams and sha256 contract hold for both.  ``barrier=False``
        for the async background writer: a collective issued from a
        second thread would race the main thread's training collectives
        (SPMD peers must enqueue collectives in one program order), so
        the async path barriers on the CALLER's thread instead."""
        final = self._step_dir(step)
        telemetry.heartbeat()   # a save is progress, not a stall
        try:
            if primary:
                tmp = tempfile.mkdtemp(prefix=f"{_TMP_PREFIX}{step}_",
                                       dir=self.directory)
                try:
                    fault.check("checkpoint.write")
                    write_payloads(tmp)
                    telemetry.heartbeat()
                    meta = {"step": int(step), "time": time.time()}
                    if extra:
                        meta["extra"] = extra
                    # integrity: restore() re-hashes each payload file
                    # against these sums before trusting the step
                    meta["files"] = {
                        name: {"sha256": _sha256(os.path.join(tmp, name)),
                               "size": os.path.getsize(
                                   os.path.join(tmp, name))}
                        for name in os.listdir(tmp) if name not in _UNSUMMED}
                    with open(os.path.join(tmp, "meta.json"), "w") as f:
                        json.dump(meta, f)
                    # durability: every payload file reaches the platter
                    # BEFORE the commit marker exists, and the marker +
                    # directory entries before the publish rename
                    for name in os.listdir(tmp):
                        _fsync_file(os.path.join(tmp, name))
                    with open(os.path.join(tmp, "COMMITTED"), "w") as f:
                        f.write("1")
                        f.flush()
                        os.fsync(f.fileno())
                    _fsync_dir(tmp)
                    fault.check("checkpoint.publish")
                    if os.path.exists(final):
                        shutil.rmtree(final)
                    self._valid_steps.pop(step, None)  # content changes now
                    self._load_failed.discard(step)
                    os.rename(tmp, final)
                    _fsync_dir(self.directory)
                except Exception:
                    shutil.rmtree(tmp, ignore_errors=True)
                    raise
                self._gc()
        finally:
            # ALL processes must reach the barrier even when the primary's
            # write fails — otherwise the peers deadlock in the collective
            if barrier:
                self._barrier()
        return final

    def save(self, step, net=None, trainer=None, extra=None, async_=None,
             train_state=None):
        """Publish checkpoint `step` atomically; returns its directory.

        ``train_state`` (a JSON-able dict, normally from
        ``lifecycle.capture_train_state``) is written as
        ``train_state.json`` — sha256-summed like every payload file —
        and read back with :meth:`read_train_state`.  It carries what a
        bit-identical resume needs beyond weights/optimizer state:
        DataLoader/sampler position, the global RNG state, loss-scaler
        counters, and step counters.

        ``async_=True`` (default from ``MXNET_CHECKPOINT_ASYNC``) makes
        only the device→host snapshot block the caller: file writes,
        fsyncs, and the atomic publish run on a background thread with
        the same fault seams and sha256 manifest.  The next ``save`` (or
        ``close()``/``restore()``) joins the previous write first — a
        failed background write surfaces there, and its step was simply
        never published (costs one step, never the job).  Supervisors
        must credit progress from ``latest_valid_step()``, which sees
        only *published* steps."""
        import jax

        if async_ is None:
            from . import env as _env

            async_ = _env.checkpoint_async_default()
        # surface a failed previous background write before anything else:
        # losing its step already cost one checkpoint; losing the ERROR
        # would hide a persistently broken disk behind green saves.
        # Multi-process: LOG instead of raising — only the primary has
        # pending state, and a primary-only raise here would strand the
        # peers in the barrier below (the all-processes-reach-the-barrier
        # invariant).  The unpublished step still never counts as
        # progress; close() at end-of-job (no more collectives) raises.
        self._join_pending(raise_=jax.process_count() == 1)
        primary = jax.process_index() == 0
        final = self._step_dir(step)
        t0 = time.perf_counter()
        # serialize NOW in both paths: train_state is host data, and the
        # caller may mutate its dicts (sampler epoch, RNG) right after
        ts_blob = None if train_state is None else \
            json.dumps(train_state).encode()
        if not async_:
            def write_payloads(tmp):
                if net is not None:
                    net.save_parameters(os.path.join(tmp, "model.params"))
                if trainer is not None:
                    trainer.save_states(os.path.join(tmp, "trainer.states"))
                if ts_blob is not None:
                    with open(os.path.join(tmp, "train_state.json"),
                              "wb") as f:
                        f.write(ts_blob)

            # a save inside an open telemetry step is its own phase; the
            # phase must close even when the barrier fails, or the
            # dangling frame mis-attributes the rest of the step
            with telemetry.phase("checkpoint"):
                self._write_step(step, write_payloads, extra, primary)
            dt = time.perf_counter() - t0
            _SAVE_HIST.observe(dt)
            # goodput: a synchronous save blocks training for its full
            # duration (the step timeline excludes its in-step
            # checkpoint phase from "productive" for the same reason)
            telemetry.goodput_note("checkpoint", dt)
            _SAVES_TOTAL.inc()
            return final
        # async: snapshot device→host NOW (host copies — the step loop
        # mutating params right after cannot leak into the file), write
        # and publish in background.  The peer barrier runs HERE, on the
        # calling thread: every process calls save() at the same point of
        # its step loop, so the collective stays in program order; a
        # barrier from the background thread would race the main thread's
        # training collectives and desync SPMD peers.  The synchronized
        # event is therefore "snapshot taken everywhere", and the publish
        # is primary-local — supervisors credit only PUBLISHED steps.
        with telemetry.phase("checkpoint"):
            try:
                writers = self._snapshot_payloads(net, trainer) if primary \
                    else {}
                if primary and ts_blob is not None:
                    def write_ts(path, _blob=ts_blob):
                        with open(path, "wb") as f:
                            f.write(_blob)

                    writers["train_state.json"] = write_ts
            finally:
                # ALL processes must reach the barrier even when the
                # primary's snapshot raises (same invariant as the sync
                # path's finally in _write_step) — peers are already
                # blocked in it
                self._barrier()
        dt_snap = time.perf_counter() - t0
        _SNAPSHOT_HIST.observe(dt_snap)
        # goodput: an ASYNC save only blocks for the device->host
        # snapshot — the background write overlaps training and is
        # deliberately NOT charged (that overlap is the feature)
        telemetry.goodput_note("checkpoint", dt_snap)
        if not primary:
            return final  # nothing to write; the snapshot barrier is done

        def write_payloads(tmp):
            for name, write in writers.items():
                write(os.path.join(tmp, name))

        self._pending_step = step
        self._pending_error = None
        _INFLIGHT.set(1)

        def task():
            try:
                # NO telemetry.phase here: the step timeline is the MAIN
                # thread's; a background frame would corrupt attribution
                self._write_step(step, write_payloads, extra, primary,
                                 barrier=False)
                _SAVE_HIST.observe(time.perf_counter() - t0)
                _SAVES_TOTAL.inc()
            except BaseException as e:
                self._pending_error = e
            finally:
                _INFLIGHT.set(0)

        self._pending = threading.Thread(
            target=task, name=f"mxnet-ckpt-save-{step}", daemon=True)
        self._pending.start()
        return final

    def _snapshot_payloads(self, net, trainer):
        """Host-resident copies of everything save() would write, as
        path-writer callables — the blocking (D2H) half of an async save."""
        import numpy as _np

        writers = {}
        if net is not None:
            snap = {k: _np.array(_np.asarray(v.data()._get()))
                    for k, v in net._collect_params_with_prefix().items()}

            def write_params(path, _snap=snap):
                from .ndarray.serialization import save as _save

                _save(path, _snap)

            writers["model.params"] = write_params
        if trainer is not None:
            blob = trainer._states_blob()

            def write_states(path, _blob=blob):
                with open(path, "wb") as f:
                    f.write(_blob)

            writers["trainer.states"] = write_states
        return writers

    def _join_pending(self, raise_=True):
        """Wait for the in-flight background write (if any); re-raise its
        failure unless ``raise_=False`` (then it is logged and dropped —
        the unpublished step is the cost)."""
        t = self._pending
        if t is not None:
            t.join()
            self._pending = None
        err, self._pending_error = self._pending_error, None
        if err is None:
            return
        if raise_:
            raise MXNetError(
                f"async checkpoint write for step {self._pending_step} "
                f"failed: {err!r}") from err
        self.logger.warning(
            "async checkpoint write for step %s failed (%r); that step "
            "was never published", self._pending_step, err)

    def close(self):
        """Join the in-flight async write; raises if it failed.  Call at
        the end of training (or use the manager as a context manager)."""
        self._join_pending()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        # don't mask an in-flight exception with the join's verdict
        self._join_pending(raise_=exc[0] is None)
        return False

    def restore(self, net=None, trainer=None, step=None, ctx=None):
        """Load the newest VALID checkpoint (default), or exactly ``step``
        when one is requested explicitly; returns the loaded step number,
        or 0 when no valid checkpoint exists.

        With ``step=None`` a checkpoint whose files fail checksum
        verification — or whose load raises — is skipped with a warning
        and the next older step is tried: one corrupt file must cost one
        checkpoint of progress, not the job.  An EXPLICIT ``step`` keeps
        the strict contract: the caller pinned that checkpoint
        (reproduction run, eval of a named step), so serving different
        weights would be silent corruption — missing or invalid raises."""
        # loading while a background save is staging/publishing would race
        # the writer (and the verify cache); a FAILED background write is
        # logged and costs its (never-published) step only
        self._join_pending(raise_=False)
        t0 = time.perf_counter()
        if step is not None:
            if step not in self.all_steps():
                raise MXNetError(
                    f"checkpoint {self._step_dir(step)} is not committed")
            problem = self.verify(step)
            if problem is not None:
                raise MXNetError(
                    f"checkpoint step {step} requested explicitly but "
                    f"failed verification: {problem}")
            self._load(step, net, trainer, ctx)
            _RESTORE_HIST.observe(time.perf_counter() - t0)
            _RESTORES_TOTAL.inc()
            return step
        for s in reversed(self.all_steps()):
            if s in self._load_failed:
                # stays skipped for this manager's lifetime even if the
                # failure was transient: latest_valid_step() skips it, so
                # loading it here would hand back step-s weights while
                # the supervisor already told train_fn to start at s-1
                continue
            problem = self.verify(s)
            if problem is not None:
                self.logger.warning(
                    "checkpoint step %d failed verification (%s); "
                    "falling back to an older step", s, problem)
                continue
            try:
                self._load(s, net, trainer, ctx)
            except Exception as e:  # checksum passed but load failed:
                # treat like corruption (e.g. pre-checksum checkpoint
                # with a torn file) and keep walking back; remember the
                # step so latest_valid_step stops advertising it
                self._load_failed.add(s)
                self.logger.warning(
                    "checkpoint step %d failed to load (%r); "
                    "falling back to an older step", s, e)
                continue
            _RESTORE_HIST.observe(time.perf_counter() - t0)
            _RESTORES_TOTAL.inc()
            return s
        return 0

    def _load(self, step, net, trainer, ctx):
        d = self._step_dir(step)
        if net is not None:
            net.load_parameters(os.path.join(d, "model.params"), ctx=ctx)
        if trainer is not None:
            tpath = os.path.join(d, "trainer.states")
            if os.path.exists(tpath):
                trainer.load_states(tpath)

    @property
    def compile_cache(self):
        """The warm-start compile cache living beside these checkpoints
        (``<directory>/compile_cache``; see :mod:`mxnet_tpu.
        compile_cache`).  None when ``MXNET_COMPILE_CACHE=0``.  Lazy —
        constructing a manager must not touch the cache dir.  Safe for
        every process to share: entries are content-addressed and
        published by atomic rename, so concurrent writers converge on
        identical files."""
        from . import compile_cache as _cc

        if self._compile_cache is None and _cc.enabled():
            self._compile_cache = _cc.CompileCache(
                os.path.join(self.directory, "compile_cache"))
        return self._compile_cache

    def read_meta(self, step):
        with open(os.path.join(self._step_dir(step), "meta.json")) as f:
            return json.load(f)

    def read_train_state(self, step):
        """The ``train_state`` dict saved with ``step`` (None when the
        checkpoint predates exact-resume or none was passed).  Feed it to
        ``lifecycle.restore_train_state`` after ``restore()``."""
        path = os.path.join(self._step_dir(step), "train_state.json")
        if not os.path.exists(path):
            return None
        with open(path) as f:
            return json.load(f)

    def _gc(self):
        steps = self.all_steps()
        for s in steps[:-self.max_to_keep] if self.max_to_keep else []:
            shutil.rmtree(self._step_dir(s), ignore_errors=True)
            self._valid_steps.pop(s, None)   # week-long jobs: no leak
            self._load_failed.discard(s)

    def _barrier(self):
        import jax

        if jax.process_count() > 1:
            from .parallel.collectives import barrier

            barrier()


def run_with_recovery(train_fn, manager, max_restarts=3,
                      should_retry=None, logger=None, backoff_ms=None,
                      resharder=None):
    """Supervised training loop: ``train_fn(start_step, manager)`` runs to
    completion or raises; on a retryable failure it is re-invoked from the
    latest checkpoint (elastic semantics for preemptible TPU jobs).

    - ``should_retry(exc) -> bool`` filters failures (default: retry
      everything except KeyboardInterrupt).
    - Restarts back off exponentially with full jitter (seed
      ``backoff_ms``, default MXNET_FAULT_BACKOFF_MS=100, capped at 30s)
      so a fleet of preempted workers does not re-stampede the
      coordinator.
    - The restart budget (``max_restarts``) counts CONSECUTIVE failures
      at the same checkpoint step: whenever ``manager.latest_step()``
      advanced since the previous failure the budget resets, so a
      long-running job survives unlimited preemptions as long as it keeps
      making progress.
    - Restart telemetry always reaches a logger — the module logger when
      ``logger`` is None — so silent restart loops show up in production
      logs.
    - A ``lifecycle.GracefulExit`` from train_fn is a PREEMPTED-CLEAN
      exit, not a failure: the final checkpoint is already published, so
      the supervisor joins any in-flight async write, does NOT count a
      restart, and re-raises — the caller translates it to
      ``sys.exit(lifecycle.EXIT_PREEMPTED)`` and the external scheduler
      relaunches the job, which resumes bit-identically.
    - ``resharder(exc) -> step | None`` is the zero-downtime elasticity
      hook (``lifecycle.elastic_resharder`` builds one): when the
      surviving in-process state is intact — and every SPMD peer AGREES
      it is — it live-reshards that state to the (possibly resized)
      mesh and returns the step the state corresponds to, so the next
      ``train_fn(start, manager)`` skips the checkpoint disk round trip
      entirely.  Returning None (state damaged, peers disagree, or the
      reshard itself failed) falls back to the checkpoint path — the
      choice is automatic, per failure.

    Returns train_fn's result."""
    from .lifecycle import GracefulExit

    log = logger or _LOGGER
    if backoff_ms is None:
        backoff_ms = fault.backoff_ms()
    # resume from the newest VERIFIED step: latest_step() would count a
    # corrupt checkpoint that restore() will skip, telling train_fn to
    # start past state it never loaded (silent step/state skew)
    progress = getattr(manager, "latest_valid_step", manager.latest_step)
    restarts = 0
    # per-path progress markers (see the reset logic below — live and
    # checkpoint steps are different clocks).  The checkpoint marker
    # seeds from the supervisor's starting state so the FIRST failure
    # already gets credit for any checkpoint published since launch.
    last_ckpt_step = progress() or 0
    last_live_step = None
    live_start = None
    fail_t = None          # goodput: failure -> next attempt downtime
    fail_bucket = "restart"  # or "rewind" for guard-verdict failures
    reshard_dt = 0.0       # resharder time inside that window (charged
    #                        to the reshard bucket by apply_transfer)
    while True:
        start = live_start if live_start is not None else progress() or 0
        live_start = None
        if fail_t is not None:
            # restart downtime: everything between the failure and this
            # re-attempt (join, progress probe, backoff sleep) except
            # the live-reshard transfer, which the resharding seam
            # already charged to its own bucket.  A numerical-integrity
            # failure (guard rewind/divergence) charges the ``rewind``
            # bucket instead: time lost to wrong VALUES, not to a lost
            # process — the distinction an SLO postmortem needs
            telemetry.goodput_note(
                fail_bucket,
                max(0.0, time.perf_counter() - fail_t - reshard_dt))
            fail_t, fail_bucket, reshard_dt = None, "restart", 0.0
        try:
            result = train_fn(start, manager)
            # a final async save may still be staging: join before the
            # supervisor returns (daemon writer threads die with the
            # interpreter).  Single-process, a FAILED final write raises
            # here, inside the try, so it re-enters the retry loop and
            # the lost step is re-trained instead of silently dropped.
            # Multi-process it is only logged: peers have already
            # returned, and a primary-only retry would desync their
            # collectives — the lost step escalates to the external
            # whole-job supervisor (PR 2's SPMD-restart philosophy).
            join = getattr(manager, "_join_pending", None)
            if join is not None:
                import jax

                join(raise_=jax.process_count() == 1)
            return result
        except KeyboardInterrupt:
            raise
        except GracefulExit as e:
            # preempted-clean: the loop honored a stop and published its
            # final checkpoint — never counted against the restart budget
            join = getattr(manager, "_join_pending", None)
            if join is not None:
                import jax

                join(raise_=jax.process_count() == 1)
            log.info("preempted-clean exit (%s); latest valid step %s",
                     e, progress())
            raise
        except Exception as e:
            if should_retry is not None and not should_retry(e):
                raise
            fail_t = time.perf_counter()
            from . import guard as _guard

            divergence = isinstance(e, _guard.NumericalDivergence)
            if divergence or isinstance(e, _guard.GuardRewind):
                fail_bucket = "rewind"
            # black-box first, while the ring still holds the failing
            # step's collectives: the dump is atomic and per-rank (the
            # mesh may be mid-desync — NEVER a collective here), and a
            # later successful recovery simply leaves the newest
            # abnormal event on record
            _flight.record_event("lifecycle", event="train_failure",
                                 error=repr(e)[:200])
            _flight.dump_blackbox("numerical_divergence" if divergence
                                  else "run_with_recovery_failure")
            # a background checkpoint write may still be in flight from
            # before the failure: let it finish (it may publish the step
            # that resets the budget) before judging progress — a FAILED
            # write is logged and its step simply never counts
            join = getattr(manager, "_join_pending", None)
            if join is not None:
                join(raise_=False)
            step_now = progress() or 0
            if resharder is not None:
                # live elasticity: reshard surviving state instead of
                # restoring from disk when the hook (with peer
                # agreement) says it is intact; any failure inside the
                # hook falls back to the checkpoint path.  Consulted
                # BEFORE the budget verdict: a live-resharded step is
                # progress exactly like a published checkpoint, so a
                # job advancing through preemptions between checkpoint
                # intervals must not exhaust the budget and die
                # "stuck" at a step it long passed.
                from .parallel import resharding as _resharding

                t_rs = time.perf_counter()
                rs_before = telemetry.goodput_summary()["buckets"].get(
                    "reshard", 0.0)
                try:
                    live_start = resharder(e)
                except Exception as re:
                    live_start = None
                    log.warning("live resharder failed (%r); falling "
                                "back to checkpoint restore", re)
                reshard_dt = time.perf_counter() - t_rs
                # the whole resharder call is reshard-bucket time, but
                # only its apply_transfer portion self-charges at the
                # seam — top the bucket up with the uncovered remainder
                # (plan building, agreement, a raise BEFORE the
                # transfer) so the time subtracted from the restart
                # bucket below never vanishes from the ledger
                covered = telemetry.goodput_summary()["buckets"].get(
                    "reshard", 0.0) - rs_before
                telemetry.goodput_note("reshard",
                                       max(0.0, reshard_dt - covered))
                if live_start is not None:
                    _resharding.record_live_reshard()
                    log.info("live reshard accepted: resuming from "
                             "in-process state at step %s (checkpoint "
                             "would have been step %s)", live_start,
                             step_now)
                else:
                    _resharding.record_reshard_fallback()
            # progress resets the budget — only repeated failures at
            # the SAME point are a crash loop.  Each recovery path is
            # compared against ITS OWN last marker: a live step and a
            # checkpoint step are different clocks (a lost live reshard
            # can outrun the checkpoints; later checkpoint advances
            # below it are still real progress and must still reset).
            # Both quantities are peer-agreed/deterministic, so the
            # verdict is uniform across SPMD peers.
            if live_start is not None:
                progressed = last_live_step is not None and \
                    live_start > last_live_step
                last_live_step = live_start
                effective = live_start
            else:
                progressed = step_now > last_ckpt_step
                last_ckpt_step = step_now
                effective = step_now
            if progressed:
                log.info("progress advanced to step %s between "
                         "failures (%s); restart budget reset",
                         effective,
                         "live reshard" if live_start is not None
                         else "checkpoint")
                restarts = 0
            restarts += 1
            _RESTARTS_TOTAL.inc()
            _flight.record_event("lifecycle", event="restart",
                                 attempt=restarts, step=effective)
            if restarts > max_restarts:
                _flight.dump_blackbox("restart_budget_exhausted")
                raise MXNetError(
                    f"training failed after {max_restarts} restarts "
                    f"without progress (stuck at step "
                    f"{effective}; last error: {e!r})") from e
            delay = fault.backoff_delay(restarts - 1, backoff_ms)
            log.warning("restart %d/%d from step %s in %.3fs after: %r",
                        restarts, max_restarts, effective, delay, e)
            if delay > 0:
                time.sleep(delay)
