"""Checkpoint/resume + failure recovery.

Reference scope (SURVEY.md §6.3): MXNet 1.x ships Module.save_checkpoint /
load_checkpoint and leaves elastic recovery to the operator; modern TPU
jobs need the full loop — atomic checkpoints, auto-resume from the latest
good step, and a supervised retry wrapper (the moral equivalent of the
ps-lite worker-restart story, redesigned for SPMD jobs where every process
restarts together).

Design:
- ``CheckpointManager``: step-indexed directory layout, ATOMIC publishes
  (write to tmp, fsync, rename — a partially-written checkpoint is never
  visible), bounded retention, ``latest_step()`` discovery for resume.
  In a multi-process job only process 0 writes (weights are replicated);
  all processes barrier on publish so no one resumes past a checkpoint a
  peer has not finished.
- ``run_with_recovery``: restarts a training function from the latest
  checkpoint after transient failures (preemption, XLA OOM after
  defragmentation, flaky interconnect) with bounded retries.
"""
from __future__ import annotations

import json
import os
import shutil
import tempfile
import time

from .base import MXNetError

__all__ = ["CheckpointManager", "run_with_recovery"]


def _fsync_file(path):
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _fsync_dir(path):
    try:
        fd = os.open(path, os.O_RDONLY | getattr(os, "O_DIRECTORY", 0))
    except OSError:  # pragma: no cover - platforms without O_DIRECTORY
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


class CheckpointManager:
    """Atomic, step-indexed checkpoints for Gluon nets + Trainers.

    Usage::

        mgr = CheckpointManager(dir, max_to_keep=3)
        start = mgr.restore(net, trainer)  # 0 if none yet
        for epoch in range(start, n):
            ...train...
            mgr.save(epoch + 1, net, trainer)
    """

    def __init__(self, directory, max_to_keep=5):
        self.directory = directory
        self.max_to_keep = max_to_keep
        os.makedirs(directory, exist_ok=True)

    # -- discovery ---------------------------------------------------------
    def _step_dir(self, step):
        return os.path.join(self.directory, f"step_{step:08d}")

    def all_steps(self):
        out = []
        for name in os.listdir(self.directory):
            if name.startswith("step_") and os.path.isdir(
                    os.path.join(self.directory, name)) and \
                    os.path.exists(os.path.join(self.directory, name,
                                                "COMMITTED")):
                out.append(int(name[len("step_"):]))
        return sorted(out)

    def latest_step(self):
        steps = self.all_steps()
        return steps[-1] if steps else None

    # -- save/restore ------------------------------------------------------
    def save(self, step, net=None, trainer=None, extra=None):
        """Publish checkpoint `step` atomically; returns its directory."""
        import jax

        primary = jax.process_index() == 0
        final = self._step_dir(step)
        try:
            if primary:
                tmp = tempfile.mkdtemp(prefix=f".tmp_step_{step}_",
                                       dir=self.directory)
                try:
                    if net is not None:
                        net.save_parameters(
                            os.path.join(tmp, "model.params"))
                    if trainer is not None:
                        trainer.save_states(
                            os.path.join(tmp, "trainer.states"))
                    meta = {"step": int(step), "time": time.time()}
                    if extra:
                        meta["extra"] = extra
                    with open(os.path.join(tmp, "meta.json"), "w") as f:
                        json.dump(meta, f)
                    # durability: every payload file reaches the platter
                    # BEFORE the commit marker exists, and the marker +
                    # directory entries before the publish rename
                    for name in os.listdir(tmp):
                        _fsync_file(os.path.join(tmp, name))
                    with open(os.path.join(tmp, "COMMITTED"), "w") as f:
                        f.write("1")
                        f.flush()
                        os.fsync(f.fileno())
                    _fsync_dir(tmp)
                    if os.path.exists(final):
                        shutil.rmtree(final)
                    os.rename(tmp, final)
                    _fsync_dir(self.directory)
                except Exception:
                    shutil.rmtree(tmp, ignore_errors=True)
                    raise
                self._gc()
        finally:
            # ALL processes must reach the barrier even when the primary's
            # write fails — otherwise the peers deadlock in the collective
            self._barrier()
        return final

    def restore(self, net=None, trainer=None, step=None, ctx=None):
        """Load the latest (or given) checkpoint; returns the step number,
        or 0 when no checkpoint exists yet."""
        step = step if step is not None else self.latest_step()
        if step is None:
            return 0
        d = self._step_dir(step)
        if not os.path.exists(os.path.join(d, "COMMITTED")):
            raise MXNetError(f"checkpoint {d} is not committed")
        if net is not None:
            net.load_parameters(os.path.join(d, "model.params"), ctx=ctx)
        if trainer is not None:
            tpath = os.path.join(d, "trainer.states")
            if os.path.exists(tpath):
                trainer.load_states(tpath)
        return step

    def read_meta(self, step):
        with open(os.path.join(self._step_dir(step), "meta.json")) as f:
            return json.load(f)

    def _gc(self):
        steps = self.all_steps()
        for s in steps[:-self.max_to_keep] if self.max_to_keep else []:
            shutil.rmtree(self._step_dir(s), ignore_errors=True)

    def _barrier(self):
        import jax

        if jax.process_count() > 1:
            from .parallel.collectives import barrier

            barrier()


def run_with_recovery(train_fn, manager, max_restarts=3,
                      should_retry=None, logger=None):
    """Supervised training loop: ``train_fn(start_step, manager)`` runs to
    completion or raises; on a retryable failure it is re-invoked from the
    latest checkpoint (elastic semantics for preemptible TPU jobs).

    ``should_retry(exc) -> bool`` filters failures (default: retry
    everything except KeyboardInterrupt).  Returns train_fn's result."""
    restarts = 0
    while True:
        start = manager.latest_step() or 0
        try:
            return train_fn(start, manager)
        except KeyboardInterrupt:
            raise
        except Exception as e:
            if should_retry is not None and not should_retry(e):
                raise
            restarts += 1
            if restarts > max_restarts:
                raise MXNetError(
                    f"training failed after {max_restarts} restarts "
                    f"(last error: {e!r})") from e
            if logger is not None:
                logger.warning("restart %d/%d from step %s after: %r",
                               restarts, max_restarts,
                               manager.latest_step(), e)
