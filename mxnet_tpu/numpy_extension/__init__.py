"""mx.npx — operator extensions for the NumPy namespace.

Reference: ``python/mxnet/numpy_extension/`` (``mx.npx``: the neural-net
operators and mode switches that NumPy itself has no name for).  Delegates
to the shared op registry, so ``npx.softmax`` etc. are the exact kernels
``mx.nd`` uses.
"""
from __future__ import annotations

from ..ndarray.ndarray import NDArray, invoke

__all__ = ["set_np", "reset_np", "is_np_array", "is_np_shape",
           "softmax", "log_softmax", "relu", "sigmoid", "activation",
           "one_hot", "pick", "topk", "batch_dot", "gamma", "erf",
           "gelu", "leaky_relu"]

_np_array = False
_np_shape = False


def set_np(shape=True, array=True):
    """Enable/disable numpy semantics (reference: npx.set_np — the flags
    deactivate when passed False).

    ``shape`` gates zero-dim support in the LEGACY ``mx.nd`` namespace:
    off (the default), ``mx.nd.array(scalar)`` promotes to shape (1,)
    exactly like the reference's legacy NDArray; on, scalars keep shape
    ().  ``mx.np`` is unaffected — numpy semantics are native there.
    ``array`` records intent only: ``mx.np.ndarray`` IS the framework
    NDArray in this build, so there is no separate array type to switch
    Gluon outputs to (the honest no-op, documented)."""
    global _np_array, _np_shape
    _np_array = bool(array)
    _np_shape = bool(shape)


def reset_np():
    set_np(shape=False, array=False)


def is_np_array():
    return _np_array


def is_np_shape():
    return _np_shape


def _op(opname, *args, **attrs):
    # invoke() coerces raw numpy/list inputs itself — pass everything through
    return invoke(opname, list(args), attrs)


def softmax(data, axis=-1):
    return _op("softmax", data, axis=axis)


def log_softmax(data, axis=-1):
    return _op("log_softmax", data, axis=axis)


def relu(data):
    return _op("relu", data)


def sigmoid(data):
    return _op("sigmoid", data)


def gelu(data):
    return _op("LeakyReLU", data, act_type="gelu")


def leaky_relu(data, slope=0.25):
    return _op("LeakyReLU", data, act_type="leaky", slope=slope)


def activation(data, act_type="relu"):
    return _op("Activation", data, act_type=act_type)


def one_hot(data, depth, on_value=1.0, off_value=0.0, dtype="float32"):
    return _op("one_hot", data, depth=depth, on_value=on_value,
               off_value=off_value, dtype=dtype)


def pick(data, index, axis=-1, keepdims=False):
    return invoke("pick", [data, index], {"axis": axis, "keepdims": keepdims})


def topk(data, k=1, axis=-1, ret_typ="indices", is_ascend=False):
    return _op("topk", data, k=k, axis=axis, ret_typ=ret_typ,
               is_ascend=is_ascend)


def batch_dot(a, b, transpose_a=False, transpose_b=False):
    return invoke("batch_dot", [a, b], {"transpose_a": transpose_a,
                                        "transpose_b": transpose_b})


def gamma(data):
    return _op("gamma", data)


def erf(data):
    return _op("erf", data)
