"""Graph compiler tier (ISSUE 11): Relay/TVM-style optimization passes
between the traced (hybridized) graph and XLA lowering.

``hybridize()``'s cached op, ``parallel.functionalize`` (TrainStep and
the serving export/AOT path), and ``SymbolBlock`` all route their
traced program through :func:`default_pipeline` when
``MXNET_GRAPH_PIPELINE`` is on (the default) — constant folding, CSE,
AMP-cast placement, elementwise-chain fusion and DCE run over the
typed :class:`Graph` IR, and the optimized graph is what jit lowers.
Every pass is pure (MXT070) and bit-parity-preserving on fp32 paths;
``subgraph.optimize_for`` backends are sugar over the same pipeline.
"""
from .ir import Graph, Node
from .pipeline import (DEFAULT_PASSES, PassPipeline, default_pipeline,
                       enabled, graph_pass, list_passes, override_enabled,
                       record_fallback, reset_stats, selected_pass_names,
                       stats_snapshot)
from . import passes as _passes  # noqa: F401  (registers the builtins)
from .executor import make_block_fn
from .trace import trace_block

__all__ = ["Graph", "Node", "PassPipeline", "default_pipeline", "enabled",
           "override_enabled", "graph_pass", "list_passes",
           "selected_pass_names", "DEFAULT_PASSES", "stats_snapshot",
           "reset_stats", "record_fallback", "make_block_fn", "trace_block"]
