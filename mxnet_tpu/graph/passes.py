"""The built-in optimization passes (pure ``Graph -> Graph``).

Every pass carries a bit-parity contract: on fp32 paths the optimized
graph's outputs BIT-MATCH the unoptimized graph's (the pipeline A/B
tests pin this).  The mechanisms used are exact by construction —
constant folding evaluates the same registry kernels under the same
AMP wrap the executor would; CSE merges structurally identical
deterministic nodes; DCE only removes unreachable work; chain fusion
replays the captured kernels in order inside one registered op; and
the AMP-cast pass applies only bit-exact moves (identity-cast removal,
widen-then-narrow collapse, commuting casts with data-movement ops).
RNG-consuming ops are excluded from folding/CSE/fusion and keep their
trace-stamped fold_in counters, so key streams never shift.

Purity (MXT070): a pass must never mutate the input graph's nodes or
attrs — each starts from ``graph.copy()`` and only mutates the copy.
"""
from __future__ import annotations

import numpy as _np

from .. import env as _env
from ..ops.registry import OP_TABLE
from .fusion import fused_plan_summary, plan_digest, register_fused_chain
from .ir import Graph, Node
from .pipeline import graph_pass

__all__ = ["fold_constants", "eliminate_common_subexpr",
           "place_amp_casts", "fuse_elemwise_chains",
           "eliminate_dead_nodes", "ELEMWISE_OPS"]

# ops a chain-fusion region may absorb: one output, elementwise, no RNG,
# no training-mode state injection (same exclusions as subgraph islands)
ELEMWISE_OPS = frozenset({
    "Activation", "activation", "relu", "sigmoid", "tanh", "softsign",
    "gelu", "silu", "softrelu", "exp", "log", "sqrt", "rsqrt", "square",
    "abs", "sign", "negative", "clip", "swiglu",
    "broadcast_add", "broadcast_sub", "broadcast_mul", "broadcast_div",
    "broadcast_maximum", "broadcast_minimum", "broadcast_power",
    "elemwise_add", "elemwise_sub", "elemwise_mul", "elemwise_div",
    "broadcast_add_scalar", "broadcast_sub_scalar", "broadcast_mul_scalar",
    "broadcast_div_scalar", "broadcast_maximum_scalar",
    "broadcast_minimum_scalar", "broadcast_power_scalar",
    "Cast", "cast", "amp_cast",
})

# never folded/CSE'd: executor injects per-call behavior (training-mode
# state threading) keyed on these names
_STATE_SENSITIVE = frozenset({"BatchNorm", "Dropout", "RNN"})

_FOLD_MAX_ELEMENTS = 1 << 20


def _literal(v):
    if isinstance(v, (type(None), bool, int, float, str, slice)):
        return True
    if isinstance(v, (tuple, list)):
        return all(_literal(x) for x in v)
    return False


def _clean(attrs):
    return {k: v for k, v in attrs.items() if not k.startswith("__")}


def _apply_edge_remap(g, remap):
    """Rewrite every edge of ``g`` (a fresh copy) through ``remap``,
    following chains so a->b->c resolves to c."""
    if not remap:
        return

    def res(e):
        seen = set()
        while e in remap and e not in seen:
            seen.add(e)
            e = remap[e]
        return e

    for n in g.nodes:
        n.inputs = [res(e) for e in n.inputs]
    g.outputs = [res(e) for e in g.outputs]
    g.state = [(k, res(e)) for k, e in g.state]


def _dtype_of(g, edge):
    nid, idx = edge
    avals = g.nodes[nid].avals
    if avals is None or idx >= len(avals):
        return None
    return _np.dtype(avals[idx][1])


# --------------------------------------------------------------------------
@graph_pass("fold_constants")
def fold_constants(graph):
    """Evaluate op nodes whose inputs are all constants and embed the
    result (the executor would recompute them every call; XLA usually
    folds too, but folding here shrinks the traced program and lets CSE
    and fusion see through the values).  Evaluation runs the same
    registry kernel under the same AMP wrap the executor applies, so
    the embedded value is the value the unfolded graph would produce."""
    import functools

    import jax.numpy as jnp

    from ..ndarray.ndarray import _AMP, _call_with_attrs

    g = graph.copy()
    counts = g.consumer_counts()
    const_vals = {}
    for nid, n in enumerate(g.nodes):
        if n.is_const:
            const_vals[(nid, 0)] = n.value
    folded = {}
    wrap = _AMP["wrap"] if _AMP["on"] else None
    for nid, n in enumerate(g.nodes):
        if n.op is None or not counts.get(nid):
            continue
        od = OP_TABLE.get(n.op)
        if od is None or od.needs_rng or not od.jit_safe or \
                n.op in _STATE_SENSITIVE:
            continue
        if not all(e in const_vals for e in n.inputs):
            continue
        f = functools.partial(_call_with_attrs, od.fn, _clean(n.attrs))
        if wrap is not None:
            f = wrap(od, f)
        try:
            out = f(*(jnp.asarray(const_vals[e]) for e in n.inputs))
        except Exception:
            continue
        outs = out if isinstance(out, (tuple, list)) else (out,)
        if any(getattr(o, "size", _FOLD_MAX_ELEMENTS + 1) >
               _FOLD_MAX_ELEMENTS for o in outs):
            continue
        vals = [_np.asarray(o) for o in outs]
        folded[nid] = vals
        for i, v in enumerate(vals):
            const_vals[(nid, i)] = v
    if not folded:
        return g

    used = set()
    for n in g.nodes:
        used.update(n.inputs)
    used.update(g.outputs)
    used.update(e for _, e in g.state)

    new_nodes, id_map, remap = [], {}, {}
    for nid, n in enumerate(g.nodes):
        if nid in folded:
            for i, v in enumerate(folded[nid]):
                if (nid, i) in used:
                    remap[(nid, i)] = (len(new_nodes), 0)
                    new_nodes.append(Node(
                        None, f"{n.name}_fold{i}", {}, [], 1, v,
                        avals=((tuple(v.shape), str(v.dtype)),)))
        else:
            id_map[nid] = len(new_nodes)
            new_nodes.append(n)
    def res(e):
        return remap[e] if e in remap else (id_map[e[0]], e[1])

    for n in new_nodes:
        n.inputs = [res(e) for e in n.inputs]
    out = Graph(
        new_nodes, [id_map[i] for i in g.inputs],
        [(id_map[i], nm) for i, nm in g.params],
        [res(e) for e in g.outputs],
        [(k, res(e)) for k, e in g.state],
        g.single)
    return out.validate()


# --------------------------------------------------------------------------
@graph_pass("eliminate_common_subexpr")
def eliminate_common_subexpr(graph):
    """Merge structurally identical deterministic nodes (same op, attrs,
    inputs): later duplicates re-route to the earliest occurrence.
    RNG ops never merge (two dropouts are two draws), state-injecting
    ops (BatchNorm/Dropout/RNN) never merge (their write-back heads
    must stay distinct); constants merge by value."""
    g = graph.copy()
    canon = {}
    remap = {}
    for nid, n in enumerate(g.nodes):
        n.inputs = [remap.get(e, e) for e in n.inputs]
        if n.is_var:
            continue
        if n.is_const:
            v = _np.asarray(n.value)
            key = ("__const__", str(v.dtype), v.shape, v.tobytes())
            first = canon.get(key)
            if first is None:
                canon[key] = nid
            else:
                remap[(nid, 0)] = (first, 0)
            continue
        od = OP_TABLE.get(n.op)
        if od is None or od.needs_rng or n.op in _STATE_SENSITIVE:
            continue
        if not all(_literal(v) for v in n.attrs.values()):
            continue
        key = (n.op, tuple(sorted((k, repr(v)) for k, v in n.attrs.items())),
               tuple(n.inputs), n.nout)
        first = canon.get(key)
        if first is None:
            canon[key] = nid
        else:
            for i in range(n.nout):
                remap[(nid, i)] = (first, i)
    _apply_edge_remap(g, remap)
    return g.validate()


# --------------------------------------------------------------------------
_CAST_OPS = frozenset({"Cast", "cast", "amp_cast"})
_MOVEMENT_OPS = frozenset({"reshape", "Reshape", "transpose", "expand_dims",
                           "squeeze", "flatten", "Flatten"})
_EXACT_WIDENINGS = {
    _np.dtype("float16"): (_np.dtype("float32"), _np.dtype("float64")),
    _np.dtype("float32"): (_np.dtype("float64"),),
}


def _bf16():
    import jax.numpy as jnp

    return jnp.bfloat16


def _is_exact_widening(narrow, wide):
    try:
        narrow = _np.dtype(narrow)
        wide = _np.dtype(wide)
    except TypeError:
        return False
    if narrow == _np.dtype(_bf16()):
        return wide in (_np.dtype("float32"), _np.dtype("float64"))
    return wide in _EXACT_WIDENINGS.get(narrow, ())


@graph_pass("place_amp_casts")
def place_amp_casts(graph):
    """Bit-exact cast placement: drop identity casts, collapse
    widen-then-narrow round trips back to the source, and hoist casts
    above single-consumer data-movement ops (reshape/transpose/...)
    so redundant casts on hot chains meet — and CSE merges them.
    Moves that would change numerics are never made."""
    g = graph.copy()
    for _ in range(8):
        counts = g.consumer_counts()
        remap = {}
        changed = False
        for nid, n in enumerate(g.nodes):
            if n.op not in _CAST_OPS or not n.inputs:
                continue
            in_edge = n.inputs[0]
            src_dt = _dtype_of(g, in_edge)
            try:
                tgt_dt = _np.dtype(n.attrs.get("dtype"))
            except TypeError:
                continue
            if src_dt is not None and src_dt == tgt_dt:
                remap[(nid, 0)] = in_edge          # identity cast
                changed = True
                continue
            pid, pidx = in_edge
            producer = g.nodes[pid]
            if producer.op in _CAST_OPS and producer.inputs:
                base_edge = producer.inputs[0]
                base_dt = _dtype_of(g, base_edge)
                wide_dt = _dtype_of(g, in_edge)
                if base_dt is not None and wide_dt is not None and \
                        base_dt == tgt_dt and \
                        _is_exact_widening(base_dt, wide_dt):
                    remap[(nid, 0)] = base_edge    # narrow(wide(x)) == x
                    changed = True
                    continue
            if producer.op in _MOVEMENT_OPS and producer.nout == 1 and \
                    counts.get(pid) == 1 and producer.inputs and \
                    src_dt is not None:
                # swap in place: cast(move(x)) -> move(cast(x)) — a pure
                # element permutation commutes with the cast bit-exactly
                base_edge = producer.inputs[0]
                base_shape = None
                if g.nodes[base_edge[0]].avals is not None and \
                        base_edge[1] < len(g.nodes[base_edge[0]].avals):
                    base_shape = g.nodes[base_edge[0]].avals[base_edge[1]][0]
                move_shape = producer.avals[0][0] \
                    if producer.avals else None
                new_cast = Node(n.op, f"{n.name}_hoist", dict(n.attrs),
                                [base_edge], 1, None,
                                avals=None if base_shape is None else
                                ((base_shape, str(tgt_dt)),))
                new_move = Node(producer.op, producer.name,
                                dict(producer.attrs), [(pid, 0)], 1, None,
                                avals=None if move_shape is None else
                                ((move_shape, str(tgt_dt)),))
                g.nodes[pid] = new_cast
                g.nodes[nid] = new_move
                changed = True
        _apply_edge_remap(g, remap)
        if not changed:
            break
    return g.validate()


# --------------------------------------------------------------------------
@graph_pass("fuse_elemwise_chains")
def fuse_elemwise_chains(graph):
    """Collapse linear single-consumer chains of elementwise ops into one
    registered fused op each (``MXNET_GRAPH_FUSE_CAP`` bounds chain
    length).  The fused op replays the captured kernels in order under
    the executor's own AMP wrap — one node, one dispatch, identical
    numerics."""
    try:
        from .. import tuning as _tuning

        cap = int(_tuning.resolve("graph_fuse_cap"))
    except Exception:
        cap = _env.graph_fuse_cap()
    if cap < 2:
        return graph.copy()
    g = graph.copy()
    counts = g.consumer_counts()
    head_ids = {nid for nid, _ in g.outputs} | \
               {nid for _, (nid, _) in g.state}

    def eligible(nid):
        n = g.nodes[nid]
        od = OP_TABLE.get(n.op)
        return n.op in ELEMWISE_OPS and n.nout == 1 and \
            od is not None and not od.needs_rng

    consumers = {}
    for cid, n in enumerate(g.nodes):
        for pid, idx in n.inputs:
            if idx == 0:
                consumers.setdefault(pid, []).append(cid)
    next_of, has_prev = {}, set()
    for nid in range(len(g.nodes)):
        if not eligible(nid) or counts.get(nid) != 1 or nid in head_ids:
            continue
        # counts == 1 means the single consuming edge appears exactly once
        cons = consumers.get(nid)
        if cons and eligible(cons[0]):
            next_of[nid] = cons[0]
            has_prev.add(cons[0])

    chains = []
    for nid in range(len(g.nodes)):
        if not eligible(nid) or nid in has_prev:
            continue
        full = [nid]
        while full[-1] in next_of:
            full.append(next_of[full[-1]])
        # the cap splits long chains into bounded segments, each fused
        for i in range(0, len(full), cap):
            seg = full[i:i + cap]
            if len(seg) >= 2:
                chains.append(seg)

    if not chains:
        return g
    member_of = {}
    for ci, chain in enumerate(chains):
        for nid in chain:
            member_of[nid] = ci

    new_nodes, id_map = [], {}
    fused_at = {chain[-1]: chain for chain in chains}
    for nid, n in enumerate(g.nodes):
        if nid in member_of and nid not in fused_at:
            continue                      # interior chain member
        if nid in fused_at:
            chain = fused_at[nid]
            chain_ids = set(chain)
            pos = {m: i for i, m in enumerate(chain)}
            ext, ext_index = [], {}
            plan = []
            for m in chain:
                srcs = []
                for e in g.nodes[m].inputs:
                    if e[0] in chain_ids:
                        srcs.append(("step", pos[e[0]]))
                    else:
                        if e not in ext_index:
                            ext_index[e] = len(ext)
                            ext.append(e)
                        srcs.append(("ext", ext_index[e]))
                plan.append((g.nodes[m].op, _clean(g.nodes[m].attrs),
                             tuple(srcs)))
            opname = register_fused_chain(plan)
            tail = g.nodes[chain[-1]]
            fused = Node(opname, f"{g.nodes[chain[0]].name}_gfused",
                         {"__fused_plan__": fused_plan_summary(plan),
                          "__fused_sig__": plan_digest(plan),
                          "__n_fused__": len(chain)},
                         list(ext), 1, None, avals=tail.avals)
            id_map[nid] = len(new_nodes)
            new_nodes.append(fused)
        else:
            id_map[nid] = len(new_nodes)
            new_nodes.append(n)
    # resolve edges: chain tails -> fused node, everything else -> id_map
    def res(e):
        nid, idx = e
        if nid in fused_at:
            return (id_map[nid], 0)
        return (id_map[nid], idx)

    for n in new_nodes:
        n.inputs = [res(e) for e in n.inputs]
    out = Graph(new_nodes, [id_map[i] for i in g.inputs],
                [(id_map[i], nm) for i, nm in g.params],
                [res(e) for e in g.outputs],
                [(k, res(e)) for k, e in g.state], g.single)
    return out.validate()


# --------------------------------------------------------------------------
@graph_pass("eliminate_dead_nodes")
def eliminate_dead_nodes(graph):
    """Drop nodes unreachable from the output/state heads.  Declared
    input and parameter variables always survive — the executor binds
    them positionally, so the call signature is stable."""
    return graph.compact(graph.live_ids()).validate()
