"""Trace a HybridBlock forward into the graph IR.

The trace reuses the export-path machinery (SymbolTracer proxies through
``ndarray.invoke``) but, unlike ``_trace_to_symbol``, it is execution-
faithful: it runs under the CURRENT training mode, records node CREATION
order (via ``symbol._TRACE_OBSERVER``) so the executor replays ops in
the exact sequence the imperative jit trace would, stamps every
needs_rng op with its fold_in counter at trace time, and captures the
running-state write-backs (BatchNorm moving stats) as extra graph
heads.  Anything the proxies cannot express (``apply_fn`` composites,
host reads in forward) raises — callers fall back to the imperative jit
path and record a ``graph_fallback`` compile event.
"""
from __future__ import annotations

import numpy as _np

from ..base import MXNetError
from .ir import Graph, Node

__all__ = ["trace_block"]


def _aval_sig(aval):
    return (tuple(aval.shape), str(_np.dtype(aval.dtype)))


def trace_block(block, param_items, input_avals, train_mode=False):
    """Trace ``block.forward`` once into a :class:`Graph`.

    ``param_items``: ordered ``(name, Parameter)`` pairs — positional
    binding order of the executor's ``param_vals``.  ``input_avals``:
    ``jax.ShapeDtypeStruct`` per data input.  Returns a validated Graph
    whose ``state`` entries name parameters from ``param_items``.
    """
    import jax

    from .. import autograd as _ag
    from ..gluon.block import _TRACE, _TraceContext
    from ..ndarray import ndarray as _ndmod
    from ..ops.registry import get_op
    from ..symbol.symbol import SymbolTracer, _Node, _TRACE_OBSERVER

    nodes, inputs, params = [], [], []
    sid = {}                 # id(_Node) -> graph node id

    def add(sn, rng_index=None, avals=None):
        nid = len(nodes)
        sid[id(sn)] = nid
        nodes.append(Node(sn.op, sn.name, dict(sn.attrs),
                          [], sn.nout, sn.value,
                          rng_index=rng_index, avals=avals))
        return nid

    param_map, name_of = {}, {}
    for name, p in param_items:
        d = p.data()
        aval = jax.ShapeDtypeStruct(tuple(d.shape), _np.dtype(d.dtype))
        sn = _Node(None, name, {})
        params.append((add(sn, avals=(_aval_sig(aval),)), name))
        param_map[p] = SymbolTracer((sn, 0), aval)
        name_of[id(p)] = name
    in_tracers = []
    for i, aval in enumerate(input_avals):
        name = "data" if len(input_avals) == 1 else f"data{i}"
        sn = _Node(None, name, {})
        inputs.append(add(sn, avals=(_aval_sig(aval),)))
        in_tracers.append(SymbolTracer((sn, 0), aval))

    recorded = []            # (sym node, avals) in creation order
    rng_counter = [0]
    rng_of = {}

    def observe(sn, out_avals):
        if get_op(sn.op).needs_rng:
            # the imperative trace key is fold_in(base, counter) with the
            # counter bumped once per needs_rng invoke — same numbering
            rng_counter[0] += 1
            rng_of[id(sn)] = rng_counter[0]
        recorded.append((sn, tuple(_aval_sig(a) for a in out_avals)))

    tc = _TraceContext(param_map)
    prev_ctx = _TRACE.ctx
    prev_obs = _TRACE_OBSERVER[0]
    if prev_obs is not None:
        raise MXNetError("graph trace is not reentrant")
    _TRACE.ctx = tc
    _TRACE_OBSERVER[0] = observe
    prev_train = _ag.set_training(train_mode)
    prev_rec = _ag.set_recording(False)
    _ndmod._SYMTRACE["on"] = True
    _ndmod._SYMTRACE["rng_ops"] = True
    try:
        out = block.forward(*in_tracers)
    finally:
        _ndmod._SYMTRACE["rng_ops"] = False
        _ndmod._SYMTRACE["on"] = False
        _ag.set_recording(prev_rec)
        _ag.set_training(prev_train)
        _TRACE_OBSERVER[0] = prev_obs
        _TRACE.ctx = prev_ctx

    # materialize ops in creation order, pulling each op's still-unseen
    # inputs (constants lifted by trace_invoke) in just before it
    for sn, out_avals in recorded:
        for inp, _ in sn.inputs:
            if id(inp) not in sid:
                if inp.op is not None:
                    raise MXNetError(
                        f"graph trace: op node {inp.name} was consumed but "
                        "never observed")
                if inp.is_var:
                    raise MXNetError(
                        f"graph trace: unbound variable {inp.name!r} "
                        "(neither a parameter nor a data input)")
                add(inp, avals=((tuple(inp.value.shape),
                                 str(inp.value.dtype)),))
        nid = len(nodes)
        sid[id(sn)] = nid
        nodes.append(Node(sn.op, sn.name, dict(sn.attrs),
                          [(sid[id(i)], idx) for i, idx in sn.inputs],
                          sn.nout, sn.value,
                          rng_index=rng_of.get(id(sn)), avals=out_avals))

    single = not isinstance(out, (list, tuple))
    outs = [out] if single else list(out)
    heads = []
    for o in outs:
        if not isinstance(o, SymbolTracer):
            raise MXNetError(
                "graph trace: forward returned a non-traced value "
                f"({type(o).__name__})")
        n, idx = o._symhead
        if id(n) not in sid:
            # forward returned an input/param unchanged — vars are in sid
            raise MXNetError("graph trace: output head was never recorded")
        heads.append((sid[id(n)], idx))

    state = []
    for p, v in tc.state_updates:
        if not isinstance(v, SymbolTracer):
            raise MXNetError(
                "graph trace: state update carried a concrete value")
        pname = name_of.get(id(p))
        if pname is None:
            raise MXNetError(
                f"graph trace: state update targets unknown parameter "
                f"{getattr(p, 'name', p)!r}")
        n, idx = v._symhead
        state.append((pname, (sid[id(n)], idx)))

    return Graph(nodes, inputs, params, heads, state, single).validate()
