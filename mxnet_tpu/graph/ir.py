"""Typed graph IR for the compiler tier (ISSUE 11; TVM/Relay playbook).

A :class:`Graph` is the explicit, pass-amenable form of one traced
HybridBlock computation (or one ``mx.sym`` graph): nodes are registered
ops with attrs, edges are data dependencies ``(node_id, out_index)``,
and the graph-level metadata marks which variables are parameters,
which are data inputs, and which edges feed running-state write-backs
(BatchNorm moving stats).  Node order IS execution order — the trace
records creation order, and the executor replays it — so RNG-consuming
ops draw the same fold_in keys as the imperative jit path (the
bit-parity contract every pass must preserve).

Passes are pure ``Graph -> Graph`` functions (MXT070-enforced): they
never mutate the input graph's nodes or attrs — :meth:`Graph.copy`
gives a fresh, freely mutable twin.
"""
from __future__ import annotations

import hashlib

import numpy as _np

from ..base import MXNetError

__all__ = ["Node", "Graph"]


class Node:
    """One graph node: an op application, a variable (op=None, value=None)
    or an embedded constant (op=None, value=ndarray).

    ``inputs`` are ``(node_id, out_index)`` edges into earlier nodes.
    ``rng_index`` is the trace-time fold_in counter for needs_rng ops —
    pinned at trace so passes that drop or reorder nodes can never shift
    another op's key stream.  ``avals`` is the per-output
    ``(shape, dtype_str)`` tuple captured at trace time (None when built
    from a shape-oblivious Symbol).
    """

    __slots__ = ("op", "name", "attrs", "inputs", "nout", "value",
                 "rng_index", "avals")

    def __init__(self, op, name, attrs=None, inputs=(), nout=1, value=None,
                 rng_index=None, avals=None):
        self.op = op
        self.name = name
        self.attrs = dict(attrs or {})
        self.inputs = list(inputs)
        self.nout = nout
        self.value = value
        self.rng_index = rng_index
        self.avals = avals

    @property
    def is_var(self):
        return self.op is None and self.value is None

    @property
    def is_const(self):
        return self.op is None and self.value is not None

    def clone(self):
        return Node(self.op, self.name, dict(self.attrs), list(self.inputs),
                    self.nout, self.value, self.rng_index, self.avals)

    def __repr__(self):
        kind = self.op or ("const" if self.is_const else "var")
        return f"<Node {self.name} {kind} <-{self.inputs}>"


class Graph:
    """The typed op graph one :class:`PassPipeline` run transforms.

    - ``nodes``: execution-ordered node list (ids are list positions)
    - ``inputs``: node ids of the data-input variables, in call order
    - ``params``: ``(node_id, param_name)`` in positional binding order
    - ``outputs``: the real output edges
    - ``state``: ``(param_name, edge)`` running-state write-backs,
      appended after the outputs by the executor
    - ``single``: the block returned one array (not a tuple)
    """

    __slots__ = ("nodes", "inputs", "params", "outputs", "state", "single")

    def __init__(self, nodes=None, inputs=None, params=None, outputs=None,
                 state=None, single=True):
        self.nodes = list(nodes or [])
        self.inputs = list(inputs or [])
        self.params = list(params or [])
        self.outputs = list(outputs or [])
        self.state = list(state or [])
        self.single = single

    # -- structure ---------------------------------------------------------
    def copy(self):
        """Deep-copy: fresh Node objects, same ids/edges.  Passes mutate
        the copy, never their input (the MXT070 purity contract)."""
        g = Graph([n.clone() for n in self.nodes], list(self.inputs),
                  list(self.params), list(self.outputs),
                  [(k, e) for k, e in self.state], self.single)
        return g

    @property
    def n_ops(self):
        return sum(1 for n in self.nodes if n.op is not None)

    def consumer_counts(self):
        """node_id -> number of consuming edges (heads count once each)."""
        counts = {}
        for n in self.nodes:
            for nid, _ in n.inputs:
                counts[nid] = counts.get(nid, 0) + 1
        for nid, _ in self.outputs:
            counts[nid] = counts.get(nid, 0) + 1
        for _, (nid, _) in self.state:
            counts[nid] = counts.get(nid, 0) + 1
        return counts

    def live_ids(self):
        """Ids reachable from the output/state heads, plus every declared
        input/param variable (the executor's signature is positional, so
        unused inputs must survive DCE)."""
        live = set(self.inputs) | {nid for nid, _ in self.params}
        stack = [nid for nid, _ in self.outputs]
        stack += [nid for _, (nid, _) in self.state]
        while stack:
            nid = stack.pop()
            if nid in live:
                continue
            live.add(nid)
            stack.extend(i for i, _ in self.nodes[nid].inputs)
        return live

    def compact(self, keep_ids):
        """New Graph with only ``keep_ids`` nodes (order preserved), edges
        and heads remapped.  Raises if a head or kept edge would dangle."""
        remap = {}
        nodes = []
        for nid, n in enumerate(self.nodes):
            if nid in keep_ids:
                remap[nid] = len(nodes)
                nodes.append(n.clone())
        for n in nodes:
            n.inputs = [(remap[i], idx) for i, idx in n.inputs]
        return Graph(
            nodes, [remap[i] for i in self.inputs],
            [(remap[i], nm) for i, nm in self.params],
            [(remap[i], idx) for i, idx in self.outputs],
            [(nm, (remap[i], idx)) for nm, (i, idx) in self.state],
            self.single)

    def validate(self):
        """Structural invariants: edges point to earlier nodes (execution
        order is a topological order), heads are in range, declared
        input/param ids are variables."""
        for nid, n in enumerate(self.nodes):
            for i, idx in n.inputs:
                if not 0 <= i < nid:
                    raise MXNetError(
                        f"graph node {n.name} (id {nid}) consumes id {i}: "
                        "edges must point to earlier nodes")
                if not 0 <= idx < self.nodes[i].nout:
                    raise MXNetError(
                        f"graph node {n.name} consumes out {idx} of "
                        f"{self.nodes[i].name} (nout {self.nodes[i].nout})")
        heads = list(self.outputs) + [e for _, e in self.state]
        for i, idx in heads:
            if not 0 <= i < len(self.nodes):
                raise MXNetError(f"graph head id {i} out of range")
        for i in self.inputs:
            if not self.nodes[i].is_var:
                raise MXNetError(f"graph input id {i} is not a variable")
        for i, name in self.params:
            if not self.nodes[i].is_var:
                raise MXNetError(f"graph param {name!r} is not a variable")
        return self

    def signature(self):
        """Canonical structural digest — equal graphs (same ops, attrs,
        wiring, heads) hash equal across processes; used by the
        idempotence tests and the CI smoke's cross-process pin."""
        h = hashlib.sha256()
        for n in self.nodes:
            # fused ops carry a process-local counter name; their stable
            # identity is the structural plan digest stamped at fusion
            op_key = ("__fused__", n.attrs["__fused_sig__"]) \
                if "__fused_sig__" in n.attrs else n.op
            h.update(repr((op_key, n.name if n.is_var else None,
                           sorted((k, repr(v)) for k, v in n.attrs.items()
                                  if not k.startswith("__")),
                           n.inputs, n.nout, n.rng_index,
                           None if n.value is None else
                           (n.value.shape, str(n.value.dtype),
                            _np.asarray(n.value).tobytes()))).encode())
        h.update(repr((self.inputs, self.params, self.outputs, self.state,
                       self.single)).encode())
        return h.hexdigest()

    def fused_op_count(self):
        """Nodes produced by the fusion pass (``__fused_plan__`` attr)."""
        return sum(1 for n in self.nodes if "__fused_plan__" in n.attrs)

    # -- symbol interop ----------------------------------------------------
    @classmethod
    def from_symbol(cls, sym, input_names=None):
        """Build from an ``mx.sym`` Symbol.  Variables named in
        ``input_names`` become data inputs; every other variable is
        marked as a parameter (positional order = topo order, which is
        how the subgraph shim and tests bind them)."""
        from ..symbol.symbol import _topo

        input_names = list(input_names or [])
        snodes = _topo(sym._heads)
        nid = {id(n): i for i, n in enumerate(snodes)}
        nodes, inputs, params = [], [], []
        for n in snodes:
            node = Node(n.op, n.name, dict(n.attrs),
                        [(nid[id(i)], idx) for i, idx in n.inputs],
                        n.nout, n.value)
            nodes.append(node)
            if node.is_var:
                if n.name in input_names:
                    inputs.append(nid[id(n)])
                else:
                    params.append((nid[id(n)], n.name))
        outputs = [(nid[id(n)], idx) for n, idx in sym._heads]
        g = cls(nodes, inputs, params, outputs, [], len(outputs) == 1)
        return g.validate()

    def to_symbol(self):
        """Convert back to an ``mx.sym`` Symbol (outputs only — state
        edges are an executor concern, not part of the user graph)."""
        from ..symbol.symbol import Symbol, _Node

        snodes = []
        for n in self.nodes:
            snodes.append(_Node(n.op, n.name, dict(n.attrs),
                                [(snodes[i], idx) for i, idx in n.inputs],
                                n.nout, n.value))
        return Symbol([(snodes[i], idx) for i, idx in self.outputs])
