"""Registered fused ops for the elementwise-chain fusion pass.

Each structurally distinct chain registers ONE op (``_gfused_chainN``)
that replays the captured registry kernels in order — pure, traceable,
differentiable, and AMP-faithful: the replay applies the same per-op
cast wrap ``ndarray.invoke`` would, so a fused chain is numerically the
unfused chain, just one dispatch and one graph node.  Structurally
identical chains share one registration (repeated pipeline runs must
not grow OP_TABLE — same contract as ``subgraph._make_region_op``).
"""
from __future__ import annotations

import functools
import threading

from ..ops.registry import OP_TABLE, register

__all__ = ["register_fused_chain", "fused_plan_summary"]

_LOCK = threading.Lock()
_CACHE = {}          # structural signature -> registered op name
_COUNTER = [0]


def fused_plan_summary(plan):
    """Human-readable chain summary for the node attrs / telemetry."""
    return "+".join(op for op, _, _ in plan)


def plan_digest(plan):
    """Structural digest of a fused plan (ops + attrs + wiring) —
    process-independent, unlike the counter-assigned op name.  Stamped
    on fused nodes (``__fused_sig__``) so ``Graph.signature()`` hashes
    the chain's STRUCTURE, keeping digests stable across processes
    with different fusion histories."""
    import hashlib

    sig = tuple(
        (op, tuple(sorted((k, repr(v)) for k, v in attrs.items())),
         tuple(srcs))
        for op, attrs, srcs in plan)
    return hashlib.sha256(repr(sig).encode()).hexdigest()


def register_fused_chain(plan):
    """Register (or reuse) the op executing ``plan``.

    ``plan``: ordered ``(op_name, attrs_dict, srcs)`` steps where each
    src is ``("ext", k)`` — the fused node's k-th input — or
    ``("step", j)`` — step j's output.  The last step's output is the
    fused op's single output.
    """
    sig = tuple(
        (op, tuple(sorted((k, repr(v)) for k, v in attrs.items())),
         tuple(srcs))
        for op, attrs, srcs in plan)
    with _LOCK:
        cached = _CACHE.get(sig)
        if cached is not None:
            return cached
        _COUNTER[0] += 1
        opname = f"_gfused_chain{_COUNTER[0]}"
    ods = [OP_TABLE[op] for op, _, _ in plan]
    steps = [(od, dict(attrs), tuple(srcs))
             for od, (_, attrs, srcs) in zip(ods, plan)]

    def fused_fn(*ext_vals):
        from ..ndarray.ndarray import _AMP, _call_with_attrs

        wrap = _AMP["wrap"] if _AMP["on"] else None
        vals = []
        for od, attrs, srcs in steps:
            f = functools.partial(_call_with_attrs, od.fn, attrs)
            if wrap is not None:
                f = wrap(od, f)
            vals.append(f(*(ext_vals[k] if kind == "ext" else vals[k]
                            for kind, k in srcs)))
        return vals[-1]

    fused_fn.__name__ = opname
    fused_fn.__doc__ = f"fused elementwise chain: {fused_plan_summary(plan)}"
    register(opname)(fused_fn)
    with _LOCK:
        _CACHE[sig] = opname
    return opname
