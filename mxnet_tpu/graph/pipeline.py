"""Pass manager: registry, selection knobs, ordering, fixed-point.

``PassPipeline`` runs an ordered list of registered passes over a
:class:`Graph`, optionally iterating the sweep to a fixed point
(structure digest stable).  Every pass run is measured — a
``kind="graph_pass"`` compile event with duration and nodes
before/after — so pipeline wins are read off telemetry, not asserted.

Knobs (env.py / README "Graph compiler"):

- ``MXNET_GRAPH_PIPELINE``: master switch (default 1).  Off = every
  consumer (hybridized blocks, TrainStep, serving export) runs the
  raw traced program.
- ``MXNET_GRAPH_PASSES``: comma-separated pass selection.  Plain names
  replace the default list; ``-name`` entries subtract from it.
- ``MXNET_GRAPH_FUSE_CAP``: max ops per fused elementwise chain
  (default 16; < 2 disables fusion).
"""
from __future__ import annotations

import threading
import time
from collections import OrderedDict
from contextlib import contextmanager

from .. import env as _env
from ..base import MXNetError

__all__ = ["graph_pass", "list_passes", "PassPipeline", "default_pipeline",
           "enabled", "override_enabled", "selected_pass_names",
           "DEFAULT_PASSES", "stats_snapshot", "reset_stats",
           "record_fallback"]

PASS_REGISTRY: "OrderedDict[str, object]" = OrderedDict()

# default order: fold + CSE shrink the graph, the AMP pass canonicalizes
# casts (so a second CSE round — via fixed point — merges the hoisted
# ones), fusion collapses the surviving chains, DCE sweeps the husks
DEFAULT_PASSES = ("fold_constants", "eliminate_common_subexpr",
                  "place_amp_casts", "fuse_elemwise_chains",
                  "eliminate_dead_nodes")


def graph_pass(name, default=True):
    """Decorator registering a pure ``Graph -> Graph`` pass under
    ``name``.  Every pass a :class:`PassPipeline` can reach MUST be
    registered (MXT071) — anonymous callables don't ride the pipeline."""

    def _do(fn):
        if name in PASS_REGISTRY and PASS_REGISTRY[name] is not fn:
            raise MXNetError(f"graph pass {name!r} already registered")
        PASS_REGISTRY[name] = fn
        fn.graph_pass_name = name
        fn.graph_pass_default = bool(default)
        return fn

    return _do


def _ensure_builtins():
    from . import passes  # noqa: F401  (import registers the builtins)


def list_passes():
    """Registered pass names, registration order."""
    _ensure_builtins()
    return list(PASS_REGISTRY)


# --------------------------------------------------------------------------
# enable / selection knobs
# --------------------------------------------------------------------------
_OVERRIDE = threading.local()


def enabled():
    """Pipeline master switch: thread-local override (tests/bench A/B)
    over ``MXNET_GRAPH_PIPELINE`` (default on)."""
    ov = getattr(_OVERRIDE, "value", None)
    if ov is not None:
        return ov
    return _env.graph_pipeline()


@contextmanager
def override_enabled(flag):
    """Force the pipeline on/off for this thread (the bench/test A/B
    seam — flipping os.environ mid-process would race other threads)."""
    prev = getattr(_OVERRIDE, "value", None)
    _OVERRIDE.value = bool(flag)
    try:
        yield
    finally:
        _OVERRIDE.value = prev


def selected_pass_names():
    """Resolve ``MXNET_GRAPH_PASSES`` against the default list."""
    _ensure_builtins()
    spec = (_env.graph_passes() or "").strip()
    if not spec:
        return list(DEFAULT_PASSES)
    removed = {p[1:].strip() for p in spec.split(",")
               if p.strip().startswith("-")}
    picked = [p.strip() for p in spec.split(",")
              if p.strip() and not p.strip().startswith("-")]
    names = picked if picked else list(DEFAULT_PASSES)
    names = [n for n in names if n not in removed]
    unknown = [n for n in names if n not in PASS_REGISTRY]
    if unknown:
        raise MXNetError(
            f"MXNET_GRAPH_PASSES names unregistered passes {unknown}; "
            f"registered: {list(PASS_REGISTRY)}")
    return names


# --------------------------------------------------------------------------
# stats (snapshot()'s "graph" section; bench extra.graph reads this too)
# --------------------------------------------------------------------------
_SLOCK = threading.Lock()
_STATS = {
    "pipeline_runs": 0,
    "fallbacks": 0,
    "fused_ops_created": 0,
    "passes": {},       # name -> {runs, nodes_in, nodes_out, seconds}
    "last_run": None,   # [{pass, nodes_before, nodes_after, seconds}]
}


def _record_pass(name, before, after, dt):
    with _SLOCK:
        rec = _STATS["passes"].setdefault(
            name, {"runs": 0, "nodes_in": 0, "nodes_out": 0, "seconds": 0.0})
        rec["runs"] += 1
        rec["nodes_in"] += before
        rec["nodes_out"] += after
        rec["seconds"] += dt


def record_fallback():
    """A consumer tried the graph path and fell back to the imperative
    trace (counted so 'pipeline on' that silently never runs is
    visible in the snapshot)."""
    with _SLOCK:
        _STATS["fallbacks"] += 1


def stats_snapshot():
    with _SLOCK:
        return {
            "enabled": enabled(),
            "pipeline_runs": _STATS["pipeline_runs"],
            "fallbacks": _STATS["fallbacks"],
            "fused_ops_created": _STATS["fused_ops_created"],
            "passes": {k: dict(v) for k, v in _STATS["passes"].items()},
            "last_run": [dict(r) for r in _STATS["last_run"]]
            if _STATS["last_run"] else None,
        }


def reset_stats():
    with _SLOCK:
        _STATS["pipeline_runs"] = 0
        _STATS["fallbacks"] = 0
        _STATS["fused_ops_created"] = 0
        _STATS["passes"].clear()
        _STATS["last_run"] = None


# --------------------------------------------------------------------------
class PassPipeline:
    """An ordered, knob-selectable pass schedule.

    ``passes``: registered pass names (strings).  ``fixed_point=True``
    repeats the sweep until the structure digest stabilizes (bounded by
    ``max_iters``) — fusion after cast-hoisting after CSE converges in
    2 sweeps on real graphs.
    """

    def __init__(self, passes=None, fixed_point=True, max_iters=3):
        _ensure_builtins()
        names = list(passes) if passes is not None else \
            selected_pass_names()
        for n in names:
            if n not in PASS_REGISTRY:
                raise MXNetError(
                    f"unknown graph pass {n!r}; registered: "
                    f"{list(PASS_REGISTRY)}")
        self.pass_names = names
        self.fixed_point = bool(fixed_point)
        self.max_iters = max(1, int(max_iters))

    def run(self, graph):
        """Apply the schedule; returns the optimized graph (input graph
        untouched — each pass is pure)."""
        from .. import telemetry as _telemetry

        out = graph
        run_log = []
        fused_before = graph.fused_op_count()
        sig_before = out.signature() if self.fixed_point else None
        for _ in range(self.max_iters if self.fixed_point else 1):
            for name in self.pass_names:
                fn = PASS_REGISTRY[name]
                before = len(out.nodes)
                t0 = time.perf_counter()
                nxt = fn(out)
                dt = time.perf_counter() - t0
                if nxt is None or nxt is out:
                    raise MXNetError(
                        f"graph pass {name!r} must return a NEW graph "
                        "(pure Graph -> Graph)")
                out = nxt
                after = len(out.nodes)
                _record_pass(name, before, after, dt)
                run_log.append({"pass": name, "nodes_before": before,
                                "nodes_after": after,
                                "seconds": round(dt, 6)})
                _telemetry.compile_event(
                    "graph_pass", name, dt, "pipeline",
                    nodes_before=before, nodes_after=after)
            if not self.fixed_point:
                break
            sig_after = out.signature()
            if sig_after == sig_before:
                break
            sig_before = sig_after   # one hash per sweep, not two
        with _SLOCK:
            _STATS["pipeline_runs"] += 1
            _STATS["fused_ops_created"] += max(
                0, out.fused_op_count() - fused_before)
            _STATS["last_run"] = run_log
        return out

    def run_symbol(self, sym, input_names=None):
        """Symbol-level sugar (the ``subgraph.optimize_for`` shim):
        Symbol -> Graph -> passes -> Symbol."""
        from .ir import Graph

        g = Graph.from_symbol(sym, input_names=input_names)
        return self.run(g).to_symbol()


def default_pipeline():
    """The knob-configured pipeline every consumer uses."""
    return PassPipeline(selected_pass_names())
