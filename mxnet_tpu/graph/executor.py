"""Replay an optimized Graph as a pure jax function.

``make_block_fn(graph)`` returns the cached-op contract function

    fn(param_vals, rng_key, *input_vals) -> tuple(outputs + state_vals)

that ``HybridBlock._call_cached_op`` and ``functionalize`` jit.  The
replay mirrors ``ndarray.invoke`` exactly — same op fns, same attr
filtering, the same AMP cast wrap per op, and RNG keys derived with the
same ``fold_in(base, counter)`` scheme using the counters stamped at
trace time — so a pipeline with no enabled passes produces a jaxpr
numerically identical to the imperative jit trace (the bit-parity
floor every pass builds on).
"""
from __future__ import annotations

import functools

from ..base import MXNetError

__all__ = ["make_block_fn"]


def make_block_fn(graph):
    """Compile-free closure over ``graph``; safe to ``jax.jit``."""
    from ..ops.registry import get_op
    from ..symbol.symbol import _clean_attrs

    steps = []           # (node_id, od, attrs, input_edges, rng_index)
    for nid, node in enumerate(graph.nodes):
        if node.op is None:
            continue
        od = get_op(node.op)     # raises MXNetError for unknown ops
        steps.append((nid, od, _clean_attrs(node.attrs),
                      tuple(node.inputs), node.rng_index))
    param_ids = [nid for nid, _ in graph.params]
    input_ids = list(graph.inputs)
    out_edges = list(graph.outputs) + [e for _, e in graph.state]
    consts = {nid: n.value for nid, n in enumerate(graph.nodes)
              if n.is_const}

    def fn(param_vals, rng_key, *input_vals):
        import jax
        import jax.numpy as jnp

        from ..ndarray.ndarray import _AMP, _call_with_attrs

        if len(param_vals) != len(param_ids) or \
                len(input_vals) != len(input_ids):
            raise MXNetError(
                f"graph executor: expected {len(param_ids)} params + "
                f"{len(input_ids)} inputs, got {len(param_vals)} + "
                f"{len(input_vals)}")
        vals = {}
        for nid, v in zip(param_ids, param_vals):
            vals[(nid, 0)] = v
        for nid, v in zip(input_ids, input_vals):
            vals[(nid, 0)] = v
        for nid, v in consts.items():
            vals[(nid, 0)] = jnp.asarray(v)
        amp_wrap = _AMP["wrap"] if _AMP["on"] else None
        fallback_rng = 0
        for nid, od, attrs, in_edges, rng_index in steps:
            f = functools.partial(_call_with_attrs, od.fn, attrs)
            if amp_wrap is not None:
                f = amp_wrap(od, f)
            args = [vals[e] for e in in_edges]
            if od.needs_rng:
                if rng_index is None:
                    # graphs built without a trace (from_symbol) carry no
                    # stamped counters — number sequentially in node order
                    # (trace_block stamps every rng node, so a graph never
                    # mixes stamped and sequential numbering)
                    fallback_rng += 1
                    rng_index = fallback_rng
                args = [jax.random.fold_in(rng_key, rng_index)] + args
            out = f(*args)
            outs = out if isinstance(out, (tuple, list)) else (out,)
            for i, v in enumerate(outs):
                vals[(nid, i)] = v
        return tuple(vals[e] for e in out_edges)

    return fn
