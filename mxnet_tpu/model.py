"""Legacy mx.model helpers (reference: python/mxnet/model.py).

The FeedForward class predates even Module; what survives in real scripts is
``save_checkpoint``/``load_checkpoint`` and ``BatchEndParam`` — provided here
over the Module implementations.
"""
from .module.module import save_checkpoint, load_checkpoint
from .module.base_module import _BatchEndParam as BatchEndParam

__all__ = ["save_checkpoint", "load_checkpoint", "BatchEndParam"]
