"""mx.np — the NumPy-compatible array namespace.

Reference: ``python/mxnet/numpy/`` (the 1.6+ ``mx.np`` experimental
namespace: NumPy semantics — zero-dim shapes, NumPy broadcasting/naming —
over the same engine; SURVEY.md §9 item 3).

TPU-native: the namespace is *delegated*, not re-implemented.  Every
``mx.np.<fn>`` resolves to ``jax.numpy.<fn>`` at call time (PEP 562 module
getattr) and runs through ``apply_fn``, so results are framework NDArrays
and gradients flow on the autograd tape exactly like registry ops.  This
gives the full jax.numpy surface — einsum, linspace, meshgrid, fancy
indexing helpers — with zero per-op code.
"""
from __future__ import annotations

import numpy as _onp

from ..ndarray.ndarray import NDArray, apply_fn

__all__ = ["ndarray", "array", "empty"]

ndarray = NDArray  # mx.np.ndarray is the same array type (numpy semantics
#                    — zero-dim shapes etc. — are native to the jax backing)


def _jnp():
    import jax.numpy as jnp

    return jnp


def _collect_nd(a, path, paths, nd_args):
    """Record NDArray leaves under ``path`` (recursing through nested
    list/tuple structure, e.g. jnp.block's [[a], [b]]) into parallel
    paths/values lists."""
    if isinstance(a, NDArray):
        paths.append(path)
        nd_args.append(a)
    elif isinstance(a, (list, tuple)):
        for j, e in enumerate(a):
            _collect_nd(e, path + (j,), paths, nd_args)


def array(obj, dtype=None, ctx=None):
    v = obj._get() if isinstance(obj, NDArray) else _onp.asarray(obj)
    out = _jnp().asarray(v, dtype=dtype)
    return NDArray._from_jax(out, ctx)


def empty(shape, dtype="float32", ctx=None):
    return NDArray._from_jax(_jnp().zeros(shape, dtype), ctx)


def _substitute(container, path, v):
    """Write ``v`` at ``path``, copying each nested list/tuple along the
    way so the caller's containers are never mutated."""
    if len(path) == 1:
        container[path[0]] = v
        return
    child = list(container[path[0]])
    container[path[0]] = child
    _substitute(child, path[1:], v)


def _wrap_fn(fn, name):
    def wrapped(*args, **kwargs):
        # collect NDArray operands at top level AND one level inside
        # list/tuple arguments — sequence-taking jax.numpy APIs
        # (concatenate, stack, vstack, block) receive arrays in a list and
        # must still route through apply_fn so autograd sees them
        paths, nd_args = [], []
        for i, a in enumerate(args):
            _collect_nd(a, ("a", i), paths, nd_args)
        for k, v in kwargs.items():
            _collect_nd(v, ("k", k), paths, nd_args)

        def pure(*vals):
            full = [list(a) if isinstance(a, (list, tuple)) else a
                    for a in args]
            kw = {k: list(v) if isinstance(v, (list, tuple)) else v
                  for k, v in kwargs.items()}
            for path, v in zip(paths, vals):
                _substitute(full if path[0] == "a" else kw, path[1:], v)
            return fn(*full, **kw)

        if nd_args:
            return apply_fn(pure, nd_args, name=f"np.{name}")
        out = fn(*args, **kwargs)
        if hasattr(out, "shape") and hasattr(out, "dtype"):
            return NDArray._from_jax(_jnp().asarray(out), None)
        if isinstance(out, (tuple, list)):
            return type(out)(
                NDArray._from_jax(o, None)
                if hasattr(o, "shape") and hasattr(o, "dtype") else o
                for o in out)
        return out

    wrapped.__name__ = name
    wrapped.__qualname__ = name
    wrapped.__doc__ = fn.__doc__
    return wrapped


_CACHE = {}


def __getattr__(name):
    if name.startswith("_"):
        raise AttributeError(name)
    if name in _CACHE:
        return _CACHE[name]
    jnp = _jnp()
    target = getattr(jnp, name, None)
    if target is None:
        raise AttributeError(f"mx.np has no attribute {name!r} "
                             "(not in jax.numpy)")
    if callable(target) and not isinstance(target, type):
        out = _wrap_fn(target, name)
    else:
        out = target  # dtypes (np.float32), constants (np.pi, np.inf)
    _CACHE[name] = out
    return out
