"""RecordIO: packed binary record format + image record pack/unpack.

Reference: ``3rdparty/dmlc-core/include/dmlc/recordio.h`` (magic + escaping)
and ``python/mxnet/recordio.py`` (MXRecordIO, MXIndexedRecordIO, IRHeader,
pack/unpack/pack_img/unpack_img — SURVEY.md §3.4).

Format (compatible with dmlc recordio): each record is
    uint32 kMagic = 0xced7230a
    uint32 lrecord  (upper 3 bits: continue-flag, lower 29: length)
    data   (padded to 4-byte boundary)
The magic is escaped inside payloads by the continue-flag chunking; this
writer uses single-chunk records (cflag=0), which the reference reader
accepts.
"""
from __future__ import annotations

import os
import struct
from collections import namedtuple

import numpy as _np

from .base import MXNetError

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IRHeader", "pack", "unpack",
           "pack_img", "unpack_img"]

_kMagic = 0xCED7230A

IRHeader = namedtuple("IRHeader", ["flag", "label", "id", "id2"])
_IR_FORMAT = "IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


class MXRecordIO:
    """Sequential RecordIO reader/writer (reference: dmlc::RecordIOWriter)."""

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        self.record = None
        self.open()

    def open(self):
        if self.flag == "w":
            self.record = open(self.uri, "wb")
            self.writable = True
        elif self.flag == "r":
            self.record = open(self.uri, "rb")
            self.writable = False
        else:
            raise MXNetError(f"invalid flag {self.flag}")

    def close(self):
        if self.record is not None:
            self.record.close()
            self.record = None

    def reset(self):
        self.close()
        self.open()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def write(self, buf):
        if not self.writable:
            raise MXNetError("not opened for writing")
        self.record.write(struct.pack("<II", _kMagic, len(buf)))
        self.record.write(buf)
        pad = (4 - len(buf) % 4) % 4
        if pad:
            self.record.write(b"\x00" * pad)

    def read(self):
        if self.writable:
            raise MXNetError("not opened for reading")
        head = self.record.read(8)
        if len(head) < 8:
            return None
        magic, lrec = struct.unpack("<II", head)
        if magic != _kMagic:
            raise MXNetError("invalid record magic")
        length = lrec & ((1 << 29) - 1)
        data = self.record.read(length)
        pad = (4 - length % 4) % 4
        if pad:
            self.record.read(pad)
        return data

    def tell(self):
        return self.record.tell()


class MXIndexedRecordIO(MXRecordIO):
    """RecordIO with an index file for random access (reference:
    MXIndexedRecordIO over .idx tsv)."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        super().__init__(uri, flag)
        if flag == "r" and os.path.isfile(idx_path):
            with open(idx_path) as f:
                for line in f:
                    parts = line.strip().split("\t")
                    if len(parts) != 2:
                        continue
                    key = key_type(parts[0])
                    self.idx[key] = int(parts[1])
                    self.keys.append(key)
        elif flag == "w":
            self.fidx = open(idx_path, "w")

    def close(self):
        super().close()
        if getattr(self, "fidx", None) is not None:
            self.fidx.close()
            self.fidx = None

    def seek(self, idx):
        self.record.seek(self.idx[idx])

    def read_idx(self, idx):
        self.seek(idx)
        return self.read()

    def write_idx(self, idx, buf):
        key = self.key_type(idx)
        pos = self.tell()
        self.write(buf)
        self.fidx.write(f"{key}\t{pos}\n")
        self.idx[key] = pos
        self.keys.append(key)


def pack(header, s):
    """Pack an IRHeader + payload into a record payload."""
    header = IRHeader(*header)
    if isinstance(header.label, (int, float)):
        hdr = struct.pack(_IR_FORMAT, 0, float(header.label), header.id,
                          header.id2)
        return hdr + s
    label = _np.asarray(header.label, dtype=_np.float32)
    hdr = struct.pack(_IR_FORMAT, len(label), 0.0, header.id, header.id2)
    return hdr + label.tobytes() + s


def unpack(s):
    flag, label, id_, id2 = struct.unpack(_IR_FORMAT, s[:_IR_SIZE])
    s = s[_IR_SIZE:]
    if flag > 0:
        arr = _np.frombuffer(s[:flag * 4], dtype=_np.float32)
        s = s[flag * 4:]
        header = IRHeader(flag, arr, id_, id2)
    else:
        header = IRHeader(flag, label, id_, id2)
    return header, s


def pack_img(header, img, quality=95, img_fmt=".npy"):
    """Pack an image. Offline environment: no OpenCV/JPEG codec is baked in,
    so the default encoding is raw .npy (shape+dtype preserved); .jpg/.png
    are attempted via PIL if available."""
    if img_fmt in (".jpg", ".jpeg", ".png"):
        import io as _io

        try:
            from PIL import Image
        except ImportError as e:
            raise MXNetError("JPEG/PNG encoding needs PIL; use img_fmt='.npy'") from e
        buf = _io.BytesIO()
        Image.fromarray(img).save(buf, format="JPEG" if "j" in img_fmt else "PNG",
                                  quality=quality)
        payload = b"IMG0" + buf.getvalue()
    else:
        import io as _io

        buf = _io.BytesIO()
        _np.save(buf, _np.asarray(img), allow_pickle=False)
        payload = b"NPY0" + buf.getvalue()
    return pack(header, payload)


def unpack_img(s, iscolor=-1, flag=1):
    header, payload = unpack(s)
    tag, body = payload[:4], payload[4:]
    import io as _io

    if tag == b"NPY0":
        img = _np.load(_io.BytesIO(body), allow_pickle=False)
    elif tag == b"IMG0":
        try:
            from PIL import Image
        except ImportError as e:
            raise MXNetError("JPEG/PNG decoding needs PIL") from e
        img = _np.asarray(Image.open(_io.BytesIO(body)))
    else:
        # raw jpeg bytes from a reference-written .rec
        try:
            from PIL import Image
        except ImportError as e:
            raise MXNetError("decoding reference .rec needs PIL") from e
        img = _np.asarray(Image.open(_io.BytesIO(payload)))
    return header, img
