"""Base utilities: errors, registry, dtype tables.

TPU-native rebuild of the role played by ``python/mxnet/base.py`` and
``3rdparty/dmlc-core`` (logging/CHECK -> dmlc::Error -> MXNetError) in the
reference (see SURVEY.md §3.5, §3.8).  There is no C ABI here: the "engine"
is the JAX/XLA runtime, so errors are ordinary Python exceptions raised
either at call time (shape/type inference) or at sync points (async XLA
errors surfacing in ``wait_to_read``/``asnumpy`` — same contract as the
reference's exception-on-var propagation, SURVEY.md §3.1).
"""
from __future__ import annotations

import numpy as _np

__all__ = ["MXNetError", "Registry", "string_types", "numeric_types", "integer_types"]


class MXNetError(RuntimeError):
    """Error raised by the framework (reference: MXGetLastError TLS,
    src/c_api/c_api_error.cc)."""


string_types = (str,)
numeric_types = (float, int, _np.generic)
integer_types = (int, _np.integer)

# dtype name <-> numpy mapping (reference: mshadow dtype enum via
# python/mxnet/base.py _DTYPE_NP_TO_MX / _DTYPE_MX_TO_NP)
_DTYPE_ALIASES = {
    "float32": _np.float32,
    "float64": _np.float64,
    "float16": _np.float16,
    "bfloat16": "bfloat16",  # resolved lazily via ml_dtypes/jnp
    "uint8": _np.uint8,
    "int8": _np.int8,
    "int32": _np.int32,
    "int64": _np.int64,
    "bool": _np.bool_,
}


class Registry:
    """Minimal name->object registry with decorator support.

    Reference: ``dmlc::Registry`` (3rdparty/dmlc-core/include/dmlc/registry.h)
    which backs the op/iterator/storage factories.  The TPU build keeps the
    registry-driven, self-describing surface (SURVEY.md §6.6) in pure Python.
    """

    def __init__(self, name):
        self.name = name
        self._fmap = {}

    def register(self, obj=None, name=None, aliases=()):
        def _do(o):
            key = name or getattr(o, "__name__", None)
            if key is None:
                raise ValueError("cannot infer registry key")
            self._fmap[key.lower()] = o
            for a in aliases:
                self._fmap[a.lower()] = o
            return o

        if obj is None:
            return _do
        return _do(obj)

    def create(self, key, *args, **kwargs):
        k = key.lower()
        if k not in self._fmap:
            raise MXNetError(
                f"{self.name} registry: unknown entry {key!r}. "
                f"Known: {sorted(self._fmap)}"
            )
        return self._fmap[k](*args, **kwargs)

    def get(self, key):
        return self._fmap.get(key.lower())

    def __contains__(self, key):
        return key.lower() in self._fmap

    def keys(self):
        return sorted(self._fmap)
