"""Llama training under pipeline parallelism (TrainStep(pipeline=...)).

Demonstrates the 4D parallelism surface on a virtual CPU mesh — the same
code runs unchanged on a TPU pod where the mesh axes map onto real chips:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python llama_pipeline.py --cpu --steps 4 --schedule 1f1b

The trunk (decoder layers) streams over pp as GPipe or hand-scheduled
1F1B microbatches; embed and lm_head run outside the pipe; the batch
shards over dp.  net.pipeline_decompose does the model surgery.
"""
import argparse

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=4)
    ap.add_argument("--dp", type=int, default=4)
    ap.add_argument("--pp", type=int, default=2)
    ap.add_argument("--microbatches", type=int, default=2)
    ap.add_argument("--schedule", default="1f1b",
                    choices=["gpipe", "1f1b"])
    ap.add_argument("--remat-stage", action="store_true")
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()
    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")

    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo.language import llama
    from mxnet_tpu.parallel.data_parallel import TrainStep

    n = args.dp * args.pp
    devices = jax.devices()
    if len(devices) < n:
        raise SystemExit(
            f"need {n} devices; set XLA_FLAGS="
            f"--xla_force_host_platform_device_count={n}")
    mesh = Mesh(np.array(devices[:n]).reshape(args.dp, args.pp),
                ("dp", "pp"))

    cfg = llama.LlamaConfig(vocab_size=256, hidden_size=64, num_layers=4,
                            num_heads=4, num_kv_heads=2,
                            intermediate_size=128, max_seq_len=64)
    net = llama.LlamaForCausalLM(cfg)
    net.initialize(ctx=mx.cpu())
    net(mx.nd.zeros((1, 16), dtype="int32"))

    def lm_loss(logits, labels):
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.take_along_axis(logp, labels[..., None], axis=-1)

    step = TrainStep(net, lm_loss, optimizer="adam",
                     optimizer_params={"learning_rate": 1e-3},
                     mesh=mesh, batch_axes=("dp",),
                     pipeline={"num_microbatches": args.microbatches,
                               "schedule": args.schedule,
                               "remat_stage": args.remat_stage})
    rs = np.random.RandomState(0)
    B = 2 * args.dp * args.microbatches
    for it in range(args.steps):
        ids = rs.randint(0, 256, (B, 32)).astype("int32")
        lbl = np.roll(ids, -1, axis=1).astype("int32")
        loss = float(np.asarray(step(ids, lbl)))
        print(f"step {it}: loss {loss:.4f} "
              f"(pp={args.pp}, {args.schedule})")


if __name__ == "__main__":
    main()
