"""Bucketed RNN language model (reference: example/rnn/bucketing/
lstm_bucketing.py — BucketSentenceIter + FusedRNNCell + BucketingModule)."""
import argparse

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-hidden", type=int, default=64)
    ap.add_argument("--num-layers", type=int, default=1)
    ap.add_argument("--vocab", type=int, default=64)
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()
    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")

    import mxnet_tpu as mx

    rs = np.random.RandomState(0)
    sents = [list(rs.randint(1, args.vocab, rs.randint(4, 17)))
             for _ in range(256)]
    it = mx.rnn.BucketSentenceIter(sents, args.batch_size,
                                   buckets=[8, 16], invalid_label=0)

    def sym_gen(seq_len):
        cell = mx.rnn.FusedRNNCell(args.num_hidden,
                                   num_layers=args.num_layers,
                                   mode="lstm", prefix="lstm_")
        data = mx.sym.var("data")
        label = mx.sym.var("softmax_label")
        emb = mx.sym.Embedding(data, input_dim=args.vocab, output_dim=32,
                               name="embed")
        outputs, _ = cell.unroll(seq_len, emb, merge_outputs=True)
        pred = mx.sym.FullyConnected(
            mx.sym.reshape(outputs, shape=(-1, args.num_hidden)),
            num_hidden=args.vocab, name="pred")
        label = mx.sym.reshape(label, shape=(-1,))
        return mx.sym.SoftmaxOutput(pred, label, name="softmax"), \
            ("data",), ("softmax_label",)

    mod = mx.mod.BucketingModule(sym_gen,
                                 default_bucket_key=it.default_bucket_key)
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(optimizer="adam",
                       optimizer_params=(("learning_rate", 3e-3),))
    metric = mx.metric.Perplexity(ignore_label=0)
    for epoch in range(args.epochs):
        it.reset()
        metric.reset()
        for batch in it:
            mod.forward(batch, is_train=True)
            mod.update_metric(metric, batch.label)
            mod.backward()
            mod.update()
        print(f"epoch {epoch}: {metric.get()[0]} {metric.get()[1]:.2f}")


if __name__ == "__main__":
    main()
