"""Factorization machine on sparse (csr) features — BASELINE config #4's
workload shape (reference: example/sparse/factorization_machine/).

The csr x dense products run through the framework's differentiable SpMM
(segment-sum over nonzeros, gradients to the dense factors), so the model
trains without ever densifying the feature matrix.

CPU smoke: python factorization_machine.py --cpu --steps 60
"""
import argparse

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-features", type=int, default=1000)
    ap.add_argument("--rank", type=int, default=8)
    ap.add_argument("--batch-size", type=int, default=128)
    ap.add_argument("--steps", type=int, default=200)
    ap.add_argument("--lr", type=float, default=1.0)
    ap.add_argument("--density", type=float, default=0.02)
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()
    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, nd

    rs = np.random.RandomState(0)
    D, K, B = args.num_features, args.rank, args.batch_size

    # ground-truth sparse logistic model for synthetic clicks
    true_w = rs.randn(D) * (rs.rand(D) < 0.1)

    def sample_batch():
        dense = (rs.rand(B, D) < args.density) * rs.rand(B, D).astype("f")
        y = (dense @ true_w + 0.1 * rs.randn(B) > 0).astype("f")
        return nd.array(dense.astype("f")).tostype("csr"), nd.array(y)

    w0 = nd.zeros((1,))
    w = nd.zeros((D, 1))
    V = nd.array((rs.randn(D, K) * 0.01).astype("f"))
    for p in (w0, w, V):
        p.attach_grad()

    losses = []
    for step in range(args.steps):
        x_csr, y = sample_batch()
        x_sq = nd.array(np.square(x_csr.asnumpy() if hasattr(x_csr, "asnumpy")
                                  else x_csr)).tostype("csr")
        with autograd.record():
            linear = nd.dot(x_csr, w)[:, 0]                     # SpMM
            xv = nd.dot(x_csr, V)                               # (B, K)
            x2v2 = nd.dot(x_sq, V * V)                          # (B, K)
            pairwise = 0.5 * (xv * xv - x2v2).sum(axis=1)
            logit = w0 + linear + pairwise
            # logistic loss
            loss = (nd.log(1 + nd.exp(-nd.abs(logit)))
                    + nd.relu(logit) - logit * y).mean()
        loss.backward()
        for p in (w0, w, V):
            p -= args.lr * p.grad
        losses.append(float(loss.asnumpy()))
        if step % 20 == 0:
            print(f"step {step}: logloss {losses[-1]:.4f}")
    print(f"final logloss {losses[-1]:.4f} (start {losses[0]:.4f})")
    assert losses[-1] < losses[0]


if __name__ == "__main__":
    main()
