"""Factorization machine on sparse (csr) features — BASELINE config #4's
workload shape (reference: example/sparse/factorization_machine/).

The csr x dense products run through the framework's differentiable SpMM
(segment-sum over nonzeros, gradients to the dense factors), so the model
trains without ever densifying the feature matrix.

Two training modes:

- local (default): parameters are NDArrays, manual SGD on autograd grads.
- ``--kvstore``: parameters live SERVER-SIDE in a kvstore (host-resident
  row-sparse tables — reference: kvstore_dist_server.h
  DataHandleRowSparse).  Each step ``row_sparse_pull``s only the rows the
  batch touches, pushes row-sparse gradients back, and the server applies
  the lazy optimizer update to those rows only — bytes moved per step
  scale with the batch's feature support, not the table size.

CPU smoke: python factorization_machine.py --cpu --steps 60 [--kvstore]
"""
import argparse

import numpy as np


def run(num_features=1000, rank=8, batch_size=128, steps=200, lr=1.0,
        density=0.02, use_kvstore=False, log_every=20, seed=0):
    """Train; returns the per-step loss list (both modes follow the same
    random stream, so trajectories are comparable across modes)."""
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, nd

    rs = np.random.RandomState(seed)
    D, K, B = num_features, rank, batch_size

    # ground-truth sparse logistic model for synthetic clicks
    true_w = rs.randn(D) * (rs.rand(D) < 0.1)

    def sample_batch():
        dense = (rs.rand(B, D) < density) * rs.rand(B, D).astype("f")
        y = (dense @ true_w + 0.1 * rs.randn(B) > 0).astype("f")
        return dense.astype("f"), y

    w0 = nd.zeros((1,))
    w0.attach_grad()
    w_init = np.zeros((D, 1), "f")
    V_init = (rs.randn(D, K) * 0.01).astype("f")

    if use_kvstore:
        from mxnet_tpu import optimizer as opt

        kv = mx.kv.create("local")
        kv.init("w", nd.array(w_init))
        kv.init("V", nd.array(V_init))
        kv.set_optimizer(opt.create("sgd", learning_rate=lr, wd=0.0,
                                    rescale_grad=1.0))
    else:
        w = nd.array(w_init)
        V = nd.array(V_init)
        for p in (w, V):
            p.attach_grad()

    def fm_loss(x_csr, x_sq_csr, wv, Vv, y):
        linear = nd.dot(x_csr, wv)[:, 0]                    # SpMM
        xv = nd.dot(x_csr, Vv)                              # (B, K)
        x2v2 = nd.dot(x_sq_csr, Vv * Vv)                    # (B, K)
        pairwise = 0.5 * (xv * xv - x2v2).sum(axis=1)
        logit = w0 + linear + pairwise
        # logistic loss
        return (nd.log(1 + nd.exp(-nd.abs(logit)))
                + nd.relu(logit) - logit * y).mean()

    losses = []
    for step in range(steps):
        dense, y_np = sample_batch()
        y = nd.array(y_np)
        if use_kvstore:
            # only the batch's feature support moves: pull those rows,
            # train on the column-compressed batch, push rsp grads back
            touched = np.nonzero(dense.any(axis=0))[0].astype("i")
            T = len(touched)
            xc = dense[:, touched]
            x_csr = nd.array(xc).tostype("csr")
            x_sq = nd.array(np.square(xc)).tostype("csr")
            w_rows = nd.zeros((T, 1))
            V_rows = nd.zeros((T, K))
            kv.row_sparse_pull("w", out=w_rows, row_ids=nd.array(touched))
            kv.row_sparse_pull("V", out=V_rows, row_ids=nd.array(touched))
            w_rows.attach_grad()
            V_rows.attach_grad()
            with autograd.record():
                loss = fm_loss(x_csr, x_sq, w_rows, V_rows, y)
            loss.backward()
            from mxnet_tpu.ndarray.sparse import row_sparse_array

            kv.push("w", row_sparse_array(
                (w_rows.grad.asnumpy(), touched), shape=(D, 1)))
            kv.push("V", row_sparse_array(
                (V_rows.grad.asnumpy(), touched), shape=(D, K)))
        else:
            x_csr = nd.array(dense).tostype("csr")
            x_sq = nd.array(np.square(dense)).tostype("csr")
            with autograd.record():
                loss = fm_loss(x_csr, x_sq, w, V, y)
            loss.backward()
            for p in (w, V):
                p -= lr * p.grad
        w0 -= lr * w0.grad
        losses.append(float(loss.asnumpy()))
        if log_every and step % log_every == 0:
            print(f"step {step}: logloss {losses[-1]:.4f}")
    print(f"final logloss {losses[-1]:.4f} (start {losses[0]:.4f})")
    return losses


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-features", type=int, default=1000)
    ap.add_argument("--rank", type=int, default=8)
    ap.add_argument("--batch-size", type=int, default=128)
    ap.add_argument("--steps", type=int, default=200)
    ap.add_argument("--lr", type=float, default=1.0)
    ap.add_argument("--density", type=float, default=0.02)
    ap.add_argument("--kvstore", action="store_true")
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()
    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")

    losses = run(num_features=args.num_features, rank=args.rank,
                 batch_size=args.batch_size, steps=args.steps, lr=args.lr,
                 density=args.density, use_kvstore=args.kvstore)
    assert losses[-1] < losses[0]


if __name__ == "__main__":
    main()
