"""Image-classification training example (reference:
example/image-classification/train_imagenet.py shape, runnable offline on
synthetic data).

CPU smoke:   python train_synthetic.py --epochs 1 --batch-size 8 --size 32
TPU:         python train_synthetic.py --layout NHWC --dtype bfloat16
"""
import argparse
import time

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--network", default="resnet18_v1")
    ap.add_argument("--classes", type=int, default=10)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--size", type=int, default=64)
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--steps-per-epoch", type=int, default=10)
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--layout", default="NCHW", choices=["NCHW", "NHWC"])
    ap.add_argument("--dtype", default=None, choices=[None, "bfloat16",
                                                      "float16"])
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()
    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")

    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu.parallel.data_parallel import TrainStep

    net = vision.get_model(args.network, classes=args.classes,
                           layout=args.layout)
    net.initialize(mx.init.Xavier(), ctx=mx.current_context())
    shape = ((1, args.size, args.size, 3) if args.layout == "NHWC"
             else (1, 3, args.size, args.size))
    net(mx.nd.zeros(shape))

    def loss_fn(logits, labels):
        import jax
        import jax.numpy as jnp

        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.take_along_axis(logp, labels[:, None], axis=-1)

    step = TrainStep(net, loss_fn, optimizer="sgd",
                     optimizer_params={"learning_rate": args.lr,
                                       "momentum": 0.9},
                     train_mode=True, dtype=args.dtype)
    rs = np.random.RandomState(0)
    bshape = (args.batch_size,) + shape[1:]
    for epoch in range(args.epochs):
        t0 = time.time()
        loss = None
        for _ in range(args.steps_per_epoch):
            x = rs.uniform(-1, 1, bshape).astype("float32")
            y = rs.randint(0, args.classes,
                           (args.batch_size,)).astype("int32")
            loss = step(x, y)
        lv = float(np.asarray(loss))
        dt = time.time() - t0
        ips = args.batch_size * args.steps_per_epoch / dt
        print(f"epoch {epoch}: loss {lv:.4f}  {ips:.1f} img/s")
    step.write_back()
    net.export("model", 0, mx.nd.zeros(shape))
    print("exported model-symbol.json / model-0000.params")


if __name__ == "__main__":
    main()
