"""MXT080: live-resharding transfer-plan discipline.

``parallel/resharding.py``'s ``apply_transfer`` moves sharded state
between meshes through device placement (and, multi-process, through
host-gather collectives): like any collective, every SPMD peer must
reach it — or none may.  Two shapes violate that:

- **rank-conditional execution** — ``apply_transfer`` reached under a
  branch derived from rank (``jax.process_index()``, launcher-rank env
  vars, or a local assigned from one, including guard-style early
  returns): the peers never enter the transfer and the mesh deadlocks.
  Same taint machinery as MXT001.
- **computed-but-dangling plans** — a ``compute_transfer_plan`` /
  ``compute_flat_transfer_plan`` result that is neither handed to
  ``apply_transfer`` nor explicitly ``.discard()``-ed in the same
  function: the undeclared intent is exactly how a later edit ends up
  applying it on some ranks only.  Every consumer must *execute or
  explicitly discard* the plan — both visible, both uniform.

Digest-only uses (the CI determinism check) call
``TransferPlan.discard()`` to state their intent.
"""
from __future__ import annotations

import ast

from ..astutil import call_name, names_in, terminates
from ..core import Finding, Pass, register
from .collectives import _classify, _rank_locals

_COMPUTE = {"compute_transfer_plan", "compute_flat_transfer_plan"}
_APPLY = {"apply_transfer"}
_DISCARD = {"discard"}


def _tail(call):
    name = call_name(call)
    return (name or "").rsplit(".", 1)[-1]


def _walk_same_scope(node):
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        for child in ast.iter_child_nodes(n):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            stack.append(child)


@register
class ReshardingTransfer(Pass):
    name = "resharding-transfer"
    codes = {
        "MXT080": "transfer plan applied rank-conditionally or "
                  "computed but neither executed nor discarded",
    }

    def run(self, ctx, mod):
        findings = []

        def emit(node, msg, hint, key):
            findings.append(Finding(
                code="MXT080", path=mod.relpath, line=node.lineno,
                message=msg, hint=hint, scope=mod.qualname(node),
                key=key, col=getattr(node, "col_offset", 0)))

        scopes = [(mod.tree, set())]
        for fn in ast.walk(mod.tree):
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scopes.append((fn, _rank_locals(fn)))
        for scope, rank_locals in scopes:
            self._scan_scope(scope, rank_locals, emit)
        return findings

    # -- rank-conditional apply_transfer (MXT001-style walk) ---------------
    def _scan_scope(self, scope, rank_locals, emit):
        body = scope.body if hasattr(scope, "body") else []
        self._scan_block(body, 0, rank_locals, emit)
        self._scan_dangling(scope, emit)

    def _scan_block(self, stmts, rank_depth, rank_locals, emit):
        guard = rank_depth
        for stmt in stmts:
            self._scan_stmt(stmt, guard, rank_locals, emit)
            if isinstance(stmt, ast.If) and \
                    _classify(stmt.test, rank_locals) == "rank" and \
                    terminates(stmt.body) and not stmt.orelse:
                guard += 1

    def _scan_stmt(self, stmt, rank_depth, rank_locals, emit):
        if isinstance(stmt, ast.If):
            arm = rank_depth + (1 if _classify(stmt.test, rank_locals)
                                == "rank" else 0)
            self._scan_block(stmt.body, arm, rank_locals, emit)
            self._scan_block(stmt.orelse, arm, rank_locals, emit)
            return
        if isinstance(stmt, ast.Try):
            for blk in (stmt.body, stmt.orelse, stmt.finalbody):
                self._scan_block(blk, rank_depth, rank_locals, emit)
            for h in stmt.handlers:
                self._scan_block(h.body, rank_depth, rank_locals, emit)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
            self._scan_block(stmt.body, rank_depth, rank_locals, emit)
            self._scan_block(stmt.orelse, rank_depth, rank_locals, emit)
            return
        if isinstance(stmt, ast.With):
            self._scan_block(stmt.body, rank_depth, rank_locals, emit)
            return
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return   # nested scopes scanned as their own functions
        for sub in _walk_same_scope(stmt):
            if isinstance(sub, ast.Call) and _tail(sub) in _APPLY \
                    and rank_depth > 0:
                emit(sub,
                     "apply_transfer reached under a rank-conditional "
                     "branch",
                     "every SPMD peer must execute the transfer or "
                     "none may — a rank-conditional apply deadlocks "
                     "the mesh exactly like a rank-conditional "
                     "collective (MXT001); hoist it above the rank "
                     "branch", key="rank-cond:apply_transfer")

    # -- computed-but-dangling plans ---------------------------------------
    def _scan_dangling(self, scope, emit):
        computed = {}       # local name -> assign node
        consumed = set()
        for sub in _walk_same_scope(scope):
            if isinstance(sub, ast.Assign) and \
                    isinstance(sub.value, ast.Call) and \
                    _tail(sub.value) in _COMPUTE:
                for t in sub.targets:
                    if isinstance(t, ast.Name):
                        computed[t.id] = sub
            elif isinstance(sub, ast.Call):
                tail = _tail(sub)
                operands = list(sub.args) + \
                    [kw.value for kw in sub.keywords]
                if tail in _APPLY:
                    for arg in operands:
                        for n in names_in(arg):
                            consumed.add(n)
                elif tail in _DISCARD and \
                        isinstance(sub.func, ast.Attribute) and \
                        isinstance(sub.func.value, ast.Name):
                    consumed.add(sub.func.value.id)
                elif tail not in _COMPUTE:
                    # a plan escaping into ANY other call (returned via
                    # helper, stored, serialized for a peer) counts as
                    # consumed — this pass polices forgotten plans, not
                    # data flow
                    for arg in operands:
                        if isinstance(arg, ast.Name):
                            consumed.add(arg.id)
            elif isinstance(sub, ast.Return) and sub.value is not None:
                for n in names_in(sub.value):
                    consumed.add(n)
            elif isinstance(sub, ast.Attribute) and \
                    isinstance(sub.value, ast.Name) and \
                    sub.attr in ("entries", "to_json", "total_bytes"):
                # reading the plan's data (serialize-for-peer idioms)
                consumed.add(sub.value.id)
        for name, node in computed.items():
            if name in consumed:
                continue
            emit(node,
                 f"transfer plan {name!r} is computed but neither "
                 f"applied nor explicitly discarded in this scope",
                 "every compute_transfer_plan consumer must "
                 "apply_transfer the plan or call plan.discard() — "
                 "both at uniform SPMD level — so a later edit can "
                 "never end up applying it on some ranks only",
                 key=f"dangling-plan:{name}")
