"""MXT110: fleet dispatch discipline — one funnel, always a deadline.

The fleet router's reliability story (ISSUE 17) hangs on a single
choke point: every router→replica send flows through the transport
funnel (``fleet/transport.py`` — ``post_json`` / ``get_json`` /
``call_local``), where the ``router.dispatch`` / ``router.health_probe``
fault seams are armed, the absolute deadline bounds the socket
timeout, and transient failures ride the shared retry budget.  A raw
HTTP call elsewhere in ``fleet/`` silently bypasses chaos coverage,
deadlines, AND the circuit-breaker's failure accounting; a funnel call
without a ``deadline`` wedges a dispatcher thread on a dead replica
forever.  This pass keeps both halves closed as the package grows:

- **Raw transport outside the funnel**: importing or calling
  ``http.client`` / ``socket`` / ``urllib`` / ``requests`` machinery
  anywhere in ``mxnet_tpu/serving/fleet/`` except ``transport.py``
  (whose ``_http_round_trip`` is the one sanctioned raw-HTTP site).
- **Funnel call without a deadline**: a ``post_json`` / ``get_json`` /
  ``call_local`` call site with no explicit ``deadline=`` keyword.
  Splatted ``**kwargs`` do not count — the deadline must be visible at
  the call site, same spirit as MXT040's literal-seam rule.
- **jax in the router plane**: any ``import jax`` under ``fleet/``.
  The router does zero device work by design — a jax import is how
  "zero" quietly becomes "some" (device init, tracer state, a second
  process fighting the replicas for the TPU).

Waive a deliberate exception inline with a reason:
``# mxtpu: noqa[MXT110] <why this site is outside the contract>``.
"""
from __future__ import annotations

import ast

from ..astutil import call_name
from ..core import Finding, Pass, register

_FLEET_PREFIX = "mxnet_tpu/serving/fleet/"
_FUNNEL_FILE = _FLEET_PREFIX + "transport.py"
_FUNNEL_CALLS = {"post_json", "get_json", "call_local"}

# module roots whose presence in fleet/ means raw-wire traffic
_RAW_ROOTS = {"socket", "http", "urllib", "urllib2", "urllib3",
              "requests", "httplib"}
# call-name fragments that are raw-wire even via indirect aliasing
_RAW_CALL_TAILS = {"HTTPConnection", "HTTPSConnection", "urlopen",
                   "create_connection"}


def _root(name):
    return (name or "").split(".", 1)[0]


@register
class FleetDiscipline(Pass):
    name = "fleet-discipline"
    codes = {"MXT110": "fleet dispatch outside the deadline-carrying "
                       "transport funnel"}

    def run(self, ctx, mod):
        if not mod.relpath.startswith(_FLEET_PREFIX):
            return []
        is_funnel = mod.relpath == _FUNNEL_FILE
        findings = []
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                findings.extend(self._check_import(mod, node, is_funnel))
            elif isinstance(node, ast.Call):
                findings.extend(self._check_call(mod, node, is_funnel))
        return findings

    def _check_import(self, mod, node, is_funnel):
        roots = []
        if isinstance(node, ast.Import):
            roots = [a.name for a in node.names]
        elif node.module and node.level == 0:
            roots = [node.module]
        out = []
        for name in roots:
            root = _root(name)
            if root == "jax":
                out.append(Finding(
                    code="MXT110", path=mod.relpath, line=node.lineno,
                    message=f"import {name}: jax in the fleet router "
                            "plane (the router does zero device work)",
                    hint="keep device work on the replicas; the router "
                         "only moves JSON and reads health records",
                    scope=mod.qualname(node), key="fleet-jax-import",
                    col=node.col_offset))
            elif root in _RAW_ROOTS and not is_funnel:
                out.append(Finding(
                    code="MXT110", path=mod.relpath, line=node.lineno,
                    message=f"import {name}: raw transport outside the "
                            "fleet funnel (transport.py)",
                    hint="send through transport.post_json/get_json/"
                         "call_local — they arm the router.dispatch/"
                         "health_probe seams, bound the socket timeout "
                         "by the request deadline, and feed the circuit "
                         "breaker's failure accounting",
                    scope=mod.qualname(node), key="fleet-raw-transport",
                    col=node.col_offset))
        return out

    def _check_call(self, mod, node, is_funnel):
        name = call_name(node)
        if name is None:
            return []
        tail = name.rsplit(".", 1)[-1]
        scope = mod.qualname(node)
        if tail in _FUNNEL_CALLS:
            if any(kw.arg == "deadline" for kw in node.keywords):
                return []
            return [Finding(
                code="MXT110", path=mod.relpath, line=node.lineno,
                message=f"{name}() without an explicit deadline= "
                        f"({scope})",
                hint="every fleet dispatch carries an absolute "
                     "monotonic deadline — without one a dispatcher "
                     "thread can wedge forever on a dead replica; "
                     "pass deadline= visibly at the call site "
                     "(**kwargs splat does not satisfy the contract)",
                scope=scope, key="fleet-no-deadline",
                col=node.col_offset)]
        if not is_funnel and (tail in _RAW_CALL_TAILS
                              or _root(name) in _RAW_ROOTS):
            return [Finding(
                code="MXT110", path=mod.relpath, line=node.lineno,
                message=f"{name}(): raw transport outside the fleet "
                        f"funnel ({scope})",
                hint="route through transport.post_json/get_json/"
                     "call_local (the seam-wrapped, deadline-bounded "
                     "choke point)",
                scope=scope, key="fleet-raw-transport",
                col=node.col_offset)]
        return []
