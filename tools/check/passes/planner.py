"""MXT060: raw sharding construction outside ``mxnet_tpu/parallel/``.

The sharding planner (ISSUE 10, ``mxnet_tpu/parallel/planner/``) exists
so layout decisions live in ONE audited place: a ``ShardingPlan`` built
from logical-axis rules, consumed by TrainStep, ``pipeline_apply``, the
ZeRO engine and the serving AOT path.  A ``PartitionSpec(...)`` /
``P(...)`` literal or ``NamedSharding(...)`` constructed anywhere else
re-scatters that intent — exactly the hand-wiring the subsystem
replaced across ~12 files.

Rule: outside ``mxnet_tpu/parallel/`` (and the checker itself), code
must not *construct* ``jax.sharding.PartitionSpec`` or
``NamedSharding``.  Sharding intent flows through the planner
(``plan.spec(name)`` / ``plan.partition_specs()`` /
``plan.batch_spec()``) or the parallel-layer helpers.  Detected shapes:

- a call to a name imported from ``jax.sharding`` (any alias —
  ``from jax.sharding import PartitionSpec as P`` makes bare ``P(...)``
  a construction site; an unrelated local variable named ``P`` stays
  silent);
- attribute calls ``jax.sharding.PartitionSpec(...)`` /
  ``<alias>.NamedSharding(...)`` where the receiver resolves to the
  ``jax.sharding`` module.

Deliberate exceptions (tests exercising the parallel primitives
directly, bench micro-harnesses) carry an inline
``# mxtpu: noqa[MXT060] <reason>`` or a baseline entry.
"""
from __future__ import annotations

import ast

from ..core import Finding, Pass, register

_ALLOWED_PREFIXES = ("mxnet_tpu/parallel/", "tools/")
_TARGETS = {"PartitionSpec", "NamedSharding", "PositionalSharding",
            "GSPMDSharding"}


def _import_aliases(tree):
    """Local name → jax.sharding symbol for every import form, plus the
    set of local aliases that *are* the jax.sharding module itself."""
    name_map = {}
    module_aliases = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            if node.module and node.module.endswith("jax.sharding") or \
                    node.module == "jax.sharding":
                for a in node.names:
                    if a.name in _TARGETS:
                        name_map[a.asname or a.name] = a.name
            if node.module == "jax":
                # `from jax import sharding [as sh]` — the alias IS the
                # module, so `sh.PartitionSpec(...)` must resolve
                for a in node.names:
                    if a.name == "sharding":
                        module_aliases.add(a.asname or "sharding")
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "jax.sharding":
                    module_aliases.add(a.asname or "jax.sharding")
    return name_map, module_aliases


@register
class PlannerSharding(Pass):
    name = "planner-sharding"
    codes = {"MXT060": "raw sharding construction outside the planner "
                       "(mxnet_tpu/parallel/)"}

    def run(self, ctx, mod):
        if mod.relpath.startswith(_ALLOWED_PREFIXES):
            return []
        name_map, module_aliases = _import_aliases(mod.tree)
        findings = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            what = None
            f = node.func
            if isinstance(f, ast.Name) and f.id in name_map:
                what = name_map[f.id]
            elif isinstance(f, ast.Attribute) and f.attr in _TARGETS:
                # jax.sharding.PartitionSpec(...) / jsh.NamedSharding(...)
                recv = f.value
                dotted = None
                if isinstance(recv, ast.Attribute) and \
                        isinstance(recv.value, ast.Name):
                    dotted = f"{recv.value.id}.{recv.attr}"
                elif isinstance(recv, ast.Name):
                    dotted = recv.id
                if dotted == "jax.sharding" or dotted in module_aliases \
                        or (dotted or "").endswith("sharding"):
                    what = f.attr
            if what is None:
                continue
            scope = mod.qualname(node)
            findings.append(Finding(
                code="MXT060", path=mod.relpath, line=node.lineno,
                message=f"{what}(...) constructed outside "
                        f"mxnet_tpu/parallel/ ({scope})",
                hint="route sharding intent through the planner: build a "
                     "ShardingPlan (parallel.planner.plan_sharding / "
                     "plan_for) and consume plan.spec()/partition_specs()"
                     "/batch_spec(), or add a parallel-layer helper; "
                     "deliberate exceptions take "
                     "`# mxtpu: noqa[MXT060] <reason>`",
                scope=scope, key=f"raw-sharding:{what}",
                col=node.col_offset))
        return findings
