"""MXT050: trace-triggering call in the serving steady-state path.

The serving engine's whole contract (ISSUE 8) is that steady state pays
**zero fresh traces**: every executable is AOT-compiled at startup from
the signature manifest, and the per-step loop only *looks up*
pre-compiled callables.  A ``jax.jit`` / ``.lower`` / ``eval_shape`` /
``functionalize`` call that creeps into the loop re-introduces exactly
the retrace storms the PR 3 compile tracer was built to diagnose — at
request latency, where they hurt most.

Rule: inside ``mxnet_tpu/serving/``, trace-triggering calls may appear
only in functions whose (qualified) name declares compile-time intent —
a name segment containing one of ``aot``, ``warmup``, ``compile``,
``lower``, ``load``, ``export``, or ``manifest``.  Everything else in
the package is presumed reachable from the steady-state loop and is
flagged.  Flagged shapes:

- ``jax.jit(...)`` / bare ``jit(...)`` / ``pjit(...)``
- ``jax.eval_shape(...)`` / ``make_jaxpr(...)``
- ``<jit-ish expr>.lower(...)`` (the receiver mentions ``jit``/``jax``;
  plain ``str.lower()`` stays silent)
- ``functionalize(...)`` (re-traces the whole block)

Waive a deliberate exception inline with a reason:
``# mxtpu: noqa[MXT050] <why this trace is not on the request path>``.
"""
from __future__ import annotations

import ast

from ..astutil import call_name, names_in
from ..core import Finding, Pass, register

_SERVING_PREFIX = "mxnet_tpu/serving/"
_ALLOWED_MARKERS = ("aot", "warmup", "compile", "lower", "load", "export",
                    "manifest")
_TRACE_TAILS = {"jit", "pjit", "eval_shape", "make_jaxpr", "functionalize"}


def _allowed_scope(qualname):
    return any(m in seg.lower() for seg in qualname.split(".")
               for m in _ALLOWED_MARKERS)


@register
class ServingHotPath(Pass):
    name = "serving-hot-path"
    codes = {"MXT050": "trace-triggering call in the serving "
                       "steady-state path"}

    def run(self, ctx, mod):
        if not mod.relpath.startswith(_SERVING_PREFIX):
            return []
        findings = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if name is None:
                continue
            head, _, tail = name.rpartition(".")
            what = None
            if tail in _TRACE_TAILS:
                what = name
            elif tail == "lower" and isinstance(node.func, ast.Attribute):
                # only a jit-ish receiver: str.lower() must stay silent
                if names_in(node.func.value) & {"jit", "jax", "pjit"}:
                    what = name
            if what is None:
                continue
            scope = mod.qualname(node)
            if _allowed_scope(scope):
                continue
            findings.append(Finding(
                code="MXT050", path=mod.relpath, line=node.lineno,
                message=f"{what}() traces inside the serving steady-state "
                        f"path ({scope})",
                hint="AOT-compile at startup instead: move the trace into "
                     "a *aot*/*warmup*/*compile*-named function and look "
                     "the executable up by dispatch_cache.signature_key "
                     "in the loop (zero-fresh-trace contract, ISSUE 8)",
                scope=scope, key=f"serving-trace:{tail}",
                col=node.col_offset))
        return findings
