"""MXT005-006: ZeRO collective pairing + bucket state keying.

PR 7's ZeRO-1 sharded weight update (parallel/zero.py) added the
reduce-scatter → sharded update → all-gather shape the ROADMAP called
out as a new contract class.  Two invariants keep it SPMD-safe:

- **MXT005** — every ``reduce_scatter`` call site must be paired with a
  matching ``all_gather`` in the same (outermost) function, at the same
  uniformity level: a reduce-scatter leaves each rank holding only its
  shard, so a missing / rank-conditional / except-guarded all-gather
  either strands the sharded value or desyncs the peers' collective
  issue counts (the PR 2 equal-call-count contract, specialized to the
  pair).  An ``all_gather`` on its own is fine — gathering is a
  complete operation; scattering is not.  The analysis unit is the
  outermost function *including its nested helpers* (the jitted
  shard_map bodies in parallel/zero.py split prep/body into closures),
  and the primitive wrapper definitions themselves
  (``def reduce_scatter``/``def all_gather`` in parallel/collectives.py)
  are exempt — the contract binds call sites, not the seam.
- **MXT006** — transient per-bucket kvstore/state keys (the
  ``__grad_bucket…`` family) must embed the plan generation.  Bucket
  plans replan when the entry signature changes; state keyed per bucket
  without the generation (compression error-feedback residuals, ZeRO
  shard state) would silently alias across plans with different bucket
  compositions — the exact leak PR 4 fixed by generation-keying residual
  keys.  Flagged shapes: an f-string or string concatenation building a
  key that starts with ``__grad_bucket`` whose dynamic parts never
  mention a generation/version; reading such keys
  (``k.startswith("__grad_bucket")``) is not a build and stays silent.
"""
from __future__ import annotations

import ast

from ..astutil import call_name, names_in
from ..core import Finding, Pass, register

_RS_NAMES = {"reduce_scatter", "psum_scatter"}
_AG_NAMES = {"all_gather"}
# see passes/collectives.py: the shared condition vocabulary
from .collectives import _classify, _rank_locals  # noqa: E402

_GEN_MARKERS = {"gen", "generation", "version", "plan_generation"}
_BUCKET_KEY_PREFIX = "__grad_bucket"


def _tail(name):
    return (name or "").rsplit(".", 1)[-1]


def _calls_with_guard(fn, rank_locals):
    """Yield ``(call, guarded)`` for every rs/ag call in ``fn``'s whole
    subtree (nested defs included — closures run as part of the same
    jitted unit here), where ``guarded`` is True when the call sits
    under a rank-conditional branch or an except handler."""
    out = []

    def emit(node, guarded):
        # expression position: every call in the subtree (lambda bodies
        # included — ast.walk descends into them) at the current level
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                out.append((sub, guarded))

    def walk(stmts, guarded):
        for stmt in stmts:
            if isinstance(stmt, ast.If):
                # the test itself runs at the CURRENT level (a call
                # inside `if reduce_scatter(...):` is unconditional)
                emit(stmt.test, guarded)
                arm = guarded or \
                    _classify(stmt.test, rank_locals) == "rank"
                walk(stmt.body, arm)
                walk(stmt.orelse, arm)
            elif isinstance(stmt, ast.Try):
                walk(stmt.body, guarded)
                for h in stmt.handlers:
                    walk(h.body, True)
                walk(stmt.orelse, guarded)
                walk(stmt.finalbody, guarded)
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                walk(stmt.body, guarded)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                # recurse statement-wise so a rank-conditional If NESTED
                # in the loop still flips the guard for its arms
                emit(stmt.iter, guarded)
                walk(stmt.body, guarded)
                walk(stmt.orelse, guarded)
            elif isinstance(stmt, ast.While):
                emit(stmt.test, guarded)
                walk(stmt.body, guarded)
                walk(stmt.orelse, guarded)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    emit(item.context_expr, guarded)
                walk(stmt.body, guarded)
            else:
                emit(stmt, guarded)

    walk(fn.body, False)
    # ast.walk above revisits nested calls; dedupe by identity-ish key
    seen, uniq = set(), []
    for call, guarded in out:
        key = (call.lineno, call.col_offset)
        if key in seen:
            continue
        seen.add(key)
        uniq.append((call, guarded))
    return uniq


def _outermost_functions(tree):
    """Module- and class-level function defs (methods), NOT functions
    nested inside other functions — those analyze with their parent."""
    stack = [tree]
    while stack:
        node = stack.pop()
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield child
            elif isinstance(child, (ast.Module, ast.ClassDef)):
                stack.append(child)
            elif isinstance(child, (ast.If, ast.Try, ast.ExceptHandler,
                                    ast.With, ast.AsyncWith, ast.For,
                                    ast.AsyncFor, ast.While)):
                stack.append(child)


@register
class CollectivePairing(Pass):
    name = "collective-pairing"
    codes = {
        "MXT005": "reduce-scatter without a matching all-gather",
        "MXT006": "bucket state key missing the plan generation",
    }

    def run(self, ctx, mod):
        findings = []
        for fn in _outermost_functions(mod.tree):
            if fn.name in _RS_NAMES | _AG_NAMES:
                continue  # primitive wrapper definition, not a call site
            rank_locals = _rank_locals(fn)
            calls = _calls_with_guard(fn, rank_locals)
            rs = [(c, g) for c, g in calls
                  if _tail(call_name(c)) in _RS_NAMES]
            if not rs:
                continue
            ag_guards = {g for c, g in calls
                         if _tail(call_name(c)) in _AG_NAMES}
            for call, guarded in rs:
                name = call_name(call) or "reduce_scatter"
                if not ag_guards:
                    findings.append(Finding(
                        code="MXT005", path=mod.relpath, line=call.lineno,
                        message=f"{name!r} has no matching all_gather in "
                                f"{fn.name!r}",
                        hint="a reduce-scatter leaves each rank holding "
                             "only its shard; pair it with an all_gather "
                             "in the same function (parallel/zero.py is "
                             "the reference shape) or the sharded value "
                             "escapes incomplete",
                        scope=mod.qualname(call), key=f"unpaired:{name}",
                        col=call.col_offset))
                elif guarded not in ag_guards:
                    findings.append(Finding(
                        code="MXT005", path=mod.relpath, line=call.lineno,
                        message=f"{name!r} and its all_gather sit at "
                                f"different uniformity levels (one is "
                                f"under a rank-conditional branch or "
                                f"except handler)",
                        hint="both halves of the pair must be reached by "
                             "every rank the same number of times; hoist "
                             "them to the same branch level (PR 2 "
                             "equal-call-count contract)",
                        scope=mod.qualname(call),
                        key=f"level-mismatch:{name}",
                        col=call.col_offset))
        findings.extend(self._check_bucket_keys(mod))
        return findings

    # -- MXT006 -------------------------------------------------------------
    def _check_bucket_keys(self, mod):
        findings = []
        for node in ast.walk(mod.tree):
            built = self._built_key_parts(node)
            if built is None:
                continue
            prefix, dynamic = built
            if not prefix.startswith(_BUCKET_KEY_PREFIX):
                continue
            names = set()
            for d in dynamic:
                names |= names_in(d)
            if not (names & _GEN_MARKERS):
                findings.append(Finding(
                    code="MXT006", path=mod.relpath, line=node.lineno,
                    message=f"bucket key built from {prefix!r} without a "
                            f"plan-generation component",
                    hint="include the Bucketer generation in the key "
                         "(f\"__grad_bucket{b.index}g{gen}\") so "
                         "per-bucket state (compression residuals, ZeRO "
                         "shards) never aliases across replans with "
                         "different bucket compositions (PR 4 contract)",
                    scope=mod.qualname(node),
                    key=f"ungenerationed:{prefix}",
                    col=node.col_offset))
        return findings

    @staticmethod
    def _built_key_parts(node):
        """``(literal_prefix, [dynamic subexpressions])`` when ``node``
        BUILDS a key string (f-string or ``"..." + expr`` concat whose
        literal head is a constant); None for anything else — plain
        constants (``startswith`` probes) are reads, not builds."""
        if isinstance(node, ast.JoinedStr):
            if not node.values or not isinstance(node.values[0],
                                                 ast.Constant):
                return None
            dynamic = [v for v in node.values
                       if not isinstance(v, ast.Constant)]
            if not dynamic:
                return None
            return str(node.values[0].value), dynamic
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add) \
                and isinstance(node.left, ast.Constant) \
                and isinstance(node.left.value, str):
            return node.left.value, [node.right]
        return None
