"""MXT030-032: the MXNET_* knob registry must stay closed.

``mxnet_tpu/env.py`` is the single registry: every knob the library
reads is declared there (describe()'s wired table or ``_SUBSUMED``) and
documented in README's knob tables — so ``mx.env.describe()`` is always
the complete operator surface and a typo'd var can never silently do
nothing.

- **MXT030** — a ``MXNET_*`` var read inside ``mxnet_tpu/`` that env.py
  does not declare.
- **MXT031** — a wired knob declared in env.py that nothing reads
  anywhere in the repo (dead registry entry or a lost call site).
- **MXT032** — a wired knob missing from README's knob tables.

Read shapes recognized: ``os.environ.get/[]``, ``os.getenv``,
``environ.get``, and the ``env.get_str/get_int/get_bool/get_float``
helpers — with a literal name argument.  Reads through a variable
(checkpoint's launcher-rank probe loops over a name tuple) are not
resolved; the registry direction (MXT031) covers those via the
repo-wide text sweep.
"""
from __future__ import annotations

import ast
import os
import re

from ..astutil import call_name
from ..core import Finding, Pass, register

_MXNET_NAME = re.compile(r"^MXNET_[A-Z0-9_]+$")
_READ_CALLS = {"os.environ.get", "environ.get", "os.getenv", "getenv",
               "env.get_str", "env.get_int", "env.get_bool",
               "env.get_float", "_env.get_str", "_env.get_int",
               "_env.get_bool", "_env.get_float", "get_str", "get_int",
               "get_bool", "get_float"}


def _read_names(node):
    """MXNET_* names read by this Call/Subscript node, if any."""
    names = []
    if isinstance(node, ast.Call):
        name = call_name(node)
        if name in _READ_CALLS or (
                name and name.endswith((".environ.get", ".getenv"))):
            for arg in node.args[:1] or []:
                for sub in ast.walk(arg):
                    if isinstance(sub, ast.Constant) and \
                            isinstance(sub.value, str) and \
                            _MXNET_NAME.match(sub.value):
                        names.append(sub.value)
    elif isinstance(node, ast.Subscript):
        from ..astutil import dotted

        base = dotted(node.value)
        if base and base.endswith("environ"):
            for sub in ast.walk(node.slice):
                if isinstance(sub, ast.Constant) and \
                        isinstance(sub.value, str) and \
                        _MXNET_NAME.match(sub.value):
                    names.append(sub.value)
    return names


@register
class EnvKnobRegistry(Pass):
    name = "env-knob-registry"
    codes = {
        "MXT030": "MXNET_* read not registered in env.py",
        "MXT031": "registered knob never read anywhere",
        "MXT032": "registered knob missing from README knob tables",
    }

    def __init__(self):
        self._reads = {}   # name -> first (path, line, scope)

    def run(self, ctx, mod):
        findings = []
        registry = ctx.repo.env_registry
        is_env_py = mod.relpath == registry["path"]
        in_lib = mod.relpath.startswith("mxnet_tpu/")
        for node in ast.walk(mod.tree):
            for name in _read_names(node):
                self._reads.setdefault(
                    name, (mod.relpath, node.lineno, mod.qualname(node)))
                if in_lib and not is_env_py and \
                        name not in registry["declared"]:
                    findings.append(Finding(
                        code="MXT030", path=mod.relpath, line=node.lineno,
                        message=f"{name} is read here but not registered "
                                f"in {registry['path']}",
                        hint="add it to env.py's describe() wired table "
                             "(+ docstring) and README's knob table so "
                             "describe() stays the complete operator "
                             "surface",
                        scope=mod.qualname(node), key=f"unregistered:{name}"))
        return findings

    def finalize(self, ctx):
        findings = []
        registry = ctx.repo.env_registry
        anchors = registry["anchors"]
        # vars whose READ legitimately lives outside the scanned roots
        # (bench.py at the repo root) are resolved by a repo-wide text
        # sweep before MXT031 fires
        unread = {n for n in registry["wired"] if n not in self._reads}
        if unread:
            unread -= _textual_reads(ctx.repo_root, unread,
                                     exclude=(registry["path"],
                                              "README.md"))
        for name in sorted(unread):
            findings.append(Finding(
                code="MXT031", path=registry["path"],
                line=anchors.get(name, 1),
                message=f"{name} is registered in env.py but nothing "
                        f"reads it",
                hint="wire it to a call site or delete the registry row "
                     "(a dead knob row misdocuments the operator surface)",
                scope="describe", key=f"unread:{name}"))
        for name in sorted(registry["wired"] - ctx.repo.readme_knobs):
            findings.append(Finding(
                code="MXT032", path=registry["path"],
                line=anchors.get(name, 1),
                message=f"{name} is registered in env.py but missing "
                        f"from README's knob tables",
                hint="add a row to README's knob reference (operators "
                     "read the README, not env.py)",
                scope="describe", key=f"undocumented:{name}"))
        return findings


def _textual_reads(repo_root, names, exclude=()):
    """Names that appear in any repo .py file outside ``exclude`` —
    the cheap fallback for read sites outside the scanned roots."""
    found = set()
    for dirpath, dirnames, filenames in os.walk(repo_root):
        dirnames[:] = [d for d in dirnames
                       if d not in ("__pycache__", ".git")]
        for fn in filenames:
            if not fn.endswith(".py"):
                continue
            rel = os.path.relpath(os.path.join(dirpath, fn),
                                  repo_root).replace(os.sep, "/")
            if rel in exclude:
                continue
            try:
                with open(os.path.join(dirpath, fn),
                          encoding="utf-8") as f:
                    text = f.read()
            except OSError:
                continue
            for n in names - found:
                if n in text:
                    found.add(n)
        if found == set(names):
            break
    return found
