"""MXT010: blocking host sync on a step hot path.

The class of bug PR 5's fused ``has_overflow`` fixed: a per-param
``bool(jnp.isfinite(v).all())`` loop paid K blocking device->host round
trips on every AMP step.  Device values must stay lazily dispatched on
the hot path; the ONE sync a step needs should be explicit and waived
with a reason (``# mxtpu: noqa[MXT010] <why this sync is the design>``).

Hot zones are the dispatch/TrainStep/Trainer/bucketing files below —
whole files, because their every function sits inside the step loop.
Flagged shapes:

- ``<expr>.item()`` / ``<expr>.asnumpy()``
- ``np.asarray(x)`` / ``np.array(x)`` (numpy aliases only — ``jnp.*``
  stays on device and is fine)
- ``jax.device_get(x)``
- ``bool(...)`` / ``int(...)`` / ``float(...)`` wrapping an expression
  that mentions ``jnp``/``jax`` (forces the value to host)
"""
from __future__ import annotations

import ast

from ..astutil import call_name, names_in
from ..core import Finding, Pass, register

HOT_ZONES = (
    "mxnet_tpu/ndarray/dispatch_cache.py",
    "mxnet_tpu/parallel/data_parallel.py",
    "mxnet_tpu/parallel/bucketing.py",
    "mxnet_tpu/gluon/trainer.py",
    "mxnet_tpu/contrib/amp/loss_scaler.py",
    # the numerical-integrity guard (ISSUE 20) runs INSIDE the step
    # loop: its contract is ONE designed host sync per guarded step
    # (the fused sentinel vector) — anything else must stay lazy
    "mxnet_tpu/guard.py",
    "mxnet_tpu/module/bucketing_module.py",
    # the serving engine's step loop + page pool (ISSUE 8): one waived
    # token fetch per engine step is the design; everything else must
    # stay lazily dispatched
    "mxnet_tpu/serving/engine.py",
    "mxnet_tpu/serving/kvcache.py",
)

_NP_ALIASES = {"np", "numpy", "_np", "onp"}
_SYNC_METHODS = {"item", "asnumpy"}
_CAST_BUILTINS = {"bool", "int", "float"}


@register
class HostSyncInHotPath(Pass):
    name = "host-sync-hot-path"
    codes = {"MXT010": "blocking host sync on a step hot path"}

    def run(self, ctx, mod):
        if mod.relpath not in HOT_ZONES:
            return []
        findings = []

        def emit(node, what):
            findings.append(Finding(
                code="MXT010", path=mod.relpath, line=node.lineno,
                message=f"{what} blocks on a device->host transfer on "
                        f"the step hot path",
                hint="keep values lazily dispatched; fuse per-item syncs "
                     "into one reduction with a single sync (PR 5 "
                     "has_overflow pattern) or waive with a reason if "
                     "this sync IS the design",
                scope=mod.qualname(node), key=f"host-sync:{what}",
                col=node.col_offset))

        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if name is None:
                continue
            head, _, tail = name.rpartition(".")
            if tail in _SYNC_METHODS and head:
                emit(node, f".{tail}()")
            elif tail in {"asarray", "array"} and \
                    head.rsplit(".", 1)[-1] in _NP_ALIASES:
                emit(node, f"{head}.{tail}()")
            elif tail == "device_get":
                emit(node, name + "()")
            elif name in _CAST_BUILTINS and node.args:
                if names_in(node.args[0]) & {"jnp", "jax"}:
                    emit(node, f"{name}() on a device expression")
        return findings
