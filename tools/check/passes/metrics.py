"""MXT090/091: the ``mxnet_*`` metric catalog must stay closed.

README's "Observability" section carries the **Metric catalog** table —
the operator-facing registry of every telemetry family the library can
emit.  PRs 4-13 each added families (compile-cache, reshard,
checkpoint, bucket-allreduce, ...) and the catalog silently drifted;
this pass closes it both ways, exactly like MXT030-032 close the
env-knob registry:

- **MXT090** — a metric family registered in code (a literal first
  argument to ``telemetry.counter/gauge/histogram`` — receiver-alias
  agnostic — or a collector family dict carrying ``name`` + ``samples``)
  that has no README catalog row.
- **MXT091** — a catalog row naming a family nothing in
  ``mxnet_tpu/`` registers (dead documentation).

Dynamic names are handled as patterns: an f-string registration
(``f"mxnet_fault_seam_{metric}_total"``) matches any catalog row its
literal parts admit, and MXT090 fires only when NO row matches.  The
catalog row grammar (implied ``mxnet_`` prefix, inner ``{a,b}``
alternation, trailing ``{label}`` annotation) lives in
``repo.expand_metric_token``.  A README with no ``**Metric catalog**``
marker leaves the pass inert (fixture mini-repos); registrations are
only collected from ``mxnet_tpu/`` so tests asserting on family names
never count as registrations.
"""
from __future__ import annotations

import ast
import re

from ..astutil import call_name
from ..core import Finding, Pass, register
from ..repo import _METRIC_NAME

# a registration is a call to one of these (last dotted component, so
# telemetry.counter / _telemetry.gauge / _tel.histogram / the local
# collector-family helper `fam` all resolve)
_REG_CALLEES = {"counter", "gauge", "histogram", "fam"}


def _literal_or_pattern(node):
    """``(exact_name, None)`` / ``(None, regex)`` / ``(None, None)``
    for a registration-name argument node."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        if _METRIC_NAME.match(node.value):
            return node.value, None
        return None, None
    if isinstance(node, ast.JoinedStr):
        parts = []
        for v in node.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                parts.append(re.escape(v.value))
            else:
                parts.append("[a-z0-9_]+")
        pat = "".join(parts)
        if pat.startswith(re.escape("mxnet_")):
            return None, "^" + pat + "$"
    return None, None


def _registration_name_nodes(node):
    """Name-argument nodes of one AST node, if it registers a family."""
    if isinstance(node, ast.Call):
        name = call_name(node)
        callee = name.rsplit(".", 1)[-1] if name else None
        if callee in _REG_CALLEES and node.args:
            return [node.args[0]]
    elif isinstance(node, ast.Dict):
        # collector output shape: {"name": ..., "type": ..., "samples":
        # ...} — the samples key distinguishes a metric family dict
        # from any other {"name": ...} literal (ONNX graphs etc.)
        keys = {k.value for k in node.keys
                if isinstance(k, ast.Constant)}
        if "name" in keys and "samples" in keys:
            return [v for k, v in zip(node.keys, node.values)
                    if isinstance(k, ast.Constant) and k.value == "name"]
    return []


@register
class MetricRegistry(Pass):
    name = "metric-registry"
    codes = {
        "MXT090": "registered metric family missing from README catalog",
        "MXT091": "README catalog row matches no metric registration",
    }

    def __init__(self):
        self._exact = {}      # name -> first (path, line, scope)
        self._patterns = {}   # regex -> first (path, line, scope)

    def run(self, ctx, mod):
        findings = []
        if not mod.relpath.startswith("mxnet_tpu/"):
            return findings
        registry = ctx.repo.readme_metrics
        for node in ast.walk(mod.tree):
            for arg in _registration_name_nodes(node):
                exact, pattern = _literal_or_pattern(arg)
                if exact is not None:
                    self._exact.setdefault(
                        exact, (mod.relpath, arg.lineno,
                                mod.qualname(arg)))
                    if registry["has_catalog"] and \
                            exact not in registry["names"]:
                        findings.append(Finding(
                            code="MXT090", path=mod.relpath,
                            line=arg.lineno,
                            message=f"metric family {exact!r} is "
                                    "registered here but has no README "
                                    "Metric-catalog row",
                            hint="add a row to README's Observability "
                                 "metric catalog (operators discover "
                                 "families there, not by scraping)",
                            scope=mod.qualname(arg),
                            key=f"uncataloged:{exact}"))
                elif pattern is not None:
                    self._patterns.setdefault(
                        pattern, (mod.relpath, arg.lineno,
                                  mod.qualname(arg)))
        return findings

    def finalize(self, ctx):
        findings = []
        registry = ctx.repo.readme_metrics
        if not registry["has_catalog"]:
            return findings
        catalog = registry["names"]
        for pattern, (path, line, scope) in sorted(
                self._patterns.items()):
            rx = re.compile(pattern)
            if not any(rx.match(n) for n in catalog):
                findings.append(Finding(
                    code="MXT090", path=path, line=line,
                    message=f"dynamically-named metric family "
                            f"(pattern {pattern}) has no matching "
                            "README catalog row",
                    hint="add a row covering the expansion (the "
                         "{a,b} alternation syntax documents the "
                         "dynamic part)",
                    scope=scope, key=f"uncataloged-pattern:{pattern}"))
        pats = [re.compile(p) for p in self._patterns]
        for name, line in sorted(catalog.items()):
            if name in self._exact:
                continue
            if any(rx.match(name) for rx in pats):
                continue
            findings.append(Finding(
                code="MXT091", path=registry["path"], line=line,
                message=f"README catalog row {name!r} matches no "
                        "metric registration in mxnet_tpu/",
                hint="delete the row or fix the name — a dead catalog "
                     "row misdocuments the scrape surface",
                scope="<catalog>", key=f"dead-row:{name}"))
        return findings
