"""MXT070/071: graph-compiler pass contracts.

The graph tier (ISSUE 11, ``mxnet_tpu/graph/``) rests on two machine-
checkable promises:

- **MXT070 — passes are pure.**  A registered graph pass
  (``@graph_pass(...)``) is a ``Graph -> Graph`` FUNCTION: it must never
  mutate the input graph's nodes, attrs, edges, or head lists.  The
  compliant pattern is ``g = graph.copy()`` (or a rebuild) and mutation
  of the copy only.  Detection is a taint scan in the style of MXT060's
  construction scan: the first parameter is tainted; attribute reads,
  subscripts and iteration propagate taint; a *call* result (``.copy()``,
  ``Graph(...)``, ``Node(...)``) is fresh.  Flagged shapes on a tainted
  receiver: attribute assignment (``n.inputs = ...``), subscript
  assignment (``n.attrs[k] = ...``), aug-assignment, and mutating method
  calls (``.append``/``.update``/``.pop``/...).

- **MXT071 — every pass reachable from PassPipeline is registered.**
  Pass schedules are built from *names* (``DEFAULT_PASSES``, literal
  lists handed to ``PassPipeline([...])``); a name that no
  ``@graph_pass("name")`` decorator registers would fail at runtime on
  whatever machine first builds that pipeline — the checker fails it at
  lint time instead.
"""
from __future__ import annotations

import ast

from ..core import Finding, Pass, register

_MUTATORS = {"append", "extend", "insert", "remove", "clear", "pop",
             "popitem", "update", "setdefault", "sort", "reverse",
             "add", "discard"}


def _decorator_pass_name(dec):
    """The literal pass name when ``dec`` is ``graph_pass("name"[, ...])``
    (any receiver spelling); None otherwise."""
    if not isinstance(dec, ast.Call):
        return None
    f = dec.func
    tail = f.id if isinstance(f, ast.Name) else \
        f.attr if isinstance(f, ast.Attribute) else None
    if tail != "graph_pass":
        return None
    if dec.args and isinstance(dec.args[0], ast.Constant) and \
            isinstance(dec.args[0].value, str):
        return dec.args[0].value
    return None


def _root_name(node):
    """The Name at the root of an Attribute/Subscript chain."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


class _TaintScan(ast.NodeVisitor):
    """Linear taint propagation over one pass function's body."""

    def __init__(self, param):
        self.tainted = {param}
        self.hits = []       # (ast node, description)

    def _expr_tainted(self, node):
        """An expression yields a tainted object when it is a read
        (name/attribute/subscript/iteration) rooted at a tainted name.
        A Call produces a FRESH object (copy()/Graph()/Node()/list())."""
        if isinstance(node, ast.Call):
            return False
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, (ast.Attribute, ast.Subscript)):
            return self._expr_tainted(node.value)
        if isinstance(node, (ast.Tuple, ast.List)):
            return any(self._expr_tainted(e) for e in node.elts)
        return False

    def _bind(self, target, tainted):
        if isinstance(target, ast.Name):
            if tainted:
                self.tainted.add(target.id)
            else:
                self.tainted.discard(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._bind(e, tainted)

    def visit_Assign(self, node):
        src_tainted = self._expr_tainted(node.value)
        for t in node.targets:
            if isinstance(t, ast.Attribute) and \
                    self._expr_tainted(t.value):
                self.hits.append((node, f"assigns .{t.attr} on the input "
                                        "graph"))
            elif isinstance(t, ast.Subscript) and \
                    self._expr_tainted(t.value):
                self.hits.append((node, "subscript-assigns into the input "
                                        "graph"))
            else:
                self._bind(t, src_tainted)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        t = node.target
        if isinstance(t, (ast.Attribute, ast.Subscript)) and \
                self._expr_tainted(t.value):
            self.hits.append((node, "aug-assigns into the input graph"))
        self.generic_visit(node)

    def visit_For(self, node):
        self._bind(node.target, self._expr_tainted(node.iter))
        self.generic_visit(node)

    def visit_Call(self, node):
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in _MUTATORS and \
                self._expr_tainted(f.value):
            self.hits.append((node, f".{f.attr}(...) mutates the input "
                                    "graph"))
        self.generic_visit(node)

    def visit_comprehension(self, node):   # pragma: no cover - via generic
        self._bind(node.target, self._expr_tainted(node.iter))
        self.generic_visit(node)


@register
class GraphPassContracts(Pass):
    name = "graph-pass-contracts"
    codes = {
        "MXT070": "graph pass mutates its input graph",
        "MXT071": "pipeline-reachable graph pass is not registered",
    }

    def __init__(self):
        self._registered = set()     # names from @graph_pass("...")
        self._referenced = []        # (name, path, line, scope)

    def run(self, ctx, mod):
        findings = []
        for node in ast.walk(mod.tree):
            # registration sites + purity scan
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                pname = None
                for dec in node.decorator_list:
                    pname = _decorator_pass_name(dec) or pname
                if pname is None:
                    continue
                self._registered.add(pname)
                if not node.args.args:
                    continue
                scan = _TaintScan(node.args.args[0].arg)
                for stmt in node.body:
                    scan.visit(stmt)
                for hit, what in scan.hits:
                    findings.append(Finding(
                        code="MXT070", path=mod.relpath, line=hit.lineno,
                        message=f"graph pass {pname!r} {what}",
                        hint="passes are pure Graph -> Graph: start from "
                             "graph.copy() (or rebuild node lists) and "
                             "mutate only the copy; the input graph may "
                             "be cached and replayed by another consumer",
                        scope=mod.qualname(hit), key=f"impure:{pname}",
                        col=hit.col_offset))
            # schedule references: DEFAULT_PASSES-style literals in the
            # graph package, and literal lists fed to PassPipeline(...)
            if isinstance(node, ast.Assign) and \
                    mod.relpath.startswith("mxnet_tpu/graph/"):
                for t in node.targets:
                    if isinstance(t, ast.Name) and \
                            t.id.endswith("_PASSES") and \
                            isinstance(node.value, (ast.Tuple, ast.List)):
                        for e in node.value.elts:
                            if isinstance(e, ast.Constant) and \
                                    isinstance(e.value, str):
                                self._referenced.append(
                                    (e.value, mod.relpath, e.lineno,
                                     mod.qualname(e)))
            if isinstance(node, ast.Call):
                f = node.func
                tail = f.id if isinstance(f, ast.Name) else \
                    f.attr if isinstance(f, ast.Attribute) else None
                if tail == "PassPipeline" and node.args and \
                        isinstance(node.args[0], (ast.Tuple, ast.List)):
                    for e in node.args[0].elts:
                        if isinstance(e, ast.Constant) and \
                                isinstance(e.value, str):
                            self._referenced.append(
                                (e.value, mod.relpath, e.lineno,
                                 mod.qualname(e)))
        return findings

    def finalize(self, ctx):
        findings = []
        for name, path, line, scope in self._referenced:
            if name in self._registered:
                continue
            findings.append(Finding(
                code="MXT071", path=path, line=line,
                message=f"pass name {name!r} is scheduled but no "
                        f"@graph_pass({name!r}) registration exists",
                hint="register the pass (@graph_pass) in "
                     "mxnet_tpu/graph/passes.py or fix the name — an "
                     "unregistered name fails at the first pipeline "
                     "build on someone else's machine",
                scope=scope, key=f"unregistered-pass:{name}"))
        return findings
