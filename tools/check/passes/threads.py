"""MXT020-022: lock-and-thread hygiene.

Three deadlock shapes this repo has already paid for:

- **MXT020** — ``threading.Lock()`` in a module that installs signal
  handlers.  The handler runs ON the main thread between bytecodes; if
  it lands while the module holds its own plain lock, re-entering
  self-deadlocks (the PR 5 lifecycle lesson — use ``RLock``).
- **MXT021** — a blocking ``.join()`` / collective / ``barrier`` while
  holding a module lock: every other thread that needs the lock (
  including the one being joined) deadlocks behind it.
- **MXT022** — thread teardown that ``join()``\\ s a worker BEFORE
  setting its stop event (the PR 2 DataLoader shape: a worker blocked
  on its queue never observes the stop and the join never returns).
"""
from __future__ import annotations

import ast

from ..astutil import call_name, dotted
from ..core import Finding, Pass, register

_BLOCKING_TAILS = {"join", "barrier", "_barrier", "allreduce_hosts",
                   "allreduce_any", "psum", "sync_global_devices",
                   "allreduce_hosts_quantized",
                   "allreduce_hosts_quantized_multi"}
_STOPPISH = ("stop", "shutdown", "done", "exit", "quit")
_THREADISH = ("thread", "worker", "pool", "producer", "consumer",
              "pending", "writer", "watchdog")


def _installs_signal_handlers(tree):
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            name = call_name(node)
            if name and name.rsplit(".", 1)[-1] == "signal" and \
                    "." in (name or ""):
                return True
    return False


@register
class LockAndThreadHygiene(Pass):
    name = "lock-thread-hygiene"
    codes = {
        "MXT020": "plain threading.Lock in a signal-handler module",
        "MXT021": "blocking join/collective while holding a lock",
        "MXT022": "thread joined before its stop event is set",
    }

    def run(self, ctx, mod):
        findings = []
        tree = mod.tree

        # MXT020 ------------------------------------------------------
        if _installs_signal_handlers(tree):
            for node in ast.walk(tree):
                if isinstance(node, ast.Call):
                    name = call_name(node)
                    if name and name.rsplit(".", 1)[-1] == "Lock" and \
                            name.rsplit(".", 1)[0] in ("threading",
                                                       "_threading"):
                        findings.append(Finding(
                            code="MXT020", path=mod.relpath,
                            line=node.lineno,
                            message="plain threading.Lock() in a module "
                                    "that installs signal handlers",
                            hint="the handler runs on the main thread "
                                 "between bytecodes — if it re-enters "
                                 "this module while the lock is held it "
                                 "self-deadlocks; use threading.RLock() "
                                 "(PR 5 lifecycle lesson)",
                            scope=mod.qualname(node), key="plain-lock",
                            col=node.col_offset))

        # MXT021 ------------------------------------------------------
        for node in ast.walk(tree):
            if not isinstance(node, ast.With):
                continue
            held = [dotted(i.context_expr) or
                    (call_name(i.context_expr) or "")
                    for i in node.items]
            if not any("lock" in h.lower() for h in held if h):
                continue
            for stmt in node.body:
                for sub in ast.walk(stmt):
                    if isinstance(sub, ast.Call):
                        name = call_name(sub) or ""
                        if name.rsplit(".", 1)[-1] in _BLOCKING_TAILS:
                            findings.append(Finding(
                                code="MXT021", path=mod.relpath,
                                line=sub.lineno,
                                message=f"blocking call {name!r} while "
                                        f"holding {held[0]!r}",
                                hint="snapshot state under the lock, "
                                     "release it, then block — the "
                                     "joined thread (or any peer) may "
                                     "need this lock to make progress",
                                scope=mod.qualname(sub),
                                key=f"lock-block:{name}",
                                col=sub.col_offset))

        # MXT022 ------------------------------------------------------
        for fn in ast.walk(tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            joins, sets = [], []
            for sub in ast.walk(fn):
                if not isinstance(sub, ast.Call):
                    continue
                name = call_name(sub)
                if not name or "." not in name:
                    continue
                recv, _, tail = name.rpartition(".")
                recv_l = recv.lower()
                if tail == "join" and any(t in recv_l for t in _THREADISH):
                    joins.append((sub.lineno, recv, sub))
                if tail == "set" and any(s in recv_l for s in _STOPPISH):
                    sets.append(sub.lineno)
            if joins and sets:
                first_set = min(sets)
                for lineno, recv, sub in joins:
                    if lineno < first_set:
                        findings.append(Finding(
                            code="MXT022", path=mod.relpath, line=lineno,
                            message=f"{recv}.join() before the stop "
                                    f"event is set (first .set() at "
                                    f"line ~{first_set})",
                            hint="a worker blocked on its queue never "
                                 "observes the stop and the join never "
                                 "returns — set the stop event FIRST, "
                                 "then join (PR 2 DataLoader deadlock)",
                            scope=mod.qualname(sub),
                            key=f"join-before-set:{recv}",
                            col=sub.col_offset))
        return findings
