"""MXT100: ledger discipline — collective issue sites stamp the flight
recorder.

ISSUE 15's distributed flight recorder
(:mod:`mxnet_tpu.flight_recorder`) turns a hang or SPMD desync from
"rank N stalled somewhere" into "rank N never entered allreduce seq
4127" — but only if **every** Python-level collective issue site in
``mxnet_tpu/parallel/`` stamps the ring.  A single unstamped site makes
the per-rank sequence numbers diverge from the true issue order and the
cross-rank blame merge points at the wrong collective.  This pass keeps
the ledger closed as new collective call sites land:

- **Flagged**: a call to a collective-issuing function (the host-value
  allreduce family, ``barrier``, ``fetch_global``, the raw
  ``process_allgather`` / ``sync_global_devices``, and the repo's
  ``reduce_scatter`` / ``all_gather`` wrappers) inside ``parallel/``
  whose enclosing outermost function contains no
  ``flight_recorder.collective(...)`` stamp.
- **Compliant by construction**: calls to *self-stamping funnels* —
  functions in ``parallel/collectives.py`` that stamp the recorder
  themselves, directly or by delegation
  (``RepoModel.collective_stampers``, extracted from the source at
  check time so the trusted set can never drift).  ``allreduce_any``
  → ``allreduce_hosts`` → ``_combine_with_seam`` (the stamp) is the
  canonical chain.
- **Exempt**: ``jax.lax.*`` receivers — trace-level primitives inside
  ``shard_map`` bodies issue at jit dispatch, not at their own line;
  their Python issue point (e.g. ``ZeroBucketEngine.step_bucket``)
  carries the stamp, and sites that cannot (the traced body builders
  in ``zero.py``) carry a reasoned ``noqa`` naming where the stamp
  lives.
"""
from __future__ import annotations

import ast

from ..astutil import call_name
from ..core import Finding, Pass, register
from ..repo import flight_aliases, is_stamp_call
from .pairing import _outermost_functions

# collective-issuing callables whose Python call site IS a runtime
# issue point (host-level families + the repo shard_map-pair wrappers)
_COLLECTIVE_NAMES = {
    "allreduce_hosts", "allreduce_hosts_quantized",
    "allreduce_hosts_quantized_multi", "allreduce_any", "barrier",
    "fetch_global", "process_allgather", "sync_global_devices",
    "reduce_scatter", "all_gather",
}


def _tail(name):
    return (name or "").rsplit(".", 1)[-1]


def _lax_receiver(name):
    """jax.lax.* (trace-level primitive) — exempt; see module doc."""
    return name.startswith("lax.") or ".lax." in name


@register
class LedgerDiscipline(Pass):
    name = "ledger-discipline"
    codes = {
        "MXT100": "collective issue site without a flight-recorder "
                  "stamp",
    }

    def run(self, ctx, mod):
        if "parallel/" not in mod.relpath:
            return []
        stampers = ctx.repo.collective_stampers
        mod_al, fn_al = flight_aliases(mod.tree)
        findings = []
        for fn in _outermost_functions(mod.tree):
            if fn.name in _COLLECTIVE_NAMES:
                continue  # primitive wrapper definition, not a call site
            calls = [sub for sub in ast.walk(fn)
                     if isinstance(sub, ast.Call)]
            has_stamp = any(is_stamp_call(c, mod_al, fn_al)
                            for c in calls)
            for call in calls:
                name = call_name(call)
                if name is None:
                    continue
                tail = _tail(name)
                if tail not in _COLLECTIVE_NAMES:
                    continue
                if _lax_receiver(name):
                    continue
                if tail in stampers:
                    continue    # self-stamping funnel (collectives.py)
                if has_stamp:
                    continue    # this function stamps the ledger itself
                findings.append(Finding(
                    code="MXT100", path=mod.relpath, line=call.lineno,
                    message=f"collective issue site {name!r} in "
                            f"{fn.name!r} does not stamp the flight "
                            f"recorder",
                    hint="wrap the issue point in flight_recorder."
                         "collective(op, shape=..., dtype=...) (see "
                         "parallel/collectives.py _combine_with_seam), "
                         "call a self-stamping funnel, or carry a "
                         "reasoned `# mxtpu: noqa[MXT100]` naming "
                         "where the stamp lives — an unstamped issue "
                         "desyncs the per-rank ledger the hang-blame "
                         "merge aligns by",
                    scope=mod.qualname(call),
                    key=f"unstamped:{tail}",
                    col=call.col_offset))
        return findings
