"""MXT040: fault-seam names must exist in the fault registry.

``mxnet_tpu/fault.py``'s ``SEAMS`` tuple is the registry; a chaos test
or CI script arming ``some.seam`` that fault.py never checks silently
tests nothing (``_parse_spec`` warns and skips unknown entries — a
drifted seam name turns a chaos lane green without exercising the
failure path).  Checked sites:

- ``fault.inject("...")`` / ``fault.check("...")`` /
  ``fault.guard("...")`` / ``call_with_retries("...", fn)`` first-arg
  literals in Python sources;
- ``MXNET_FAULT_SPEC`` values — monkeypatch/env-dict/assignment string
  literals in Python, and ``MXNET_FAULT_SPEC=...`` assignments in
  ``ci/*.sh`` / ``*.yml`` (scanned textually).
"""
from __future__ import annotations

import ast
import re

from ..astutil import call_name
from ..core import Finding, Pass, register

_SEAM_CALLS = {"inject", "check", "guard", "call_with_retries"}
_FAULT_MODULES = {"fault", "_fault"}
_SPEC_SH_RE = re.compile(r"MXNET_FAULT_SPEC=[\"']?([^\"'\s]+)")


def _fault_receivers(tree):
    """Local names bound to the fault module in this file — ``fault``/
    ``_fault`` plus any import alias (``from mxnet_tpu import fault as
    flt``, ``import mxnet_tpu.fault as mf``), so an aliased
    ``flt.inject("drifted.seam")`` cannot evade MXT040."""
    recv = set(_FAULT_MODULES)
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.asname and a.name.rsplit(".", 1)[-1] in \
                        _FAULT_MODULES:
                    recv.add(a.asname)
        elif isinstance(node, ast.ImportFrom):
            for a in node.names:
                if a.asname and a.name in _FAULT_MODULES:
                    recv.add(a.asname)
    return recv


def _spec_seams(spec):
    """Seam names from a ``seam:mode[:...]`` comma-separated spec."""
    out = []
    for entry in (spec or "").split(","):
        entry = entry.strip()
        if entry and ":" in entry:
            out.append(entry.split(":", 1)[0])
    return out


@register
class FaultSeamIntegrity(Pass):
    name = "fault-seam-integrity"
    codes = {"MXT040": "unknown fault-seam name"}

    def _finding(self, path, line, seam, ctx, scope="<module>"):
        known = ", ".join(sorted(ctx.repo.fault_seams))
        return Finding(
            code="MXT040", path=path, line=line,
            message=f"fault seam {seam!r} is not in fault.SEAMS",
            hint=f"a drifted seam name arms nothing and the chaos lane "
                 f"goes green without testing the failure path; known "
                 f"seams: {known}",
            scope=scope, key=f"seam:{seam}")

    def run(self, ctx, mod):
        seams = ctx.repo.fault_seams
        if not seams:
            return []
        findings = []
        receivers = _fault_receivers(mod.tree)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node) or ""
            tail = name.rsplit(".", 1)[-1]
            if tail in _SEAM_CALLS and node.args and \
                    isinstance(node.args[0], ast.Constant) and \
                    isinstance(node.args[0].value, str):
                # qualified check()/guard()/inject() only count on the
                # fault module or an alias of it (env.get/fault.check
                # both end in 'check'); the receiver's LAST segment
                # matches too (mxnet_tpu.fault.inject)
                if "." in name and tail != "call_with_retries":
                    recv = name.rsplit(".", 1)[0]
                    if recv not in receivers and \
                            recv.rsplit(".", 1)[-1] not in _FAULT_MODULES:
                        continue
                seam = node.args[0].value
                if "." in seam and seam not in seams:
                    findings.append(self._finding(
                        mod.relpath, node.lineno, seam, ctx,
                        mod.qualname(node)))
        # MXNET_FAULT_SPEC string values (setenv / environ[...] / dicts)
        for node in ast.walk(mod.tree):
            specs = _spec_values(node)
            for lineno, spec in specs:
                for seam in _spec_seams(spec):
                    if seam not in seams:
                        findings.append(self._finding(
                            mod.relpath, lineno, seam, ctx,
                            mod.qualname(node)))
        return findings

    def finalize(self, ctx):
        seams = ctx.repo.fault_seams
        if not seams:
            return []
        findings = []
        for ap, rel in ctx.text_files:
            try:
                with open(ap, encoding="utf-8") as f:
                    lines = f.read().splitlines()
            except OSError:
                continue
            for i, line in enumerate(lines, 1):
                for m in _SPEC_SH_RE.finditer(line):
                    for seam in _spec_seams(m.group(1)):
                        if seam not in seams:
                            findings.append(self._finding(rel, i, seam,
                                                          ctx))
        return findings


def _spec_values(node):
    """(line, spec-string) pairs associated with MXNET_FAULT_SPEC in
    this node: setenv()/environ[...] assignments and dict literals."""
    out = []
    if isinstance(node, ast.Call):
        args = list(node.args)
        for i, arg in enumerate(args[:-1]):
            if isinstance(arg, ast.Constant) and \
                    arg.value == "MXNET_FAULT_SPEC" and \
                    isinstance(args[i + 1], ast.Constant) and \
                    isinstance(args[i + 1].value, str):
                out.append((args[i + 1].lineno, args[i + 1].value))
    elif isinstance(node, ast.Assign):
        tgt = node.targets[0]
        if isinstance(tgt, ast.Subscript):
            for sub in ast.walk(tgt.slice):
                if isinstance(sub, ast.Constant) and \
                        sub.value == "MXNET_FAULT_SPEC" and \
                        isinstance(node.value, ast.Constant) and \
                        isinstance(node.value.value, str):
                    out.append((node.value.lineno, node.value.value))
    elif isinstance(node, ast.Dict):
        for k, v in zip(node.keys, node.values):
            if isinstance(k, ast.Constant) and \
                    k.value == "MXNET_FAULT_SPEC" and \
                    isinstance(v, ast.Constant) and \
                    isinstance(v.value, str):
                out.append((v.lineno, v.value))
    return out
