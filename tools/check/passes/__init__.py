"""Builtin passes — importing this package registers them.

To add a pass: create a module here with a :class:`tools.check.core.Pass`
subclass decorated with ``@register``, then import it below.  Codes are
namespaced by decade: MXT00x collective-safety (001-003 general,
005-006 reduce-scatter pairing / bucket keying), MXT01x hot-path,
MXT02x lock/thread, MXT03x env knobs, MXT04x fault seams, MXT05x
serving steady-state (no traces outside AOT warmup), MXT06x sharding
planner (no raw PartitionSpec/NamedSharding outside mxnet_tpu/parallel/),
MXT07x graph-compiler pass contracts (purity + registration closure),
MXT08x live-resharding transfer discipline (plans executed or
explicitly discarded, at uniform SPMD level), MXT09x metric-catalog
closure, MXT10x flight-recorder ledger discipline, MXT11x fleet
dispatch discipline (one funnel, always a deadline, no jax in the
router plane), MXT12x numerical-integrity guard discipline (verdict
collectives call-count-uniform, no mutation bypassing the verdict
gate).
"""
from . import collectives  # noqa: F401
from . import envknobs  # noqa: F401
from . import faultseams  # noqa: F401
from . import fleetdiscipline  # noqa: F401
from . import graphpass  # noqa: F401
from . import guarddiscipline  # noqa: F401
from . import hotpath  # noqa: F401
from . import ledger  # noqa: F401
from . import metrics  # noqa: F401
from . import pairing  # noqa: F401
from . import planner  # noqa: F401
from . import resharding  # noqa: F401
from . import serving  # noqa: F401
from . import threads  # noqa: F401
