"""MXT001-003: SPMD collective-safety.

The contract (earned in PR 2's retry-policy postmortem and PR 5's
check_stop hardening, documented in ``parallel/collectives.py``): every
SPMD peer must issue the SAME collectives in the SAME program order.  A
collective that only some ranks reach — because it sits under a
rank-conditional branch, inside an ``except`` handler, or inside a
unilateral retry wrapper — hangs or desyncs the mesh.

- **MXT001** — collective reached under a rank-conditional branch
  (``jax.process_index()``, ``kv.rank``, ``MXNET_WORKER_ID``-family env
  reads, launcher-rank helpers, or a local flag assigned from one).
  Conditions that are *uniform* across ranks (``process_count()``,
  ``_testing_force``) are exempt: every rank takes the same arm.
- **MXT002** — collective issued inside an ``except`` handler or passed
  to a retry wrapper (``call_with_retries``): a lone re-issue desyncs
  the peers' collective call counts (PR 2: "no unilateral retry of a
  collective").
- **MXT003** — collective call counts differ across the arms of a
  branch whose condition is neither provably uniform nor
  rank-conditional (the equal-call-count contract): if the condition
  CAN diverge across ranks, so do the collective counts.
"""
from __future__ import annotations

import ast

from ..astutil import call_name, dotted, names_in, terminates
from ..core import Finding, Pass, register

# names that issue (or transitively issue) a mesh collective
COLLECTIVE_NAMES = {
    "psum", "pmean", "all_gather", "reduce_scatter", "ppermute",
    "all_to_all", "allreduce_hosts", "allreduce_hosts_quantized",
    "allreduce_hosts_quantized_multi", "allreduce_any", "barrier",
    "_barrier", "sync_global_devices", "_allreduce_bucketed",
}
# kvstore transport methods count when called on something kvstore-ish
_KV_METHODS = {"push", "pull", "pushpull", "row_sparse_pull"}
_KV_RECEIVERS = {"kv", "_kv", "kvstore", "_kvstore", "store", "_store"}

# condition vocabulary
_RANK_MARKERS = {"process_index", "worker_id", "launcher_rank",
                 "_launcher_rank", "rank", "primary", "_primary",
                 "is_primary", "MXNET_WORKER_ID", "DMLC_WORKER_ID",
                 "TPU_WORKER_ID"}
_UNIFORM_MARKERS = {"process_count", "_testing_force", "device_count",
                    "local_device_count", "is_initialized"}
_RETRY_WRAPPERS = {"call_with_retries", "retry", "with_retries"}


def _is_collective(call):
    name = call_name(call)
    if name is None:
        return False
    tail = name.rsplit(".", 1)[-1]
    if tail in COLLECTIVE_NAMES:
        return True
    if tail in _KV_METHODS and isinstance(call.func, ast.Attribute):
        recv = dotted(call.func.value)
        if recv and recv.rsplit(".", 1)[-1] in _KV_RECEIVERS:
            return True
    return False


def _classify(test, rank_locals):
    """'rank' | 'uniform' | 'unknown' for a branch condition."""
    names = names_in(test)
    lowered = {n.lower() for n in _RANK_MARKERS}
    if names & lowered or names & _RANK_MARKERS or \
            names & {n.lower() for n in rank_locals}:
        return "rank"
    if names & _UNIFORM_MARKERS:
        return "uniform"
    return "unknown"


def _rank_locals(fn):
    """Names assigned from a rank-valued expression inside ``fn``
    (``primary = jax.process_index() == 0`` taints ``primary``)."""
    tainted = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and \
                _classify(node.value, tainted) == "rank":
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    tainted.add(tgt.id)
    return tainted


def _walk_same_scope(node):
    """ast.walk that does NOT descend into nested function/lambda
    definitions — defining a closure issues nothing; its body is
    analyzed when (if) it runs, as its own scope."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        for child in ast.iter_child_nodes(n):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            stack.append(child)


def _collectives_in(stmts):
    out = []
    for stmt in stmts:
        for sub in _walk_same_scope(stmt):
            if isinstance(sub, ast.Call) and _is_collective(sub):
                out.append(sub)
    return out


@register
class CollectiveSafety(Pass):
    name = "collective-safety"
    codes = {
        "MXT001": "collective under a rank-conditional branch",
        "MXT002": "collective inside except handler / retry wrapper",
        "MXT003": "collective call-count imbalance across branch arms",
    }

    def run(self, ctx, mod):
        findings = []
        tree = mod.tree

        def emit(code, node, msg, hint, key):
            findings.append(Finding(
                code=code, path=mod.relpath, line=node.lineno,
                message=msg, hint=hint, scope=mod.qualname(node), key=key,
                col=getattr(node, "col_offset", 0)))

        def scan_block(stmts, rank_depth, except_depth, rank_locals):
            """Walk statements tracking rank-conditional and except
            nesting; also apply guard-style taint (a rank-conditional
            early return makes the REST of the block rank-conditional)."""
            guard_tainted = rank_depth
            for stmt in stmts:
                self._scan_stmt(stmt, guard_tainted, except_depth,
                                rank_locals, emit, scan_block)
                if isinstance(stmt, ast.If) and \
                        _classify(stmt.test, rank_locals) == "rank" and \
                        terminates(stmt.body) and not stmt.orelse:
                    guard_tainted += 1

        scan_block(tree.body, 0, 0, set())
        for fn in ast.walk(tree):
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scan_block(fn.body, 0, 0, _rank_locals(fn))
        return findings

    def _scan_stmt(self, stmt, rank_depth, except_depth, rank_locals,
                   emit, scan_block):
        # direct collective calls at this nesting level
        own_subtrees = []
        if isinstance(stmt, ast.If):
            cls = _classify(stmt.test, rank_locals)
            arm_rank = rank_depth + (1 if cls == "rank" else 0)
            scan_block(stmt.body, arm_rank, except_depth, rank_locals)
            scan_block(stmt.orelse, arm_rank, except_depth, rank_locals)
            if cls == "unknown":
                n_body = len(_collectives_in(stmt.body))
                n_else = len(_collectives_in(stmt.orelse))
                if n_body != n_else and max(n_body, n_else) > 0:
                    emit("MXT003", stmt,
                         f"collective call count differs across branch "
                         f"arms ({n_body} vs {n_else}) under a condition "
                         f"not provably uniform across ranks",
                         "every SPMD peer must issue the same collectives "
                         "in the same order; hoist the collective out of "
                         "the branch or derive the condition from "
                         "rank-uniform state (see "
                         "parallel/collectives.py docstring)",
                         key=f"if-imbalance:{n_body}v{n_else}")
            # collective IN the test expression itself
            for sub in ast.walk(stmt.test):
                if isinstance(sub, ast.Call) and _is_collective(sub):
                    self._emit_ctx(sub, rank_depth, except_depth, emit)
            return
        if isinstance(stmt, ast.Try):
            scan_block(stmt.body, rank_depth, except_depth, rank_locals)
            for h in stmt.handlers:
                scan_block(h.body, rank_depth, except_depth + 1,
                           rank_locals)
            scan_block(stmt.orelse, rank_depth, except_depth, rank_locals)
            scan_block(stmt.finalbody, rank_depth, except_depth,
                       rank_locals)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
            scan_block(stmt.body, rank_depth, except_depth, rank_locals)
            scan_block(stmt.orelse, rank_depth, except_depth, rank_locals)
            own_subtrees = [stmt.iter] if hasattr(stmt, "iter") else \
                [stmt.test]
        elif isinstance(stmt, ast.With):
            scan_block(stmt.body, rank_depth, except_depth, rank_locals)
            own_subtrees = [i.context_expr for i in stmt.items]
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
            return  # nested scopes are scanned as their own functions
        else:
            own_subtrees = [stmt]
        for sub_tree in own_subtrees:
            for sub in _walk_same_scope(sub_tree):
                if isinstance(sub, ast.IfExp):
                    # ternaries branch exactly like If statements
                    cls = _classify(sub.test, rank_locals)
                    arm_calls = [c for arm in (sub.body, sub.orelse)
                                 for c in _collectives_in([arm])]
                    if cls == "rank":
                        for c in arm_calls:
                            self._emit_ctx(c, rank_depth + 1,
                                           except_depth, emit)
                    elif cls == "unknown" and arm_calls:
                        n_body = len(_collectives_in([sub.body]))
                        n_else = len(_collectives_in([sub.orelse]))
                        if n_body != n_else:
                            emit("MXT003", sub,
                                 f"collective call count differs across "
                                 f"ternary arms ({n_body} vs {n_else}) "
                                 f"under a condition not provably "
                                 f"uniform across ranks",
                                 "every SPMD peer must issue the same "
                                 "collectives in the same order (see "
                                 "parallel/collectives.py docstring)",
                                 key=f"if-imbalance:{n_body}v{n_else}")
                elif isinstance(sub, ast.Call):
                    if _is_collective(sub):
                        self._emit_ctx(sub, rank_depth, except_depth, emit)
                    else:
                        name = call_name(sub)
                        tail = (name or "").rsplit(".", 1)[-1]
                        if tail in _RETRY_WRAPPERS:
                            self._check_retry_args(sub, tail, emit)

    def _check_retry_args(self, call, wrapper, emit):
        """MXT002 for a collective handed to a retry wrapper — as a
        direct name OR wrapped in a lambda closing over arguments."""
        hint = ("a unilateral retry re-issues the collective on one "
                "rank only and desyncs SPMD call counts; escalate to a "
                "whole-job restart instead (PR 2 contract)")
        for arg in call.args:
            aname = dotted(arg)
            if aname and aname.rsplit(".", 1)[-1] in COLLECTIVE_NAMES:
                emit("MXT002", call,
                     f"collective {aname!r} passed to retry wrapper "
                     f"{wrapper!r}", hint, key=f"retry:{aname}")
            elif isinstance(arg, ast.Lambda):
                for sub in ast.walk(arg.body):
                    if isinstance(sub, ast.Call) and _is_collective(sub):
                        cname = call_name(sub) or "<collective>"
                        emit("MXT002", sub,
                             f"collective {cname!r} issued from a lambda "
                             f"passed to retry wrapper {wrapper!r}",
                             hint, key=f"retry:lambda:{cname}")

    def _emit_ctx(self, call, rank_depth, except_depth, emit):
        name = call_name(call) or "<collective>"
        if except_depth > 0:
            emit("MXT002", call,
                 f"collective {name!r} issued inside an except handler",
                 "an error path runs on SOME ranks only — peers never "
                 "issue the matching collective and the mesh hangs; "
                 "escalate to a whole-job restart (PR 2 contract)",
                 key=f"except:{name}")
        if rank_depth > 0:
            emit("MXT001", call,
                 f"collective {name!r} reached under a rank-conditional "
                 f"branch",
                 "every SPMD peer must issue it or none may; hoist it "
                 "above the rank branch (uniform process_count() guards "
                 "are fine — see parallel/collectives.py docstring)",
                 key=f"rank-cond:{name}")
