"""MXT120-121: numerical-integrity guard discipline.

ISSUE 20's guard (:mod:`mxnet_tpu.guard`) only works if two structural
invariants hold at every adoption site:

- **MXT120 — mutation bypassing the verdict gate.**  In a *guarded
  scope* (a function that assigns a verdict from ``<guard>.check(...)``),
  every optimizer/parameter mutation (``step`` / ``_update`` /
  ``step_bucket`` / ``updater`` / ``apply_gradients`` and friends) must
  be conditioned — directly or through one level of derivation
  (``act = g.action(verdict)``) — on that verdict.  An unconditional
  mutation next to a computed verdict means the guard observes but no
  longer protects: the anomalous update commits anyway, which is
  exactly the silent failure the skip tier exists to stop.
- **MXT121 — rank-conditional verdict collective.**  ``Guard.check``
  issues the verdict-agreement collective (one ``allreduce_hosts`` of
  the sentinel vector), so calling it under a rank-conditional branch
  (``process_index()``, ``rank``-tainted locals, worker-id env reads)
  breaks the equal-call-count contract the agreement rides on: some
  peers issue the collective, others never do, and the mesh hangs —
  the MXT001 failure mode, surfaced at the guard's own seam.  Stride
  amortization belongs INSIDE ``check`` (``MXNET_GUARD_SYNC_EVERY``,
  call-count-deterministic), never at the call site.

Scope: only functions that actually seed a verdict are analyzed
(MXT120) — the pass adds no noise to the 99% of the repo that never
touches the guard.  Guard receivers are names assigned from
``Guard(...)`` / ``attach(...)`` expressions, or any name/attribute
spelled ``guard``-ish (``g._guard``, ``trainer._guard``, parameter
``guard``).
"""
from __future__ import annotations

import ast

from ..astutil import call_name, names_in
from ..core import Finding, Pass, register
from .pairing import _outermost_functions

# verdict-producing guard methods (the collective + sync live here)
_CHECK_METHODS = {"check", "poll_loss"}
# optimizer/parameter mutators that must sit behind the verdict gate
_MUTATORS = {"step", "plain_step", "orig_step", "amp_step", "_update",
             "update", "step_bucket", "_zero_step_bucket", "updater",
             "apply_gradients"}
# rank-conditional vocabulary (the MXT001 classifier's, minus the
# uniform markers — a process_count() guard is fine)
_RANK_MARKERS = {"process_index", "worker_id", "launcher_rank",
                 "_launcher_rank", "rank", "primary", "_primary",
                 "is_primary", "mxnet_worker_id", "dmlc_worker_id"}


def _guardish(name):
    """A dotted name that denotes a guard by spelling (``guard``,
    ``self._guard``, ``trainer._guard``...)."""
    return name is not None and "guard" in name.rsplit(".", 1)[-1].lower()


def _receivers(fn):
    """Names bound to a guard inside ``fn``: guard-ish parameters plus
    assignment targets whose value mentions ``Guard(...)``/``attach``
    or an already guard-ish name."""
    recv = set()
    args = fn.args
    for a in (args.posonlyargs + args.args + args.kwonlyargs):
        if _guardish(a.arg):
            recv.add(a.arg)
    for node in ast.walk(fn):
        if not isinstance(node, ast.Assign):
            continue
        src = node.value
        hit = any(isinstance(sub, ast.Call) and
                  (call_name(sub) or "").rsplit(".", 1)[-1]
                  in {"Guard", "attach"}
                  for sub in ast.walk(src))
        if not hit:
            hit = any(_guardish(n) for n in names_in(src))
        if hit:
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    recv.add(tgt.id)
    return recv


def _is_check_call(call, recv):
    """``<receiver>.check(...)`` / ``<receiver>.poll_loss(...)`` where
    the receiver is a known guard name or guard-ish attribute chain."""
    name = call_name(call)
    if name is None or "." not in name:
        return False
    head, _, tail = name.rpartition(".")
    if tail not in _CHECK_METHODS:
        return False
    base = head.split(".", 1)[0]
    return base in recv or _guardish(head)


def _tainted_names(fn, recv):
    """The verdict taint set: assignment targets of guard check calls,
    closed one derivation level (``act = g.action(verdict)``)."""
    tainted = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Call) and \
                _is_check_call(node.value, recv):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    tainted.add(tgt.id)
    if not tainted:
        return tainted
    changed = True
    while changed:
        changed = False
        for node in ast.walk(fn):
            if not isinstance(node, ast.Assign):
                continue
            if {n for n in names_in(node.value)} & \
                    {t.lower() for t in tainted}:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name) and tgt.id not in tainted:
                        tainted.add(tgt.id)
                        changed = True
    return tainted


@register
class GuardDiscipline(Pass):
    name = "guard-discipline"
    codes = {
        "MXT120": "optimizer/param mutation bypasses the guard verdict "
                  "gate",
        "MXT121": "guard verdict collective under a rank-conditional "
                  "branch",
    }

    def run(self, ctx, mod):
        findings = []
        # outermost functions only: a closure (guard.attach's guarded
        # step) analyzes WITH its parent, which holds the receiver
        # bindings and the taint
        for fn in _outermost_functions(mod.tree):
            recv = _receivers(fn)
            # MXT121 needs no seed: ANY guard check call under a rank
            # branch is a hang, assigned or not
            self._scan_rank(fn, recv, mod, findings)
            tainted = _tainted_names(fn, recv)
            if not tainted:
                continue
            lowered = {t.lower() for t in tainted}
            self._scan_gate(fn, fn.body, recv, lowered, False, mod,
                            findings)
        return findings

    # -- MXT121: rank-conditional verdict collectives -------------------
    def _scan_rank(self, fn, recv, mod, findings):
        def walk(stmts, rank_depth):
            for stmt in stmts:
                local = rank_depth
                if isinstance(stmt, (ast.If, ast.While)) and \
                        names_in(stmt.test) & _RANK_MARKERS:
                    local = rank_depth + 1
                for expr in self._own_exprs(stmt):
                    for sub in ast.walk(expr):
                        if isinstance(sub, ast.Call) and \
                                _is_check_call(sub, recv) and \
                                rank_depth > 0:
                            findings.append(Finding(
                                code="MXT121", path=mod.relpath,
                                line=sub.lineno,
                                message="guard verdict check issued "
                                        "under a rank-conditional branch "
                                        "— the agreement collective "
                                        "inside it desyncs SPMD call "
                                        "counts",
                                hint="call Guard.check unconditionally "
                                     "at the step boundary on every "
                                     "rank; amortize with "
                                     "MXNET_GUARD_SYNC_EVERY instead of "
                                     "a rank branch",
                                scope=mod.qualname(sub),
                                key=f"rank-check:{call_name(sub)}",
                                col=sub.col_offset))
                for field in ("body", "orelse", "finalbody"):
                    inner = getattr(stmt, field, None)
                    if inner and isinstance(inner, list):
                        walk(inner, local)
                for h in getattr(stmt, "handlers", ()) or ():
                    walk(h.body, local)

        walk(fn.body, 0)

    # -- MXT120: ungated mutations in a seeded scope --------------------
    def _scan_gate(self, fn, stmts, recv, tainted_l, gated, mod,
                   findings):
        for stmt in stmts:
            local_gated = gated
            if isinstance(stmt, (ast.If, ast.While)) and \
                    names_in(stmt.test) & tainted_l:
                local_gated = True
            for expr in self._own_exprs(stmt):
                for sub in ast.walk(expr):
                    if not isinstance(sub, ast.Call):
                        continue
                    name = call_name(sub)
                    tail = (name or "").rsplit(".", 1)[-1]
                    if tail not in _MUTATORS:
                        continue
                    if gated:
                        continue
                    findings.append(Finding(
                        code="MXT120", path=mod.relpath,
                        line=sub.lineno,
                        message=f"mutator {name!r} called in a guarded "
                                f"scope without consulting the verdict "
                                f"— the anomalous update commits "
                                f"anyway",
                        hint="gate the mutation on the agreed verdict "
                             "(if verdict == 'ok': ... / the "
                             "Guard.action ladder), or carry a "
                             "reasoned `# mxtpu: noqa[MXT120]` if this "
                             "mutation is deliberately verdict-free",
                        scope=mod.qualname(sub),
                        key=f"ungated:{tail}",
                        col=sub.col_offset))
            for field in ("body", "orelse", "finalbody"):
                inner = getattr(stmt, field, None)
                if inner and isinstance(inner, list):
                    self._scan_gate(fn, inner, recv, tainted_l,
                                    local_gated, mod, findings)
            for h in getattr(stmt, "handlers", ()) or ():
                self._scan_gate(fn, h.body, recv, tainted_l,
                                local_gated, mod, findings)

    @staticmethod
    def _own_exprs(stmt):
        """The statement's OWN expression subtrees — excludes nested
        statement blocks (walked separately with their gate state) and
        nested function/class definitions (their bodies are their own
        scopes)."""
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return []
        if isinstance(stmt, (ast.If, ast.While)):
            return [stmt.test]
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            return [stmt.iter]
        if isinstance(stmt, ast.With):
            return [i.context_expr for i in stmt.items]
        if isinstance(stmt, ast.Try):
            return []
        return [stmt]
