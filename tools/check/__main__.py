"""CLI for mxtpu-check.

Exit status: 0 = clean (or baselined/waived only), 1 = new findings,
2 = usage/parse errors.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

from .core import Baseline, all_passes, run_checks

DEFAULT_ROOTS = ("mxnet_tpu", "tests", "ci")


def _find_repo_root(start):
    """Walk up from ``start`` to the directory holding mxnet_tpu/env.py."""
    cur = os.path.abspath(start)
    while True:
        if os.path.exists(os.path.join(cur, "mxnet_tpu", "env.py")):
            return cur
        parent = os.path.dirname(cur)
        if parent == cur:
            return os.path.abspath(start)
        cur = parent


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m tools.check",
        description="repo-specific static analysis (SPMD collective "
                    "safety, hot-path host syncs, lock/thread hygiene, "
                    "env-knob registry, fault-seam integrity)")
    ap.add_argument("roots", nargs="*", default=list(DEFAULT_ROOTS),
                    help="files/directories to scan (default: %(default)s)")
    ap.add_argument("--root", default=None,
                    help="repo root (default: auto-detected from cwd)")
    ap.add_argument("--baseline", default=None,
                    help="baseline file (default: tools/check/"
                         "baseline.json under the repo root)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline (report everything)")
    ap.add_argument("--update-baseline", action="store_true",
                    help="write current findings into the baseline "
                         "(reasons marked TODO — fill them in)")
    ap.add_argument("--select", default=None,
                    help="comma-separated pass names or MXT codes to run")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--list-passes", action="store_true")
    args = ap.parse_args(argv)

    if args.list_passes:
        for name, cls in sorted(all_passes().items()):
            print(f"{name}:")
            for code, title in sorted(cls.codes.items()):
                print(f"  {code}  {title}")
        return 0

    repo_root = args.root or _find_repo_root(os.getcwd())
    baseline_path = args.baseline or os.path.join(
        repo_root, "tools", "check", "baseline.json")
    select = set(args.select.split(",")) if args.select else None

    findings, errors = run_checks(repo_root, args.roots, select=select)

    baseline = Baseline() if args.no_baseline else Baseline.load(
        baseline_path)
    new, suppressed, unused = baseline.filter(findings)

    if args.update_baseline:
        dropped = 0
        if select is None:
            # a full run proves these entries match nothing — prune
            # them so a stale entry can never mask a future finding
            unused_ids = {id(e) for e in unused}
            baseline.entries = [e for e in baseline.entries
                                if id(e) not in unused_ids]
            dropped = len(unused_ids)
        for f in new:
            baseline.entries.append(Baseline.entry_for(
                f, "TODO: justify or fix"))
        baseline.save(baseline_path)
        print(f"baseline: +{len(new)} entries, -{dropped} stale -> "
              f"{baseline_path}")
        for e in errors:
            print(f"error: {e}", file=sys.stderr)
        return 1 if errors else 0

    if select is None and not args.no_baseline:
        # stale entries fail the gate: a fixed finding must be deleted
        # from the baseline or it would suppress the NEXT real finding
        # with the same code+path+scope+key (--update-baseline prunes)
        for e in unused:
            errors.append(
                f"baseline entry never matched — delete it or fix the "
                f"regression: {e.get('code')} {e.get('path')} "
                f"{e.get('scope')} {e.get('key')}")

    for e in errors:
        print(f"error: {e}", file=sys.stderr)

    if args.format == "json":
        print(json.dumps({
            "findings": [vars(f) for f in new],
            "suppressed": len(suppressed),
            "errors": errors}, indent=2))
    else:
        for f in new:
            print(f.render())
        tail = f"{len(new)} finding(s)"
        if suppressed:
            tail += f", {len(suppressed)} baselined"
        print(("FAIL: " if new else "OK: ") + tail)
    return 1 if new or errors else 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:
        # `... | head` closed stdout; swallow the write at shutdown too
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        sys.exit(0)
