"""mxtpu-check: repo-specific static analysis for mxnet-tpu.

``python -m tools.check [roots...]`` runs every registered pass (see
``tools/check/passes/``) over the given roots (default:
``mxnet_tpu tests ci``) and fails on any finding that is neither waived
inline (``# mxtpu: noqa[MXTnnn] <reason>``) nor carried in
``tools/check/baseline.json``.  README "Static analysis" documents the
pass catalog.
"""
from .core import (Baseline, CheckContext, Finding, ParsedModule, Pass,
                   all_passes, register, run_checks)

__all__ = ["Baseline", "CheckContext", "Finding", "ParsedModule", "Pass",
           "all_passes", "register", "run_checks", "main"]


def main(argv=None):
    from .__main__ import main as _main

    return _main(argv)
