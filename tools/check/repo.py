"""Repo model: the ground-truth registries the passes check against.

Everything here is extracted from the repo's own source of truth at
check time — ``mxnet_tpu/env.py`` for the knob registry, ``mxnet_tpu/
fault.py`` for the seam list, ``README.md`` for the documented knob
tables — so the checker can never drift from the code it polices.
"""
from __future__ import annotations

import ast
import os
import re

_MXNET_NAME = re.compile(r"^MXNET_[A-Z0-9_]+$")
_METRIC_NAME = re.compile(r"^mxnet_[a-z0-9_]+$")


def expand_metric_token(tok):
    """Expand one catalog-cell token into full family names: drop a
    TRAILING ``{labels}`` group, expand inner ``{a,b}`` alternation,
    imply the ``mxnet_`` prefix.  Tokens that expand to nothing metric-
    shaped (prose in backticks) yield []."""
    tok = re.sub(r"\{[^{}]*\}$", "", tok.strip())

    def expand(s):
        m = re.search(r"\{([^{}]*)\}", s)
        if not m:
            return [s]
        out = []
        for alt in m.group(1).split(","):
            out.extend(expand(s[:m.start()] + alt.strip() + s[m.end():]))
        return out

    names = []
    for name in expand(tok):
        if not name.startswith("mxnet_"):
            name = "mxnet_" + name
        if _METRIC_NAME.match(name):
            names.append(name)
    return names


def flight_aliases(tree):
    """``(module_aliases, fn_aliases)`` — names the module binds to the
    flight-recorder module / its ``collective`` stamper (top-level AND
    function-local imports: the repo's lazy-import idiom)."""
    mod_aliases, fn_aliases = set(), set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            for alias in node.names:
                if alias.name == "flight_recorder":
                    mod_aliases.add(alias.asname or alias.name)
                elif mod.endswith("flight_recorder") \
                        and alias.name == "collective":
                    fn_aliases.add(alias.asname or alias.name)
        elif isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name.endswith("flight_recorder"):
                    mod_aliases.add(alias.asname or alias.name)
    return mod_aliases, fn_aliases


def is_stamp_call(call, mod_aliases, fn_aliases):
    """Is this Call a flight-recorder collective stamp
    (``_flight.collective(...)`` / aliased forms)?"""
    name = None
    node = call.func
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        name = ".".join(reversed(parts))
    if not name:
        return False
    if "." not in name:
        return name in fn_aliases
    recv, tail = name.rsplit(".", 1)
    return tail == "collective" and (
        recv in mod_aliases or recv.endswith("flight_recorder"))


class RepoModel:
    """Lazily-extracted registries for the repo rooted at ``root``."""

    def __init__(self, root):
        self.root = root
        self._env = None
        self._seams = None
        self._readme = None
        self._metrics = None
        self._stampers = None

    # -- env knob registry (mxnet_tpu/env.py) ------------------------------
    def _load_env(self):
        if self._env is not None:
            return
        wired, subsumed, declared, anchors = set(), set(), set(), {}
        path = os.path.join(self.root, "mxnet_tpu", "env.py")
        if os.path.exists(path):
            with open(path, encoding="utf-8") as f:
                src = f.read()
            tree = ast.parse(src, filename=path)
            body = tree.body
            # skip the module docstring: a knob must be *registered*
            # (describe()/_SUBSUMED/a read), not merely name-dropped
            if body and isinstance(body[0], ast.Expr) and \
                    isinstance(body[0].value, ast.Constant):
                body = body[1:]
            for node in body:
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Constant) and \
                            isinstance(sub.value, str) and \
                            _MXNET_NAME.match(sub.value):
                        declared.add(sub.value)
                        anchors.setdefault(sub.value, sub.lineno)
            # wired = names in describe()'s `wired` table; subsumed =
            # _SUBSUMED keys:
            for node in ast.walk(tree):
                if isinstance(node, ast.Assign) and any(
                        isinstance(t, ast.Name) and t.id == "_SUBSUMED"
                        for t in node.targets):
                    if isinstance(node.value, ast.Dict):
                        for k in node.value.keys:
                            if isinstance(k, ast.Constant) and \
                                    isinstance(k.value, str):
                                subsumed.add(k.value)
                if isinstance(node, ast.FunctionDef) and \
                        node.name == "describe":
                    for sub in ast.walk(node):
                        if isinstance(sub, ast.Tuple) and sub.elts and \
                                isinstance(sub.elts[0], ast.Constant) and \
                                isinstance(sub.elts[0].value, str) and \
                                _MXNET_NAME.match(str(sub.elts[0].value)):
                            wired.add(sub.elts[0].value)
                            anchors[sub.elts[0].value] = sub.lineno
        self._env = {"wired": wired, "subsumed": subsumed,
                     "declared": declared | wired | subsumed,
                     "anchors": anchors,
                     "path": os.path.relpath(path, self.root).replace(
                         os.sep, "/")}

    @property
    def env_registry(self):
        """``{"wired", "subsumed", "declared", "anchors", "path"}`` —
        ``declared`` is every exact MXNET_* name registered in env.py."""
        self._load_env()
        return self._env

    # -- fault seams (mxnet_tpu/fault.py) ----------------------------------
    @property
    def fault_seams(self):
        if self._seams is None:
            self._seams = set()
            path = os.path.join(self.root, "mxnet_tpu", "fault.py")
            if os.path.exists(path):
                with open(path, encoding="utf-8") as f:
                    tree = ast.parse(f.read(), filename=path)
                for node in ast.walk(tree):
                    if isinstance(node, ast.Assign) and any(
                            isinstance(t, ast.Name) and t.id == "SEAMS"
                            for t in node.targets):
                        for elt in ast.walk(node.value):
                            if isinstance(elt, ast.Constant) and \
                                    isinstance(elt.value, str):
                                self._seams.add(elt.value)
        return self._seams

    # -- flight-recorder self-stamping collective funnels ------------------
    @property
    def collective_stampers(self):
        """Module-level functions in ``mxnet_tpu/parallel/collectives.py``
        that stamp the flight recorder themselves — directly, or by
        delegating to another function in the same module that does
        (transitive fixed point).  A call to one of these is a
        compliant ledger entry by construction, so the
        ``ledger-discipline`` pass never asks its caller for a second
        stamp.  Extracted from the source at check time, so the pass
        can never drift from the funnels it trusts."""
        if self._stampers is None:
            self._stampers = set()
            path = os.path.join(self.root, "mxnet_tpu", "parallel",
                                "collectives.py")
            if os.path.exists(path):
                with open(path, encoding="utf-8") as f:
                    tree = ast.parse(f.read(), filename=path)
                mod_al, fn_al = flight_aliases(tree)
                funcs = {}
                for node in tree.body:
                    if isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        funcs[node.name] = node
                direct, calls_of = set(), {}
                for name, fn in funcs.items():
                    called = set()
                    for sub in ast.walk(fn):
                        if not isinstance(sub, ast.Call):
                            continue
                        if is_stamp_call(sub, mod_al, fn_al):
                            direct.add(name)
                        elif isinstance(sub.func, ast.Name):
                            called.add(sub.func.id)
                    calls_of[name] = called
                stamped = set(direct)
                changed = True
                while changed:
                    changed = False
                    for name, called in calls_of.items():
                        if name not in stamped and called & stamped:
                            stamped.add(name)
                            changed = True
                self._stampers = stamped
        return self._stampers

    # -- README metric catalog ---------------------------------------------
    @property
    def readme_metrics(self):
        """``{"names": {family: line}, "path", "has_catalog"}`` — the
        families documented in README's "Metric catalog" table (the
        markdown table following the ``**Metric catalog**`` marker).

        Row format contract (the metric-registry pass's parse target):
        backticked tokens in the FIRST column are family names, the
        ``mxnet_`` prefix implied; an inner ``{a,b}`` group expands by
        alternation (``kvstore_{push,pull}_bytes_total``); a trailing
        ``{label,...}`` group annotates labels and is dropped.  With no
        marker present ``has_catalog`` is False and the pass is inert
        (mini fixture repos)."""
        if self._metrics is None:
            names, has_catalog = {}, False
            path = os.path.join(self.root, "README.md")
            if os.path.exists(path):
                with open(path, encoding="utf-8") as f:
                    lines = f.read().splitlines()
                in_table = False
                seen_marker = False
                for lineno, line in enumerate(lines, 1):
                    if "**Metric catalog**" in line:
                        seen_marker, has_catalog = True, True
                        continue
                    stripped = line.lstrip()
                    if seen_marker and not in_table:
                        if stripped.startswith("|"):
                            in_table = True
                        continue
                    if in_table:
                        if not stripped.startswith("|"):
                            in_table = seen_marker = False
                            continue
                        cells = stripped.split("|")
                        first = cells[1] if len(cells) > 1 else ""
                        for tok in re.findall(r"`([^`]+)`", first):
                            for name in expand_metric_token(tok):
                                names.setdefault(name, lineno)
            self._metrics = {"names": names, "path": "README.md",
                             "has_catalog": has_catalog}
        return self._metrics

    # -- README knob mentions ----------------------------------------------
    @property
    def readme_knobs(self):
        if self._readme is None:
            self._readme = set()
            path = os.path.join(self.root, "README.md")
            if os.path.exists(path):
                with open(path, encoding="utf-8") as f:
                    self._readme = set(
                        re.findall(r"MXNET_[A-Z0-9_]+", f.read()))
        return self._readme
