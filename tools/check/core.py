"""mxtpu-check core: findings, noqa waivers, baseline, and the runner.

The repo's SPMD/concurrency/hot-path contracts (CHANGES.md PRs 1-5) are
machine-enforced here instead of living only in reviewers' memories.  A
*pass* is an AST visitor over one parsed module (plus an optional
cross-file ``finalize``); it emits :class:`Finding` objects with a stable
``MXTnnn`` code.  The gate is "zero NEW findings":

- inline waiver: ``# mxtpu: noqa[MXT001] <reason>`` on the flagged line
  (or on a comment line directly above it);
- baseline file (``tools/check/baseline.json``): known findings carried
  with a written reason, matched by (code, path, scope, key) so line
  drift does not invalidate them.

Run ``python -m tools.check mxnet_tpu tests ci`` from the repo root.
"""
from __future__ import annotations

import ast
import dataclasses
import json
import os
import re

_NOQA_RE = re.compile(r"mxtpu:\s*noqa\[([A-Z0-9,\s]+)\]")


@dataclasses.dataclass
class Finding:
    """One rule violation.

    ``scope`` is the enclosing function qualname (``<module>`` at top
    level) and ``key`` a line-number-free detail string; together with
    ``code`` and ``path`` they form the baseline fingerprint, so a
    baselined finding survives unrelated edits that shift line numbers.
    ``col`` distinguishes two violations on the SAME line (both are
    real) from one AST node reported twice; it is deliberately NOT part
    of the baseline fingerprint.
    """

    code: str
    path: str          # repo-relative, '/'-separated
    line: int
    message: str
    hint: str = ""
    scope: str = "<module>"
    key: str = ""
    col: int = 0

    @property
    def fingerprint(self):
        return (self.code, self.path, self.scope, self.key or self.message)

    def render(self):
        out = f"{self.path}:{self.line}: {self.code} {self.message}"
        if self.hint:
            out += f"\n    hint: {self.hint}"
        return out


class ParsedModule:
    """A source file parsed once and shared by every pass."""

    def __init__(self, abspath, relpath, source):
        self.abspath = abspath
        self.relpath = relpath
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=relpath)
        self._qualnames = None

    def qualname(self, node):
        """Enclosing function qualname for a node (``<module>`` if none)."""
        if self._qualnames is None:
            self._qualnames = {}
            self._walk_scopes(self.tree, [])
        best = "<module>"
        best_span = None
        for (lo, hi), name in self._qualnames.items():
            if lo <= node.lineno <= hi:
                if best_span is None or (lo >= best_span[0]
                                         and hi <= best_span[1]):
                    best, best_span = name, (lo, hi)
        return best

    def _walk_scopes(self, node, stack):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                qual = ".".join(stack + [child.name])
                if not isinstance(child, ast.ClassDef):
                    hi = max((n.lineno for n in ast.walk(child)
                              if hasattr(n, "lineno")), default=child.lineno)
                    self._qualnames[(child.lineno, hi)] = qual
                self._walk_scopes(child, stack + [child.name])
            else:
                self._walk_scopes(child, stack)

    def noqa_codes(self, line):
        """Waiver codes covering ``line``: an inline ``# mxtpu: noqa[...]``
        on the line itself or a standalone comment directly above."""
        codes = set()
        for ln in (line, line - 1):
            if 1 <= ln <= len(self.lines):
                text = self.lines[ln - 1]
                if ln != line and not text.lstrip().startswith("#"):
                    continue
                m = _NOQA_RE.search(text)
                if m:
                    codes.update(c.strip() for c in m.group(1).split(","))
        return codes


# -- pass registry ---------------------------------------------------------
_REGISTRY = {}


def register(cls):
    """Class decorator: adds a pass to the registry keyed on its name."""
    _REGISTRY[cls.name] = cls
    return cls


def all_passes():
    from . import passes  # noqa: F401  (imports register the builtins)

    return dict(_REGISTRY)


class Pass:
    """Base pass.  Subclasses set ``name``, ``codes`` (dict code->title)
    and implement ``run(ctx, mod) -> list[Finding]``; cross-file passes
    may also implement ``finalize(ctx) -> list[Finding]``."""

    name = ""
    codes: dict = {}

    def run(self, ctx, mod):  # pragma: no cover - interface
        return []

    def finalize(self, ctx):
        return []


# -- baseline --------------------------------------------------------------
class Baseline:
    """Multiset of known findings, each carried with a reason.

    File format: ``{"findings": [{"code", "path", "scope", "key",
    "reason"}, ...]}``.  Matching consumes entries, so N baselined
    findings suppress at most N occurrences.
    """

    def __init__(self, entries=None):
        self.entries = list(entries or [])

    @classmethod
    def load(cls, path):
        if not path or not os.path.exists(path):
            return cls()
        with open(path) as f:
            data = json.load(f)
        return cls(data.get("findings", []))

    def save(self, path):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as f:
            json.dump({"findings": self.entries}, f, indent=2,
                      sort_keys=False)
            f.write("\n")

    def filter(self, findings):
        """Split findings into (new, suppressed, unused); consumes
        matches.  ``unused`` is the baseline entries that matched
        nothing — a fixed finding must be DELETED from the baseline,
        or its stale entry would suppress the next real finding with
        the same fingerprint."""
        pool = {}
        for e in self.entries:
            fp = (e.get("code"), e.get("path"), e.get("scope"),
                  e.get("key"))
            pool[fp] = pool.get(fp, 0) + 1
        new, suppressed = [], []
        for f in findings:
            if pool.get(f.fingerprint, 0) > 0:
                pool[f.fingerprint] -= 1
                suppressed.append(f)
            else:
                new.append(f)
        unused = []
        for e in reversed(self.entries):
            fp = (e.get("code"), e.get("path"), e.get("scope"),
                  e.get("key"))
            if pool.get(fp, 0) > 0:
                pool[fp] -= 1
                unused.append(e)
        unused.reverse()
        return new, suppressed, unused

    @staticmethod
    def entry_for(finding, reason):
        code, path, scope, key = finding.fingerprint
        return {"code": code, "path": path, "scope": scope, "key": key,
                "reason": reason}


# -- runner ----------------------------------------------------------------
_SKIP_DIRS = {"__pycache__", ".git", ".pytest_cache", "node_modules",
              ".ipynb_checkpoints"}


def iter_source_files(roots, repo_root, suffixes=(".py",)):
    """Yield (abspath, relpath) under ``roots`` (files or directories),
    sorted for deterministic output."""
    seen = set()
    out = []
    for root in roots:
        root = os.path.join(repo_root, root) if not os.path.isabs(root) \
            else root
        if os.path.isfile(root):
            cand = [root]
        else:
            cand = []
            for dirpath, dirnames, filenames in os.walk(root):
                dirnames[:] = sorted(d for d in dirnames
                                     if d not in _SKIP_DIRS)
                for fn in sorted(filenames):
                    cand.append(os.path.join(dirpath, fn))
        for path in cand:
            if not path.endswith(tuple(suffixes)):
                continue
            ap = os.path.abspath(path)
            if ap in seen:
                continue
            seen.add(ap)
            rel = os.path.relpath(ap, repo_root).replace(os.sep, "/")
            out.append((ap, rel))
    return out


class CheckContext:
    """Shared state for one checker run: repo model + scanned roots."""

    def __init__(self, repo_root, roots):
        from .repo import RepoModel

        self.repo_root = os.path.abspath(repo_root)
        self.roots = list(roots)
        self.repo = RepoModel(self.repo_root)
        self.modules = []          # ParsedModule list, filled by run_checks
        self.text_files = []       # (abspath, relpath) for .sh/.yml scans


def run_checks(repo_root, roots, select=None):
    """Run every registered pass over ``roots``.

    Returns ``(findings, errors)`` — findings already filtered through
    inline noqa waivers (waived ones dropped), NOT yet through the
    baseline.  ``errors`` are files that failed to parse (reported, never
    silently skipped).
    """
    ctx = CheckContext(repo_root, roots)
    findings, errors = [], []
    for root in ctx.roots:
        rp = root if os.path.isabs(root) else \
            os.path.join(ctx.repo_root, root)
        if not os.path.exists(rp):
            # a typo'd/renamed root must FAIL the gate, not silently
            # scan nothing and report the tree clean
            errors.append(f"{root}: no such file or directory "
                          f"(root not scanned)")
    passes = [cls() for name, cls in sorted(all_passes().items())
              if select is None or name in select
              or any(c in select for c in cls.codes)]
    mods = {}
    for ap, rel in iter_source_files(roots, ctx.repo_root):
        try:
            with open(ap, encoding="utf-8") as f:
                src = f.read()
            mods[rel] = ParsedModule(ap, rel, src)
        except (SyntaxError, UnicodeDecodeError) as e:
            errors.append(f"{rel}: parse error: {e}")
    ctx.modules = list(mods.values())
    ctx.text_files = iter_source_files(roots, ctx.repo_root,
                                       suffixes=(".sh", ".yml", ".yaml"))
    for p in passes:
        for mod in ctx.modules:
            findings.extend(p.run(ctx, mod))
        findings.extend(p.finalize(ctx))
    text_lines = {}
    for ap, rel in ctx.text_files:
        try:
            with open(ap, encoding="utf-8") as f:
                text_lines[rel] = f.read().splitlines()
        except OSError:
            pass
    kept, seen = [], set()
    for f in findings:
        mod = mods.get(f.path)
        if mod is not None and f.code in mod.noqa_codes(f.line):
            continue
        if mod is None and f.path in text_lines:
            # non-Python findings (MXT040 in .sh/.yml) honor the same
            # inline waiver: on the flagged line or the line above
            lines = text_lines[f.path]
            window = [lines[i] for i in (f.line - 1, f.line - 2)
                      if 0 <= i < len(lines)]
            if any(f.code in (m.group(1) if (m := _NOQA_RE.search(t))
                              else "") for t in window):
                continue
        # a ternary collective is reachable both via its IfExp handler
        # and the generic call walk — report each NODE once (col keeps
        # two distinct same-line violations distinct)
        fp = (f.code, f.path, f.line, f.col, f.scope, f.key)
        if fp in seen:
            continue
        seen.add(fp)
        kept.append(f)
    kept.sort(key=lambda f: (f.path, f.line, f.code))
    return kept, errors
