"""Small AST helpers shared by the passes."""
from __future__ import annotations

import ast


def dotted(node):
    """Best-effort dotted-name string for a Name/Attribute chain
    (``jax.process_index`` -> "jax.process_index"); None otherwise."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(call):
    """Dotted name of a Call's callee (None for computed callees)."""
    return dotted(call.func) if isinstance(call, ast.Call) else None


def names_in(node):
    """All bare identifiers + attribute tails in a subtree (lowercased),
    plus exact string constants — the soup rank/uniform classifiers
    match against."""
    out = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name):
            out.add(sub.id.lower())
        elif isinstance(sub, ast.Attribute):
            out.add(sub.attr.lower())
        elif isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            out.add(sub.value)
    return out


def terminates(stmts):
    """True when a statement list always leaves the enclosing block
    (ends in return/raise/continue/break)."""
    return bool(stmts) and isinstance(
        stmts[-1], (ast.Return, ast.Raise, ast.Continue, ast.Break))


def iter_functions(tree):
    """Yield every (Async)FunctionDef in the module."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node
