#!/usr/bin/env python
"""im2rec: image folder -> RecordIO dataset (reference: tools/im2rec.py).

Usage:
    python tools/im2rec.py PREFIX ROOT [--resize N] [--quality Q]
                           [--img-fmt .jpg|.npy] [--list-only]

Creates PREFIX.rec (+ PREFIX.idx, PREFIX.lst).  Class labels are assigned
per subdirectory of ROOT, sorted (the reference's folder convention).
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np  # noqa: E402

from mxnet_tpu import recordio  # noqa: E402


def build_list(root):
    classes = sorted(d for d in os.listdir(root)
                     if os.path.isdir(os.path.join(root, d)))
    items = []
    for label, cls in enumerate(classes):
        for fname in sorted(os.listdir(os.path.join(root, cls))):
            if fname.lower().endswith((".jpg", ".jpeg", ".png", ".npy")):
                items.append((os.path.join(root, cls, fname), float(label)))
    return items, classes


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("prefix")
    ap.add_argument("root")
    ap.add_argument("--resize", type=int, default=0)
    ap.add_argument("--quality", type=int, default=95)
    ap.add_argument("--img-fmt", default=".jpg")
    ap.add_argument("--list-only", action="store_true")
    args = ap.parse_args()

    items, classes = build_list(args.root)
    with open(args.prefix + ".lst", "w") as f:
        for i, (path, label) in enumerate(items):
            f.write(f"{i}\t{label}\t{path}\n")
    print(f"{len(items)} images, {len(classes)} classes")
    if args.list_only:
        return

    writer = recordio.MXIndexedRecordIO(args.prefix + ".idx",
                                        args.prefix + ".rec", "w")
    for i, (path, label) in enumerate(items):
        if path.endswith(".npy"):
            img = np.load(path)
        else:
            from PIL import Image

            im = Image.open(path).convert("RGB")
            if args.resize:
                w, h = im.size
                scale = args.resize / min(w, h)
                im = im.resize((int(w * scale), int(h * scale)),
                               Image.BILINEAR)
            img = np.asarray(im)
        header = recordio.IRHeader(0, label, i, 0)
        packed = recordio.pack_img(header, img, quality=args.quality,
                                   img_fmt=args.img_fmt)
        writer.write_idx(i, packed)
    writer.close()
    print(f"wrote {args.prefix}.rec")


if __name__ == "__main__":
    main()
