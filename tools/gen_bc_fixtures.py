"""Generate model backwards-compatibility fixtures (reference:
model_backwards_compatibility_check/ — SURVEY.md §5 nightly tier).

Run ONCE per format version; the committed fixtures pin today's .params /
symbol-JSON wire formats so future framework versions must keep loading
them (tests/nightly/test_model_backwards_compat.py enforces it)."""
import json
import os
import sys

import numpy as np

import jax

jax.config.update("jax_platforms", "cpu")

import mxnet_tpu as mx
from mxnet_tpu import gluon, nd

OUT = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))), "tests", "nightly", "bc_fixtures", "v1")


def build_mlp():
    net = gluon.nn.HybridSequential(prefix="bcmlp_")
    with net.name_scope():
        net.add(gluon.nn.Dense(8, activation="relu"),
                gluon.nn.Dense(3))
    return net, np.linspace(-1, 1, 2 * 5).reshape(2, 5).astype("f")


def build_conv():
    net = gluon.nn.HybridSequential(prefix="bcconv_")
    with net.name_scope():
        net.add(gluon.nn.Conv2D(4, 3, padding=1, activation="relu"),
                gluon.nn.BatchNorm(),
                gluon.nn.Flatten(),
                gluon.nn.Dense(2))
    return net, np.linspace(-1, 1, 1 * 3 * 8 * 8).reshape(
        1, 3, 8, 8).astype("f")


def main():
    os.makedirs(OUT, exist_ok=True)
    mx.random.seed(0)   # reproducible: re-running regenerates bitwise
    manifest = {}
    for name, (net, x) in {"mlp": build_mlp(), "conv": build_conv()}.items():
        net.initialize(mx.init.Xavier(rnd_type="gaussian", magnitude=2))
        net.hybridize()
        y = net(nd.array(x))
        base = os.path.join(OUT, name)
        # deploy format: symbol JSON + Module-checkpoint params
        net.export(base, 0, nd.array(x))
        # gluon format: save_parameters
        net.save_parameters(base + ".gluon.params")
        np.save(base + ".input.npy", x)
        np.save(base + ".expected.npy", y.asnumpy())
        manifest[name] = {"input": name + ".input.npy",
                          "expected": name + ".expected.npy"}
    with open(os.path.join(OUT, "manifest.json"), "w") as f:
        json.dump({"format_version": 1, "models": manifest}, f, indent=1)
    print("fixtures written to", OUT)


if __name__ == "__main__":
    main()
