#!/usr/bin/env python
"""Multi-process job launcher (reference: tools/launch.py + the dmlc-core
local tracker — SURVEY.md §3.3 "Launcher": spawns the process group and sets
the bootstrap env contract each process reads).

TPU-native shape: there are no separate server/scheduler roles — every
process is an SPMD worker that calls ``mxnet_tpu.parallel.distributed.init()``
(≙ Postoffice::Start), which reads the env this launcher sets:

    MXNET_COORDINATOR_ADDRESS   host:port of process 0 (jax.distributed)
    MXNET_NUM_WORKERS           process count
    MXNET_WORKER_ID             this process's id

The reference's ``DMLC_*`` names are also set for script compatibility.

Usage (mirrors the reference CLI)::

    python tools/launch.py -n 4 [--launcher local] [--env K=V ...] \
        python train.py --your-args

``--launcher local`` (default) runs all workers on this machine — exactly
how the reference CI ran its dist kvstore tests without a cluster
(integrationtest_ubuntu_cpu_dist_kvstore).  ``ssh``/``mpi`` launchers are
out of scope for a single-pod TPU job: multi-host pods are provisioned by
the TPU runtime which starts one process per host with the coordinator env
already present.
"""
from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import time


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__,
                                 formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("-n", "--num-workers", type=int, required=True,
                    help="number of worker processes")
    ap.add_argument("-s", "--num-servers", type=int, default=0,
                    help="accepted for reference CLI compatibility; the TPU "
                         "build has no server role (ignored)")
    ap.add_argument("--launcher", default="local", choices=["local"],
                    help="process launcher (local = this machine)")
    ap.add_argument("--env", action="append", default=[],
                    help="extra K=V env entries for every worker")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0,
                    help="coordinator port (0 = pick a free one)")
    ap.add_argument("command", nargs=argparse.REMAINDER,
                    help="the worker command")
    args = ap.parse_args(argv)
    if not args.command:
        ap.error("missing worker command")
    cmd = args.command
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]

    port = args.port or _free_port()
    coord = f"{args.host}:{port}"
    procs = []
    try:
        for wid in range(args.num_workers):
            env = dict(os.environ)
            env.update({
                "MXNET_COORDINATOR_ADDRESS": coord,
                "MXNET_NUM_WORKERS": str(args.num_workers),
                "MXNET_WORKER_ID": str(wid),
                # reference env contract (§4.4 bootstrap)
                "DMLC_PS_ROOT_URI": args.host,
                "DMLC_PS_ROOT_PORT": str(port),
                "DMLC_NUM_WORKER": str(args.num_workers),
                "DMLC_WORKER_ID": str(wid),
                "DMLC_ROLE": "worker",
            })
            for kv in args.env:
                k, _, v = kv.partition("=")
                env[k] = v
            procs.append(subprocess.Popen(cmd, env=env))
        # poll the whole group: one worker dying early must tear the job
        # down immediately (a sequential wait() would hang forever on the
        # survivors blocked in collectives)
        rc = 0
        running = list(procs)
        while running:
            for p in running[:]:
                r = p.poll()
                if r is not None:
                    running.remove(p)
                    rc = rc or r
            if rc:
                break
            time.sleep(0.2)
        return rc
    finally:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        deadline = time.time() + 10
        for p in procs:
            if p.poll() is None:
                try:
                    p.wait(timeout=max(0.1, deadline - time.time()))
                except subprocess.TimeoutExpired:
                    p.kill()


if __name__ == "__main__":
    sys.exit(main())
