#!/usr/bin/env python
"""Allreduce / pushpull bandwidth harness.

Reference: ``tools/bandwidth/measure.py`` (the kvstore bandwidth tool the
BASELINE.md binding table cites: "KVStore allreduce BW" GB/s vs message
size).  TPU-native: the reduction is one jit'd ``psum`` over the device
mesh (what ``dist_tpu_sync`` pushpull lowers to), so the measured number is
the ICI/DCN collective bandwidth GSPMD achieves at each message size.

Usage::

    python tools/bandwidth_measure.py [--sizes-mb 1,4,16,64,256]
                                      [--iters 10] [--json]

On the virtual CPU mesh (JAX_PLATFORMS=cpu +
--xla_force_host_platform_device_count=8) the numbers are memcpy-bound —
useful for validating the harness, not the interconnect.

Reported metric: algorithmic bus bandwidth ``2*(n-1)/n * bytes / time``
(the standard allreduce accounting, comparable to nccl-tests / the
reference's tool).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

# runnable from anywhere: the repo root (= mxnet_tpu's parent) sits next
# to tools/
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def measure_allreduce(size_bytes, iters=10, warmup=2, mesh=None):
    """Time a psum of `size_bytes` over all devices; returns (seconds/iter,
    bus_bandwidth_GB/s)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map

    if mesh is None:
        mesh = Mesh(np.array(jax.devices()), ("dp",))
    n = mesh.size
    elems = max(size_bytes // 4, 1)
    # every device contributes its own `elems`-float vector and receives
    # the elementwise sum — the canonical allreduce setup (nccl-tests
    # semantics).  shard_map + lax.psum guarantees a true all-reduce in
    # the HLO (a reshard-to-replicated would compile to all-gather and
    # overstate bandwidth ~2x).
    x = jax.device_put(
        jnp.ones((n, elems), dtype=jnp.float32),
        NamedSharding(mesh, P("dp", None)))

    @jax.jit
    def allreduce(v):
        def f(local):
            return jax.lax.psum(local, "dp")

        return shard_map(f, mesh=mesh, in_specs=P("dp", None),
                         out_specs=P("dp", None))(v)

    out = allreduce(x)
    out.block_until_ready()
    if n > 1 and "all-reduce" not in \
            allreduce.lower(x).compile().as_text():
        raise RuntimeError("collective did not lower to all-reduce")
    for _ in range(warmup):
        allreduce(x).block_until_ready()
    t0 = time.perf_counter()
    for _ in range(iters):
        out = allreduce(x)
    out.block_until_ready()
    dt = (time.perf_counter() - t0) / iters
    bus_bytes = 2.0 * (n - 1) / n * elems * 4
    return dt, bus_bytes / dt / 1e9


def measure_pushpull(size_bytes, iters=10, warmup=2):
    """End-to-end kvstore pushpull (includes frontend overhead): GB/s of
    gradient bytes synchronized per second.

    Note: in a single-process single-worker session the dist kvstore's
    pushpull degenerates to a local buffer update (as in the reference), so
    this number reflects frontend/dispatch overhead; the interconnect
    figure is ``measure_allreduce`` / a real multi-process launch."""
    import mxnet_tpu as mx

    kv = mx.kv.create("dist_tpu_sync")
    elems = max(size_bytes // 4, 1)
    g = mx.nd.ones((elems,))
    kv.init(0, g)
    out = mx.nd.zeros((elems,))
    for _ in range(warmup):
        kv.push(0, g)
        kv.pull(0, out)
        out.wait_to_read()
    t0 = time.perf_counter()
    for _ in range(iters):
        kv.push(0, g)
        kv.pull(0, out)
    out.wait_to_read()
    dt = (time.perf_counter() - t0) / iters
    return dt, elems * 4 / dt / 1e9


# per-chip ICI bandwidth (GB/s, all links) by device kind substring —
# public figures, for the vs_peak column only
_ICI_PEAK = (("v5 lite", 400.0), ("v5e", 400.0), ("v5p", 1200.0),
             ("v4", 1200.0), ("v3", 700.0))


def _ici_peak():
    import jax

    try:
        kind = jax.devices()[0].device_kind.lower()
    except Exception:
        return None
    for sub, peak in _ICI_PEAK:
        if sub in kind:
            return peak
    return None


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--sizes-mb", default="1,4,16,64",
                    help="comma-separated message sizes in MiB")
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--mode", choices=["allreduce", "pushpull", "both"],
                    default="both")
    ap.add_argument("--json", action="store_true",
                    help="one JSON line per measurement")
    args = ap.parse_args(argv)

    import os

    import jax

    if os.environ.get("JAX_PLATFORMS"):
        # the axon TPU-tunnel sitecustomize force-selects its platform via
        # jax.config; honor an explicit JAX_PLATFORMS request (cpu mesh)
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    n = len(jax.devices())
    peak = _ici_peak()
    results = []
    for mb in [float(s) for s in args.sizes_mb.split(",")]:
        size = int(mb * 1024 * 1024)
        row = {"size_mb": mb, "devices": n}
        if args.mode in ("allreduce", "both"):
            dt, bw = measure_allreduce(size, iters=args.iters)
            row["allreduce_gbps"] = round(bw, 3)
            row["allreduce_ms"] = round(dt * 1e3, 3)
            if peak:
                row["vs_ici_peak"] = round(bw / peak, 4)
        if args.mode in ("pushpull", "both"):
            dt, bw = measure_pushpull(size, iters=args.iters)
            row["pushpull_gbps"] = round(bw, 3)
        results.append(row)
        if args.json:
            print(json.dumps(row), flush=True)
        else:
            print("  ".join(f"{k}={v}" for k, v in row.items()), flush=True)
    return results


if __name__ == "__main__":
    main()
