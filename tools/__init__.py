# Makes the repo's tooling importable as a package (`python -m tools.check`).
