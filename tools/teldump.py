"""teldump: pretty-print, merge, and diff telemetry snapshots.

The operator-side half of the runtime introspection plane (ISSUE 14).
A snapshot is the JSON ``telemetry.snapshot()`` produces — from the
``/snapshot`` HTTP route, a watchdog stall dump's ``telemetry`` field,
a ``rank<N>.json`` aggregation file, or a merged ``/agg`` document.

Usage::

    python -m tools.teldump show snap.json [--metrics PREFIX]
    python -m tools.teldump diff before.json after.json
    python -m tools.teldump agg  /path/to/agg_dir   # offline re-merge
    python -m tools.teldump blame /path/to/agg_dir  # black-box blame

``show`` prints the metric families (counters/gauges as values,
histograms as count/sum/mean), the step-phase breakdown, the goodput
ledger, and the compile summary.  ``diff`` prints counter/gauge deltas
and step-rate change between two snapshots of the SAME process (the
"what changed across this incident" view).  ``agg`` re-runs the pure
:func:`mxnet_tpu.telemetry_agg.merge_snapshots` over a directory of
rank files and prints the per-rank summary + straggler skew — the
same merge the live aggregator serves at ``/agg``, reproducible
offline because the merge is deterministic.  ``blame`` merges the
``blackbox.rank<N>.json`` flight-recorder dumps each rank wrote on its
abnormal exit (:func:`mxnet_tpu.telemetry_agg.merge_blackboxes` —
pure, so the offline re-merge bit-matches any live one) and prints the
verdict: which collective the mesh wedged in, at which per-rank
sequence number, and which rank fell out of program order.
"""
from __future__ import annotations

import argparse
import json
import sys


def _load(path):
    with open(path) as f:
        doc = json.load(f)
    # accept a watchdog stall dump transparently
    if "telemetry" in doc and "metrics" not in doc:
        return doc["telemetry"]
    return doc


def _fmt_labels(labels):
    labels = {k: v for k, v in (labels or {}).items()}
    if not labels:
        return ""
    return "{" + ",".join(f"{k}={v}" for k, v in sorted(labels.items())) \
        + "}"


def _sample_rows(name, fam):
    rows = []
    for s in fam.get("samples", ()):
        lab = _fmt_labels(s.get("labels"))
        if "buckets" in s:
            count = s.get("count", 0)
            total = s.get("sum", 0.0)
            mean = (total / count) if count else 0.0
            rows.append((name + lab,
                         f"count={count} sum={total:.6g} "
                         f"mean={mean:.6g}"))
        else:
            rows.append((name + lab, f"{s.get('value', 0):.6g}"))
    return rows


def cmd_show(args):
    snap = _load(args.snapshot)
    rows = []
    for name in sorted(snap.get("metrics") or {}):
        if args.metrics and not name.startswith(args.metrics):
            continue
        rows.extend(_sample_rows(name, snap["metrics"][name]))
    width = max((len(r[0]) for r in rows), default=20)
    print(f"# {args.snapshot}: {len(rows)} series")
    for key, val in rows:
        print(f"  {key:<{width}}  {val}")
    phases = snap.get("step_phase_totals") or {}
    if phases:
        total = sum(phases.values()) or 1.0
        print(f"\n# step phases ({len(snap.get('steps') or [])} steps "
              "in ring)")
        for name, dt in sorted(phases.items(), key=lambda p: -p[1]):
            print(f"  {name:<20} {dt:10.4f}s  {100 * dt / total:5.1f}%")
    good = snap.get("goodput") or {}
    if good.get("tracked_s"):
        print("\n# goodput")
        for bucket, dt in sorted((good.get("buckets") or {}).items(),
                                 key=lambda p: -p[1]):
            print(f"  {bucket:<20} {dt:10.4f}s")
        ratio = good.get("productive_ratio")
        if ratio is not None:
            print(f"  {'ratio':<20} {ratio:10.4f}")
    comp = snap.get("compile") or {}
    if comp:
        print(f"\n# compile: {comp.get('count', 0)} events, "
              f"{comp.get('total_s', 0):.3f}s total")
    return 0


def _scalars(snap):
    out = {}
    for name, fam in (snap.get("metrics") or {}).items():
        for s in fam.get("samples", ()):
            key = name + _fmt_labels(s.get("labels"))
            if "buckets" in s:
                out[key + ":count"] = s.get("count", 0)
                out[key + ":sum"] = s.get("sum", 0.0)
            else:
                out[key] = s.get("value", 0)
    return out


def cmd_diff(args):
    a, b = _load(args.a), _load(args.b)
    sa, sb = _scalars(a), _scalars(b)
    keys = sorted(set(sa) | set(sb))
    width = max((len(k) for k in keys), default=20)
    n = 0
    for key in keys:
        va, vb = sa.get(key, 0), sb.get(key, 0)
        if va == vb:
            continue
        n += 1
        print(f"  {key:<{width}}  {va:.6g} -> {vb:.6g} "
              f"({vb - va:+.6g})")
    dt = (b.get("time") or 0) - (a.get("time") or 0)
    print(f"# {n} series changed over {dt:.1f}s "
          f"({args.a} -> {args.b})")
    return 0


def cmd_agg(args):
    from mxnet_tpu import telemetry_agg

    snaps = telemetry_agg.read_dir(args.directory)
    if not snaps:
        print(f"no rank*.json files in {args.directory}",
              file=sys.stderr)
        return 1
    doc = telemetry_agg.merge_snapshots(snaps)
    print(f"# merged ranks: {doc['ranks']}")
    for rank in doc["ranks"]:
        pr = doc["per_rank"][rank]
        print(f"  rank {rank}: steps={pr['steps']} "
              f"last_step={pr['last_step']} "
              f"compiles={pr['compile_count']} "
              f"goodput={pr['goodput_ratio']}")
    skew = doc["skew"]
    if skew["step"] is not None:
        print(f"# phase skew at step {skew['step']} (max - min across "
              "ranks)")
        for phase, dt in sorted(skew["phases"].items(),
                                key=lambda p: -p[1]):
            print(f"  {phase:<20} {dt * 1e3:8.3f}ms")
    else:
        print("# no common step across ranks yet (no skew)")
    if args.out:
        with open(args.out, "w") as f:
            json.dump(doc, f)
        print(f"# merged document written to {args.out}")
    return 0


def cmd_blame(args):
    from mxnet_tpu import telemetry_agg

    boxes = telemetry_agg.read_blackboxes(args.directory)
    if not boxes:
        print(f"no blackbox.rank*.json files in {args.directory}",
              file=sys.stderr)
        return 1
    doc = telemetry_agg.merge_blackboxes(boxes)
    print(f"# black boxes merged: ranks {doc['ranks']}")
    for rank in doc["ranks"]:
        pr = doc["per_rank"][rank]
        state = "exited" if pr["last_exited"] else (
            "FAILED" if pr["last_error"] else "ENTERED-NOT-EXITED")
        print(f"  rank {rank}: reason={pr['reason']} "
              f"seq=[{pr['first_seq']}..{pr['last_seq']}] "
              f"last={pr['last_tag']} ({state})")
    v = doc["verdict"]
    print(f"# verdict: {v['kind'].upper()}")
    if v.get("seq") is not None:
        print(f"  seq    {v['seq']}")
    if v.get("step") is not None:
        print(f"  step   {v['step']}")
    if v.get("tag"):
        print(f"  tag    {v['tag']}  (digest {v.get('digest')})")
    if v.get("ranks"):
        print(f"  ranks  {v['ranks']}")
    print(f"  {v['detail']}")
    if args.out:
        with open(args.out, "w") as f:
            json.dump(doc, f, sort_keys=True)
        print(f"# merged blame report written to {args.out}")
    return 0


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="python -m tools.teldump",
        description="pretty-print / diff / merge telemetry snapshots")
    sub = parser.add_subparsers(dest="cmd", required=True)
    p_show = sub.add_parser("show", help="print one snapshot")
    p_show.add_argument("snapshot")
    p_show.add_argument("--metrics", default="",
                        help="only families with this prefix")
    p_show.set_defaults(fn=cmd_show)
    p_diff = sub.add_parser("diff", help="counter/gauge deltas a -> b")
    p_diff.add_argument("a")
    p_diff.add_argument("b")
    p_diff.set_defaults(fn=cmd_diff)
    p_agg = sub.add_parser(
        "agg", help="offline re-merge of an aggregation directory")
    p_agg.add_argument("directory")
    p_agg.add_argument("--out", default="",
                       help="also write the merged JSON here")
    p_agg.set_defaults(fn=cmd_agg)
    p_blame = sub.add_parser(
        "blame", help="merge black-box rings and print the hang/desync "
                      "blame verdict")
    p_blame.add_argument("directory")
    p_blame.add_argument("--out", default="",
                         help="also write the merged blame report here")
    p_blame.set_defaults(fn=cmd_blame)
    args = parser.parse_args(argv)
    try:
        return args.fn(args)
    except BrokenPipeError:   # | head must not traceback
        return 0


if __name__ == "__main__":
    sys.exit(main())
