"""Headline benchmarks on the real chip: ResNet-50 / BERT-base / Llama-proxy
fused bf16 training steps.

Prints ONE JSON line:
  {"metric", "value", "unit", "vs_baseline", "mfu", "extra": {...}}

- metric/value: ResNet-50 train throughput (img/s/chip), bf16 mixed precision
  (BASELINE config #1).  vs_baseline divides by the reference's recalled V100
  fp32 number (350 img/s mid-range; BASELINE.md marks it unverified) — the
  honest figure is "mfu": achieved training FLOP/s over the chip's bf16 peak.
- extra: BERT-base pretrain samples/s + Llama-proxy tokens/s (BASELINE
  configs #2/#5), each with its own MFU, through the flash-attention kernel.
"""
from __future__ import annotations

import json
import time

import numpy as np

BASELINE_IMG_S_PER_CHIP = 350.0  # recalled V100 fp32, BASELINE.md config #1

# ResNet-50 @224: ~3.9 GFLOPs forward per image, x3 for fwd+bwd
RESNET50_TRAIN_FLOPS_PER_IMG = 11.7e9

# bf16 peak FLOP/s per chip by device_kind substring
_PEAKS = (("v5 lite", 197e12), ("v5e", 197e12), ("v5p", 459e12),
          ("v6", 918e12), ("v4", 275e12), ("v3", 123e12), ("v2", 45e12))


def chip_peak_flops():
    import jax

    kind = jax.devices()[0].device_kind.lower()
    for sub, peak in _PEAKS:
        if sub in kind:
            return peak
    return 197e12 if jax.default_backend() == "tpu" else None


def _time_steps(step_fn, args, warmup, iters):
    import jax

    # stage inputs on-device once: measured steps must not pay host->device
    # transfer (the training loop overlaps it via the prefetching input
    # pipeline; over the axon tunnel it would dominate entirely)
    args = tuple(jax.device_put(a) for a in args)
    for _ in range(warmup):
        np.asarray(step_fn(*args))
    t0 = time.perf_counter()
    loss = None
    for _ in range(iters):
        loss = step_fn(*args)
    # fetch the value: over the axon tunnel block_until_ready() acks the
    # enqueue, not the completion — only a D2H read proves the work ran
    lv = float(np.asarray(loss))
    dt = time.perf_counter() - t0
    assert np.isfinite(lv), "non-finite bench loss"
    return dt


def _matmul_params(step):
    """Approximate '6N' N: matmul-participating parameter count (embedding
    lookups excluded — they are gathers, not MXU FLOPs)."""
    return sum(int(np.prod(v.shape)) for k, v in step.params.items()
               if "embedding" not in k and len(v.shape) >= 2)


def bench_resnet50(on_tpu):
    # NHWC: XLA:TPU tiles channel-last convs onto the MXU without the
    # internal relayout transposes logical-NCHW convs pay (override with
    # MXNET_BENCH_LAYOUT=NCHW to A/B the layouts on the chip).  The
    # headline must survive any config failing, so fall back per config.
    #
    # Escalation sweep (PERF_NOTES: if plain NHWC lands under MFU 0.35):
    # on TPU the bench ALSO measures batch-512+remat and the
    # space-to-depth stem unattended, reports each in extras, and
    # headlines the best — one wedged-tunnel round must not leave the
    # escalation unmeasured again.  MXNET_BENCH_SWEEP=0 pins the single
    # default config.
    import os
    import sys

    layout = os.environ.get("MXNET_BENCH_LAYOUT", "NHWC")
    sweep = os.environ.get("MXNET_BENCH_SWEEP", "1") != "0"
    # MXNET_BENCH_FORCE_SWEEP=1: exercise the TPU-gated sweep branches on
    # CPU (VERDICT Weak #1: first chip contact must not be the first time
    # this code runs).  CPU keeps the default batch — the point is the
    # code path, not the number.
    force = os.environ.get("MXNET_BENCH_FORCE_SWEEP", "0") == "1"
    configs = [("base", layout, None, False, "conv7")]
    if (on_tpu or force) and sweep and layout == "NHWC":
        sweep_batch = 512 if on_tpu else None
        configs += [("b512_remat", layout, sweep_batch, True, "conv7"),
                    ("b512_remat_s2d", layout, sweep_batch, True, "s2d")]
    results, errors = {}, {}
    last_exc = None
    for name, lay, batch, remat, stem in configs:
        try:
            results[name] = _bench_resnet50_layout(
                on_tpu, lay, batch=batch, remat=remat, stem=stem)
        except Exception as e:
            print(f"bench: resnet config {name} failed ({e!r})",
                  file=sys.stderr)
            errors[name] = repr(e)[:200]
            last_exc = e
    if not results and layout != "NCHW":
        # every NHWC config failed: one last try on the old layout
        print("bench: all NHWC configs failed; falling back to NCHW",
              file=sys.stderr)
        results["base_nchw"] = _bench_resnet50_layout(on_tpu, "NCHW")
    if not results:
        raise last_exc  # surfaced as the parseable error JSON in main()
    best = max(results, key=lambda k: results[k][0])
    extras = {k: {"value": round(v[0], 2), "mfu": round(v[1], 4)}
              for k, v in results.items()}
    # failed configs stay visible, distinguishable from never-swept ones
    for k, err in errors.items():
        extras[k] = {"error": err}
    return results[best] + ({"configs": extras, "best": best},)


def _bench_resnet50_layout(on_tpu, layout, batch=None, remat=False,
                           stem="conv7"):
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu.parallel.data_parallel import TrainStep

    batch = batch or (256 if on_tpu else 16)
    size = 224 if on_tpu else 64
    net = vision.resnet50_v1(layout=layout, stem=stem)
    net.initialize(ctx=mx.current_context())
    dshape = (1, size, size, 3) if layout == "NHWC" else (1, 3, size, size)
    net(mx.nd.zeros(dshape))  # settle deferred param shapes

    def loss_fn(logits, labels):
        import jax
        import jax.numpy as jnp

        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.take_along_axis(logp, labels[:, None], axis=-1)

    step = TrainStep(net, loss_fn, optimizer="sgd",
                     optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
                     train_mode=True, dtype="bfloat16", remat=remat)

    xshape = (batch, size, size, 3) if layout == "NHWC" else \
        (batch, 3, size, size)
    x = np.random.uniform(-1, 1, xshape).astype("float32")
    y = np.random.randint(0, 1000, (batch,)).astype("int32")
    iters = 20 if on_tpu else 3
    dt = _time_steps(step, (x, y), warmup=2, iters=iters)
    img_s = batch * iters / dt
    peak = chip_peak_flops()
    mfu = (img_s * RESNET50_TRAIN_FLOPS_PER_IMG / peak) if peak else 0.0
    return img_s, mfu


def bench_bert(on_tpu):
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo.language import bert
    from mxnet_tpu.parallel.data_parallel import TrainStep

    batch, seq = (64, 128) if on_tpu else (2, 32)
    net = bert.BertForPretraining(
        bert.BertConfig() if on_tpu else
        bert.BertConfig(vocab_size=1024, hidden_size=128, num_layers=2,
                        num_heads=2, intermediate_size=256, max_position=64))
    net.initialize(ctx=mx.current_context())
    ids0 = mx.nd.zeros((1, seq), dtype="int32")
    net(ids0)

    def loss_fn(outs, labels):
        import jax
        import jax.numpy as jnp

        mlm, nsp = outs
        mlm_labels, nsp_labels = labels[:, :-1], labels[:, -1]
        logp = jax.nn.log_softmax(mlm, axis=-1)
        mlm_l = -jnp.take_along_axis(logp, mlm_labels[..., None], axis=-1)
        nsp_logp = jax.nn.log_softmax(nsp, axis=-1)
        nsp_l = -jnp.take_along_axis(nsp_logp, nsp_labels[:, None], axis=-1)
        return jnp.mean(mlm_l) + jnp.mean(nsp_l)

    step = TrainStep(net, loss_fn, optimizer="adam",
                     optimizer_params={"learning_rate": 1e-4},
                     train_mode=True, dtype="bfloat16")
    vocab = net._cfg.vocab_size
    ids = np.random.randint(0, vocab, (batch, seq)).astype("int32")
    labels = np.concatenate(
        [np.random.randint(0, vocab, (batch, seq)),
         np.random.randint(0, 2, (batch, 1))], axis=1).astype("int32")
    iters = 20 if on_tpu else 2
    dt = _time_steps(step, (ids, labels), warmup=2, iters=iters)
    samples_s = batch * iters / dt
    peak = chip_peak_flops()
    flops_per_sample = 6.0 * _matmul_params(step) * seq
    mfu = (samples_s * flops_per_sample / peak) if peak else 0.0
    return samples_s, mfu


def bench_llama(on_tpu):
    """On TPU (and unless MXNET_BENCH_SWEEP=0) this sweeps flash-attention
    block sizes — the tune PERF_NOTES flagged as needing a chip run — and
    headlines the best (block config reported in extras)."""
    import os
    import sys

    sweep = os.environ.get("MXNET_BENCH_SWEEP", "1") != "0"
    force = os.environ.get("MXNET_BENCH_FORCE_SWEEP", "0") == "1"
    explicit = ("MXNET_FLASH_BLOCK_Q" in os.environ
                or "MXNET_FLASH_BLOCK_KV" in os.environ)
    if explicit:
        # user pinned a config: measure EXACTLY that, touch nothing
        bq = int(os.environ.get("MXNET_FLASH_BLOCK_Q", 128))
        bkv = int(os.environ.get("MXNET_FLASH_BLOCK_KV", 128))
        tok, mfu = _bench_llama_once(on_tpu)
        key = f"q{bq}_kv{bkv}"
        return tok, mfu, {"flash_blocks": {key: {
            "value": round(tok, 2), "mfu": round(mfu, 4)}}, "best": key}
    grid = [(128, 128)]
    if (on_tpu or force) and sweep:
        grid += [(256, 256), (256, 512), (512, 512)]
    results, errors = {}, {}
    last_exc = None
    for bq, bkv in grid:
        os.environ["MXNET_FLASH_BLOCK_Q"] = str(bq)
        os.environ["MXNET_FLASH_BLOCK_KV"] = str(bkv)
        try:
            results[f"q{bq}_kv{bkv}"] = _bench_llama_once(on_tpu)
        except Exception as e:
            print(f"bench: llama blocks ({bq},{bkv}) failed ({e!r})",
                  file=sys.stderr)
            errors[f"q{bq}_kv{bkv}"] = repr(e)[:200]
            last_exc = e
    os.environ.pop("MXNET_FLASH_BLOCK_Q", None)
    os.environ.pop("MXNET_FLASH_BLOCK_KV", None)
    if not results:
        raise last_exc  # the real root cause reaches BENCH.json's error
    best = max(results, key=lambda k: results[k][0])
    tok, mfu = results[best]
    cfgs = {k: {"value": round(v[0], 2), "mfu": round(v[1], 4)}
            for k, v in results.items()}
    # failed configs stay visible, distinguishable from never-swept ones
    for k, err in errors.items():
        cfgs[k] = {"error": err}
    return tok, mfu, {"flash_blocks": cfgs, "best": best}


def _bench_llama_once(on_tpu):
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo.language import llama
    from mxnet_tpu.parallel.data_parallel import TrainStep

    if on_tpu:
        # ~250M-param proxy of the Llama-3 architecture sized for one chip
        cfg = dict(vocab_size=32000, hidden_size=1024, num_layers=16,
                   num_heads=16, num_kv_heads=8, intermediate_size=2816,
                   max_seq_len=1024)
        batch, seq = 8, 1024
    else:
        cfg = dict(vocab_size=512, hidden_size=128, num_layers=2, num_heads=4,
                   num_kv_heads=2, intermediate_size=256, max_seq_len=256)
        batch, seq = 2, 64
    net = llama.LlamaForCausalLM(llama.LlamaConfig(**cfg))
    net.initialize(ctx=mx.current_context())
    net(mx.nd.zeros((1, seq), dtype="int32"))

    def loss_fn(logits, labels):
        import jax
        import jax.numpy as jnp

        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.take_along_axis(logp, labels[..., None], axis=-1)

    step = TrainStep(net, loss_fn, optimizer="adam",
                     optimizer_params={"learning_rate": 3e-4},
                     train_mode=True, dtype="bfloat16")
    ids = np.random.randint(0, cfg["vocab_size"], (batch, seq)).astype("int32")
    labels = np.random.randint(0, cfg["vocab_size"],
                               (batch, seq)).astype("int32")
    iters = 10 if on_tpu else 2
    dt = _time_steps(step, (ids, labels), warmup=2, iters=iters)
    tokens_s = batch * seq * iters / dt
    peak = chip_peak_flops()
    flops_per_token = 6.0 * _matmul_params(step)
    mfu = (tokens_s * flops_per_token / peak) if peak else 0.0
    return tokens_s, mfu


def bench_eager_op_overhead(iters=300, warmup=30):
    """µs/op over a small-op eager loop, jit-cache on vs off (ISSUE 1
    tentpole: the dispatch fast path must show up as a per-op dispatch win,
    not just a cache-counter win).

    The loop is the pathological imperative workload VERDICT r5 flags
    (batch-1 eager CNN inference, minutes over the tunnel): many tiny
    registry-op calls — BatchNorm(inference) / activation / add / softmax —
    where per-call dispatch and per-primitive eager launch, not kernel
    time, dominate.  Returns a dict with us_per_op for both modes, the
    speedup, and the cache stats after the jit-on run.
    """
    import mxnet_tpu as mx
    import numpy as np

    C = 32
    R = np.random.RandomState(0)
    x = mx.nd.array(R.randn(1, C, 8, 8).astype("f"))
    y = mx.nd.array(R.randn(1, C, 8, 8).astype("f"))
    gamma = mx.nd.array(np.ones(C, "f"))
    beta = mx.nd.array(np.zeros(C, "f"))
    rmean = mx.nd.array(np.zeros(C, "f"))
    rvar = mx.nd.array(np.ones(C, "f"))

    def loop(n):
        out = x
        for _ in range(n):
            h = mx.nd.BatchNorm(out, gamma, beta, rmean, rvar,
                                training=False)[0]
            h = h + y
            h = mx.nd.Activation(h, act_type="softsign")
            out = h.softmax(axis=1)
        out.asnumpy()  # sync: async dispatch must not flatter the number
        return 4 * n   # registry-op invokes per iteration

    def measure(jit_on):
        prev = mx.nd.set_eager_jit(jit_on)
        try:
            loop(warmup)  # warm cache / warm eager dispatch
            t0 = time.perf_counter()
            nops = loop(iters)
            dt = time.perf_counter() - t0
        finally:
            mx.nd.set_eager_jit(prev)
        return dt / nops * 1e6

    mx.nd.reset_dispatch_stats()
    us_jit = measure(True)
    stats = mx.nd.dispatch_stats()
    us_eager = measure(False)
    return {
        "us_per_op_jit": round(us_jit, 2),
        "us_per_op_eager": round(us_eager, 2),
        "speedup": round(us_eager / us_jit, 2) if us_jit else 0.0,
        "cache": {k: stats[k] for k in ("hits", "misses", "evictions",
                                        "bypasses", "size")},
    }


def bench_overlap():
    """Overlap-engine A/B (ISSUE 4).

    - ``input_bound``: a synthetic loader whose per-batch host latency is
      calibrated to one compute step (the input-bound regime prefetch
      exists for), driven with the device prefetcher on vs off.  Each arm
      syncs the loss per step — the realistic logging-loop pattern where
      jax async dispatch alone cannot hide the input wait.  Ideal speedup
      is 2x; the acceptance bar is >= 1.5x.
    - ``allreduce_fused``: per-key vs bucket-fused kvstore round trips at
      1 KiB..64 MiB message sizes.  On one device this measures the
      per-call dispatch+copy overhead fusion amortizes (the collective
      itself is the identity); on a pod the same code path adds the
      per-collective latency win.  Each size also gets a ZeRO-1 arm
      (ISSUE 7): the fused flat buffer through reduce-scatter +
      all-gather in one jitted shard_map — the exact collective pair
      ``MXNET_ZERO=1`` issues per bucket (``rs_ag_ms``/``rs_ag_gb_s``).
    - ``zero_optimizer``: per-rank optimizer-state bytes, ZeRO vs
      replicated, from a real 2-step ``MXNET_ZERO=1`` Trainer loop —
      the ~1/dp memory win, read from the telemetry gauge.
    """
    import mxnet_tpu as mx
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.gluon.data.prefetcher import PrefetchIterator
    from mxnet_tpu.parallel import bucketing
    from mxnet_tpu.parallel.data_parallel import TrainStep

    out = {}
    # -- input-bound A/B ---------------------------------------------------
    np.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(256, activation="relu"),
            nn.Dense(256, activation="relu"), nn.Dense(10))
    net.initialize()
    net(mx.nd.zeros((2, 128)))

    def loss_fn(o, y):
        import jax.numpy as jnp

        return jnp.mean((o - y) ** 2)

    step = TrainStep(net, loss_fn, optimizer="sgd")
    x = np.random.randn(64, 128).astype("f")
    y = np.random.randn(64, 10).astype("f")
    for _ in range(3):
        np.asarray(step(x, y))  # warm the jit
    t0 = time.perf_counter()
    for _ in range(5):
        np.asarray(step(x, y))
    compute_s = (time.perf_counter() - t0) / 5
    delay_s = max(compute_s, 1e-3)
    n_steps = 30

    def batches():
        for _ in range(n_steps):
            time.sleep(delay_s)  # synthetic decode/augment latency
            yield (x, y)

    def run_epoch(depth):
        it = PrefetchIterator(batches(), depth=depth,
                              sharding=step._batch_shard)
        t0 = time.perf_counter()
        try:
            for bx, by in it:
                float(np.asarray(step(bx, by)))  # per-step sync
        finally:
            it.close()  # a mid-epoch failure must not leak the producer
        return n_steps / (time.perf_counter() - t0)

    without = run_epoch(0)
    with_pf = run_epoch(2)
    out["input_bound"] = {
        "steps_s_prefetch": round(with_pf, 2),
        "steps_s_serial": round(without, 2),
        "speedup": round(with_pf / without, 2) if without else 0.0,
        "loader_delay_ms": round(delay_s * 1e3, 3),
        "compute_ms": round(compute_s * 1e3, 3),
    }

    # -- fused vs per-key allreduce curve ----------------------------------
    # drives the REAL collective issue path (allreduce_hosts with the
    # single-process identity short-circuit off): per-key = K collectives,
    # fused = pack + 1 collective + unpack — the exact code the dist store
    # runs per push.  On one chip this isolates per-collective issue cost;
    # on a pod the same curve adds the network latency win.
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from mxnet_tpu.parallel import collectives as coll
    from mxnet_tpu.parallel.collectives import allreduce_hosts

    mesh = Mesh(np.array(jax.devices()), ("dp",))
    dp = len(jax.devices())

    # the MXNET_ZERO per-bucket pair: reduce-scatter to 1/dp shards,
    # all-gather the (here: identity) updated shard back — one jit,
    # same program shape as ZeroBucketEngine._make_step; jit
    # re-specializes per padded flat size
    def _rs_ag_body(x):
        s = coll.reduce_scatter(x, axis_name="dp")
        return coll.all_gather(s, axis_name="dp", axis=0, tiled=True)

    rs_ag_pair = jax.jit(coll.shard_map(_rs_ag_body, mesh, in_specs=(P(),),
                                        out_specs=P()))

    curve = {}
    for label, elems, k in (("1KiB", 256, 16), ("32KiB", 8192, 16),
                            ("1MiB", 1 << 18, 16), ("8MiB", 1 << 21, 4),
                            ("64MiB", 1 << 24, 2)):
        vals = [jnp.asarray(np.random.randn(elems).astype("f"))
                for _ in range(k)]
        plan = bucketing.assign_buckets(
            [(i, (elems,), "float32") for i in range(k)],
            cap_bytes=64 << 20)
        iters = 3

        def per_key():
            outs = [allreduce_hosts(v, _testing_force=True) for v in vals]
            jax.block_until_ready(outs)  # ALL results: async dispatch
            # must not let late collectives escape the timed region

        def fused():
            outs = []
            for b in plan.buckets:
                flat = bucketing.pack([vals[i] for i in b.keys])
                outs.extend(bucketing.unpack(
                    b, allreduce_hosts(flat, _testing_force=True)))
            jax.block_until_ready(outs)

        def rs_ag():
            outs = []
            for b in plan.buckets:
                flat = bucketing.pack([vals[i] for i in b.keys])
                _, _, pad = bucketing.shard_layout(b.size, dp)
                if pad:
                    flat = jnp.pad(flat, (0, pad))
                outs.extend(bucketing.unpack(b, rs_ag_pair(flat)))
            jax.block_until_ready(outs)

        per_key()
        fused()  # warm every jit path
        rs_ag()
        t0 = time.perf_counter()
        for _ in range(iters):
            per_key()
        t_key = (time.perf_counter() - t0) / iters
        t0 = time.perf_counter()
        for _ in range(iters):
            fused()
        t_fused = (time.perf_counter() - t0) / iters
        t0 = time.perf_counter()
        for _ in range(iters):
            rs_ag()
        t_zero = (time.perf_counter() - t0) / iters
        total_mb = k * elems * 4 / (1 << 20)
        curve[label] = {
            "tensors": k,
            "buckets": len(plan.buckets),
            "per_key_ms": round(t_key * 1e3, 3),
            "fused_ms": round(t_fused * 1e3, 3),
            "rs_ag_ms": round(t_zero * 1e3, 3),
            "speedup": round(t_key / t_fused, 2) if t_fused else 0.0,
            "per_key_gb_s": round(total_mb / 1024 / t_key, 2),
            "fused_gb_s": round(total_mb / 1024 / t_fused, 2),
            "rs_ag_gb_s": round(total_mb / 1024 / t_zero, 2),
        }
    out["allreduce_fused"] = curve
    out["zero_optimizer"] = _bench_zero_optimizer_bytes(dp)
    return out


def _bench_zero_optimizer_bytes(dp):
    """Per-rank optimizer-state bytes, sharded vs replicated (the
    MXNET_ZERO ~1/dp HBM win), measured from a real 2-step Trainer loop
    through the telemetry gauge."""
    import os

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd, telemetry

    prev = os.environ.get("MXNET_ZERO")
    os.environ["MXNET_ZERO"] = "1"
    try:
        np.random.seed(0)
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Dense(256, activation="relu"), gluon.nn.Dense(64))
        net.initialize()
        net(nd.zeros((2, 128)))
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.1, "momentum": 0.9},
                           kvstore="device")
        x = np.random.randn(8, 128).astype("f")
        y = np.random.randn(8, 64).astype("f")
        for _ in range(2):
            with autograd.record():
                loss = ((net(nd.array(x)) - nd.array(y)) ** 2).mean()
            loss.backward()
            tr.step(8)
        sharded = telemetry.gauge("mxnet_zero_optimizer_bytes_per_rank").value
        # replicated momentum = one fp32 buffer per parameter element
        replicated = sum(
            int(np.prod(p.shape)) * 4
            for p in net.collect_params().values())
        return {
            "dp": dp,
            "bytes_per_rank": int(sharded),
            "replicated_bytes": int(replicated),
            "ratio": round(sharded / replicated, 4) if replicated else 0.0,
        }
    finally:
        if prev is None:
            os.environ.pop("MXNET_ZERO", None)
        else:
            os.environ["MXNET_ZERO"] = prev


def bench_graph():
    """Graph compiler (ISSUE 11): pass-pipeline one-time cost, measured
    fused-op count, and step-time A/B (pipeline on vs off) on (a) the
    llama proxy through TrainStep and (b) a deep elementwise-chain
    microbench — the workload whose dispatch graph the fusion pass
    collapses hardest."""
    import time

    import jax
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, nd
    from mxnet_tpu import graph as G
    from mxnet_tpu.gluon import HybridBlock, nn
    from mxnet_tpu.gluon.model_zoo.language import llama
    from mxnet_tpu.parallel.data_parallel import TrainStep

    out = {}

    # -- (a) deep elementwise-chain microbench ----------------------------
    class Chain(HybridBlock):
        def __init__(self, depth=24, **kw):
            super().__init__(**kw)
            self.depth = depth
            with self.name_scope():
                self.fc = nn.Dense(128, in_units=64)

        def hybrid_forward(self, F, x):
            h = self.fc(x)
            for _ in range(self.depth):
                h = F.tanh(h * 0.5 + 0.125)
            return h

    def chain_arm(flag, prefix, iters=60):
        mx.random.seed(0)
        np.random.seed(0)
        net = Chain(prefix=prefix)
        net.initialize()
        net.hybridize()
        x = nd.array(np.random.RandomState(1).randn(16, 64).astype("f"))
        with G.override_enabled(flag):
            t0 = time.perf_counter()
            net(x).asnumpy()                      # build
            build_s = time.perf_counter() - t0
            for _ in range(5):
                net(x).asnumpy()                  # warm
            t0 = time.perf_counter()
            for _ in range(iters):
                y = net(x)
            y.asnumpy()
            step_ms = (time.perf_counter() - t0) / iters * 1e3
        fused = 0
        for ir in getattr(net, "_cached_graph_ir", {}).values():
            fused += ir.fused_op_count()
        return {"build_s": round(build_s, 3),
                "forward_ms": round(step_ms, 3), "fused_ops": fused}

    G.reset_stats()
    raw = chain_arm(False, "graw_")
    opt = chain_arm(True, "gopt_")
    stats = G.stats_snapshot()
    pipeline_s = sum(p["seconds"] for p in stats["passes"].values())
    out["elemwise_chain"] = {
        "optimized": opt, "raw": raw,
        "pipeline_one_time_s": round(pipeline_s, 4),
        "speedup": round(raw["forward_ms"] / opt["forward_ms"], 3)
        if opt["forward_ms"] else 0.0,
    }

    # -- (b) llama proxy through TrainStep (the functionalize seam) -------
    cfg = dict(vocab_size=512, hidden_size=128, num_layers=2, num_heads=4,
               num_kv_heads=2, intermediate_size=256, max_seq_len=64)
    ids = np.random.RandomState(0).randint(
        0, cfg["vocab_size"], (2, 64)).astype("int32")
    labels = np.random.RandomState(1).randint(
        0, cfg["vocab_size"], (2, 64)).astype("int32")

    def loss_fn(logits, y):
        import jax.numpy as jnp

        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.take_along_axis(logp, y[..., None], axis=-1)

    def llama_arm(flag, iters=12):
        mx.random.seed(0)
        np.random.seed(0)
        net = llama.LlamaForCausalLM(llama.LlamaConfig(**cfg))
        net.initialize()
        net(mx.nd.zeros((1, 64), dtype="int32"))
        step = TrainStep(net, loss_fn, optimizer="adam",
                         optimizer_params={"learning_rate": 3e-4})
        G.reset_stats()
        with G.override_enabled(flag):
            t0 = time.perf_counter()
            step(ids, labels)                     # build
            build_s = time.perf_counter() - t0
            for _ in range(3):
                float(step(ids, labels))          # warm (sync each)
            t0 = time.perf_counter()
            for _ in range(iters):
                loss = step(ids, labels)
            float(loss)
            step_ms = (time.perf_counter() - t0) / iters * 1e3
        snap = G.stats_snapshot()
        return {"build_s": round(build_s, 2),
                "step_ms": round(step_ms, 2),
                "fused_ops": snap["fused_ops_created"],
                "pipeline_one_time_s": round(
                    sum(p["seconds"] for p in snap["passes"].values()), 4),
                "fallbacks": snap["fallbacks"]}

    l_raw = llama_arm(False)
    l_opt = llama_arm(True)
    out["llama_proxy"] = {
        "optimized": l_opt, "raw": l_raw,
        "speedup": round(l_raw["step_ms"] / l_opt["step_ms"], 3)
        if l_opt["step_ms"] else 0.0,
    }
    out["fused_op_count"] = opt["fused_ops"] + l_opt["fused_ops"]
    return out


def bench_planner():
    """Sharding planner (ISSUE 10): plan-time overhead (one-time, host
    only), the zero-per-step-cost contract (compile-tracer-asserted:
    after the warmup step every further planner-driven step performs
    ZERO fresh traces and zero plan work), and estimated-vs-actual HBM
    bytes for the llama proxy under 2 mesh shapes."""
    import time

    import jax
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import telemetry
    from mxnet_tpu.gluon.model_zoo.language import llama
    from mxnet_tpu.parallel import planner
    from mxnet_tpu.parallel.data_parallel import TrainStep
    from mxnet_tpu.parallel.functional import functionalize

    cfg = dict(vocab_size=512, hidden_size=128, num_layers=2,
               num_heads=4, num_kv_heads=2, intermediate_size=256,
               max_seq_len=256)
    # the global batch shards over the data axes: keep it divisible by
    # the device count on any mesh this arm builds
    n_dev = len(jax.devices())
    batch, seq = max(2, n_dev), 64

    def make_net():
        net = llama.LlamaForCausalLM(llama.LlamaConfig(**cfg))
        net.initialize(ctx=mx.current_context())
        net(mx.nd.zeros((1, seq), dtype="int32"))
        return net

    def loss_fn(logits, labels):
        import jax.numpy as jnp

        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.take_along_axis(logp, labels[..., None], axis=-1)

    def actual_resident_bytes(step):
        """Measured per-device bytes of params + optimizer state (the
        plan-governed resident footprint; grads/activations are
        transient inside the donated jit)."""
        total = 0
        leaves = list(step.train_params.values()) \
            + list(step.rest_params.values()) \
            + jax.tree_util.tree_leaves(step.opt_state)
        for leaf in leaves:
            shard = leaf.sharding.shard_shape(leaf.shape)
            total += int(np.prod(shard) or 1) * leaf.dtype.itemsize
        return total

    meshes = {"dp": {"dp": n_dev}}
    if n_dev % 2 == 0 and n_dev > 1:
        # dp*fsdp = n_dev here, so `batch` stays divisible; an odd
        # device count has no even dp×fsdp split — skip the arm, keep
        # the dp numbers
        meshes["dp_fsdp"] = {"dp": n_dev // 2, "fsdp": 2}
    out = {"device_count": n_dev}
    ids = np.random.randint(0, cfg["vocab_size"],
                            (batch, seq)).astype("int32")
    labels = np.random.randint(0, cfg["vocab_size"],
                               (batch, seq)).astype("int32")
    for name, axes in meshes.items():
        # one net per arm, planned from ITS OWN signature — plan specs
        # key on param names, and gluon auto-name prefixes differ
        # between net instances
        net = make_net()
        sig = planner.signature_of(functionalize(net)[1])
        t0 = time.perf_counter()
        plan = planner.plan_sharding(
            planner.PlannerConfig(mesh=axes, rules="fsdp",
                                  optimizer="sgd_momentum",
                                  batch_rows=batch), sig, n_dev)
        plan_ms = (time.perf_counter() - t0) * 1e3
        step = TrainStep(net, loss_fn, optimizer="sgd",
                         optimizer_params={"learning_rate": 0.01,
                                           "momentum": 0.9}, plan=plan)
        step(ids, labels)            # warmup: the one compile
        before = telemetry.snapshot()["compile"]["count"]
        iters = 4
        t0 = time.perf_counter()
        for _ in range(iters):
            step(ids, labels)
        last = step(ids, labels)
        np.asarray(last)             # drain async dispatch
        dt = time.perf_counter() - t0
        fresh = telemetry.snapshot()["compile"]["count"] - before
        est = plan.hbm
        actual = actual_resident_bytes(step)
        est_resident = est["params"] + est["optimizer"]
        out[name] = {
            "plan_ms": round(plan_ms, 2),
            "steady_steps_per_s": round((iters + 1) / dt, 2),
            "fresh_traces_after_warmup": int(fresh),
            "estimated_resident_bytes": int(est_resident),
            "actual_resident_bytes": int(actual),
            "estimate_ratio": round(actual / max(1, est_resident), 3),
            "estimated_total_bytes": int(est["total"]),
        }
        assert fresh == 0, \
            f"planner arm {name}: {fresh} fresh traces after warmup " \
            "(the zero-per-step-cost contract is compile-tracer-asserted)"
    return out


def bench_elastic():
    """Zero-downtime elasticity (ISSUE 13): restart-to-first-step for a
    cold (trace + XLA compile) vs warm (persistent compile cache)
    TrainStep resume, live ZeRO resharding vs the checkpoint-restore
    round trip, and serving replica handoff join-to-first-token —
    the ROADMAP's target metrics, measured rather than asserted."""
    import os
    import tempfile
    import time

    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import compile_cache as cc
    from mxnet_tpu import gluon, nd, telemetry
    from mxnet_tpu import autograd
    from mxnet_tpu.parallel import planner, resharding
    from mxnet_tpu.parallel.data_parallel import TrainStep
    from mxnet_tpu.parallel.functional import functionalize

    out = {}
    tmp = tempfile.mkdtemp(prefix="bench_elastic_")
    cache = cc.CompileCache(os.path.join(tmp, "compile_cache"))

    def make_net(seed=0):
        np.random.seed(seed)
        mx.random.seed(seed)
        from mxnet_tpu.gluon import block as _block

        _block._NAME_SCOPE.counters.clear()
        del _block._NAME_SCOPE.scope_stack[:]
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Dense(64, activation="relu", in_units=8),
                gluon.nn.Dense(64, activation="relu", in_units=64),
                gluon.nn.Dense(64, activation="relu", in_units=64),
                gluon.nn.Dense(4, in_units=64))
        net.initialize()
        return net

    def loss_fn(o, y):
        return (o - y) ** 2

    # -- restart-to-first-step: cold trace vs warm compile-cache load --
    def first_step_s(use_cache):
        net = make_net()
        rng = np.random.RandomState(7)
        x = rng.randn(8, 8).astype("f")
        y = (rng.randn(8, 4) > 0).astype("f")
        t0 = time.perf_counter()
        step = TrainStep(net, loss_fn, optimizer="sgd",
                         optimizer_params={"learning_rate": 0.1},
                         compile_cache=cache if use_cache else None)
        np.asarray(step(x, y))
        dt = time.perf_counter() - t0
        resharding.observe_restart_to_first_step(dt)
        return dt

    cold = first_step_s(use_cache=True)    # populates the cache
    warm = first_step_s(use_cache=True)    # loads the executable
    out["restart_to_first_step"] = {
        "cold_s": round(cold, 4), "warm_s": round(warm, 4),
        "speedup": round(cold / max(warm, 1e-9), 2),
        "cache": cache.stats()}

    # -- live ZeRO reshard vs checkpoint-restore round trip ------------
    def plan_for(net, dp):
        _, params = functionalize(net)
        pcfg = planner.PlannerConfig(mesh={"dp": dp},
                                     rules="replicated",
                                     optimizer="sgd_momentum",
                                     zero=True)
        return planner.plan_sharding(pcfg,
                                     planner.signature_of(params), dp)

    os.environ["MXNET_ZERO"] = "1"
    try:
        net = make_net()
        net(nd.zeros((2, 8)))
        planner.set_default_plan(plan_for(net, 8))
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.1, "momentum": 0.9},
                           kvstore="device")
        rng = np.random.RandomState(3)
        for _ in range(3):
            x = nd.array(rng.randn(8, 8).astype("f"))
            y = nd.array((rng.randn(8, 4) > 0).astype("f"))
            with autograd.record():
                loss = ((net(x) - y) ** 2).mean()
            loss.backward()
            tr.step(8)
        def moved_bytes():
            snap = telemetry.snapshot()["metrics"]
            return {s["labels"].get("kind", "?"): int(s["value"])
                    for s in snap.get("mxnet_reshard_bytes_total",
                                      {}).get("samples", [])}

        # live reshard FIRST, while the sharded state is resident (a
        # load_states would harvest it to host pieces and give the
        # transfer nothing to move)
        plan2 = plan_for(net, 2)
        base = moved_bytes()
        t0 = time.perf_counter()
        tr._zero.reshard(plan2)
        reshard_s = time.perf_counter() - t0
        moved = {k: v - base.get(k, 0) for k, v in
                 moved_bytes().items() if v - base.get(k, 0)}
        fname = os.path.join(tmp, "trainer.states")
        t0 = time.perf_counter()
        tr.save_states(fname)
        tr.load_states(fname)
        ckpt_s = time.perf_counter() - t0
        out["zero_reshard_dp8_to_dp2"] = {
            "live_reshard_s": round(reshard_s, 4),
            "checkpoint_roundtrip_s": round(ckpt_s, 4),
            "resharded_bytes": moved,
            # at this toy scale the "disk" is tmpfs and the payload is
            # KB, so the checkpoint arm is unrealistically cheap; the
            # live path's win is (a) no retrace (see
            # restart_to_first_step) and (b) O(state/dp) device moves
            # vs O(state) host round trips at real scale — the real-pod
            # numbers are the ROADMAP's outstanding TPU round
            "note": "toy-scale: tmpfs checkpoint, KB payload"}
    finally:
        os.environ.pop("MXNET_ZERO", None)
        planner.set_default_plan(None)

    # -- serving replica handoff: join-to-first-token ------------------
    from mxnet_tpu.gluon.model_zoo.language import llama
    from mxnet_tpu.serving.engine import ServingEngine

    lcfg = llama.LlamaConfig(vocab_size=64, hidden_size=32,
                             num_layers=2, num_heads=4, num_kv_heads=2,
                             intermediate_size=48, max_seq_len=64)
    lnet = llama.LlamaForCausalLM(lcfg)
    lnet.initialize(ctx=mx.current_context())
    lnet(mx.nd.zeros((1, 8), dtype="int32"))
    kw = dict(batch_buckets=[1], prefill_buckets=[8], kv_pages=16,
              page_size=4, max_batch=1, compile_cache=cache)

    def ttft(engine):
        t0 = time.monotonic()
        engine.start()
        engine.submit([1, 2, 3, 4], max_new_tokens=2).result(120)
        return time.monotonic() - t0

    cold_eng = ServingEngine(lnet, **kw)
    cold_ttft = ttft(cold_eng)             # AOT-compiles + caches
    joiner = ServingEngine.join_replica(lnet, cold_eng, **kw)
    join_ttft = ttft(joiner)               # donated params + warm cache
    joiner.close()
    cold_eng.close()
    out["serving_replica_handoff"] = {
        "cold_start_to_first_token_s": round(cold_ttft, 4),
        "join_to_first_token_s": round(join_ttft, 4),
        "speedup": round(cold_ttft / max(join_ttft, 1e-9), 2)}
    return out


def bench_serving():
    """Serving-engine load generator (ISSUE 8).

    Two arms against the AOT-compiled continuous-batching engine on the
    tiny llama proxy:

    - **closed loop**: N concurrent clients, each submitting its next
      request the moment the previous completes — measures the
      latency/throughput trade as the decode batch fills.
    - **open loop**: requests arrive on a fixed schedule (at ~60% of the
      closed-loop peak rate) regardless of completions — measures
      latency under sustained arrival pressure, queueing included.

    Reports p50/p99 latency and tokens/s(/chip) per concurrency level,
    plus the engine diagnosis context: warmup cost, compiled-signature
    count, batch occupancy, and the steady-state fresh-trace count
    (which must be 0 — the ISSUE 8 contract)."""
    import threading

    import jax
    import numpy as np

    from mxnet_tpu import nd, serving, telemetry
    from mxnet_tpu.gluon.model_zoo.language.llama import llama_tiny

    net = llama_tiny()
    net.initialize()
    net(nd.zeros((1, 8), dtype="int32"))
    eng = serving.ServingEngine(net, batch_buckets=[1, 2, 4],
                                prefill_buckets=[8, 16], kv_pages=64,
                                page_size=8, max_batch=4)
    t0 = time.perf_counter()
    eng.start()
    warmup_s = time.perf_counter() - t0
    # touch every bucket once so steady state is honestly steady
    warm = [eng.submit(np.random.RandomState(k).randint(
        1, 512, (n,)).astype("int32"), max_new_tokens=2)
        for k, n in enumerate((3, 8, 11, 16))]
    for q in warm:
        q.result(timeout=300)
    compile_before = telemetry.snapshot()["compile"]["count"]
    n_chips = max(1, jax.local_device_count())
    max_new = 8

    def percentile(lat, p):
        return lat[min(len(lat) - 1, int(p * len(lat)))]

    def run_closed(conc, total=16):
        lat, lock = [], threading.Lock()
        per_client = total // conc

        def client(k):
            rr = np.random.RandomState(1000 + k)
            for _ in range(per_client):
                prompt = rr.randint(1, 512,
                                    (int(rr.randint(1, 17)),)).astype("int32")
                t1 = time.perf_counter()
                eng.submit(prompt, max_new_tokens=max_new).result(
                    timeout=600)
                with lock:
                    lat.append(time.perf_counter() - t1)

        t1 = time.perf_counter()
        threads = [threading.Thread(target=client, args=(k,))
                   for k in range(conc)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t1
        lat.sort()
        toks = len(lat) * max_new
        return {
            "requests": len(lat),
            "p50_ms": round(percentile(lat, 0.50) * 1e3, 1),
            "p99_ms": round(percentile(lat, 0.99) * 1e3, 1),
            "requests_per_s": round(len(lat) / wall, 2),
            "tokens_per_s": round(toks / wall, 1),
            "tokens_per_s_chip": round(toks / wall / n_chips, 1),
        }

    closed = {str(c): run_closed(c) for c in (1, 2, 4)}

    def run_open(rate_rps, total=24):
        pending = []
        start = time.perf_counter()
        rr = np.random.RandomState(7)
        for i in range(total):
            target = start + i / rate_rps
            now = time.perf_counter()
            if target > now:
                time.sleep(target - now)
            prompt = rr.randint(1, 512,
                                (int(rr.randint(1, 17)),)).astype("int32")
            pending.append(eng.submit(prompt, max_new_tokens=max_new))
        lat = []
        for req in pending:
            # the request records its own submit->done latency, so late
            # collection here cannot inflate early completions
            lat.append(req.result(timeout=600)["latency_s"])
        wall = time.perf_counter() - start
        lat.sort()
        return {
            "arrival_rps": round(rate_rps, 2),
            "requests": total,
            "p50_ms": round(percentile(lat, 0.50) * 1e3, 1),
            "p99_ms": round(percentile(lat, 0.99) * 1e3, 1),
            "tokens_per_s": round(total * max_new / wall, 1),
            "tokens_per_s_chip": round(total * max_new / wall / n_chips,
                                       1),
        }

    open_loop = run_open(max(0.5, 0.6 * closed["4"]["requests_per_s"]))
    snap = telemetry.snapshot()
    occ = snap["metrics"].get("mxnet_serving_batch_occupancy", {})
    occ_samples = occ.get("samples", [])
    occupancy = None
    if occ_samples and occ_samples[0].get("count"):
        occupancy = round(occ_samples[0]["sum"] / occ_samples[0]["count"],
                          3)
    fresh = snap["compile"]["count"] - compile_before
    stats = eng.stats()
    eng.close()
    return {
        "model": "llama_tiny",
        "warmup_s": round(warmup_s, 2),
        "compiled_signatures": stats["compiled_signatures"],
        "fresh_traces_steady_state": int(fresh),
        "batch_occupancy_mean": occupancy,
        "kv_pool_bytes": stats["kv_pages"]["pool_bytes"],
        "closed_loop": closed,
        "open_loop": open_loop,
    }


def bench_fleet():
    """Serving fleet router (ISSUE 17): throughput scaling and
    kill-recovery cost.

    - **scaling**: closed-loop load through the fleet router at 1 and 3
      in-process replicas — p50/p99 latency and tokens/s.  Router
      overhead shows up as the 1-replica delta vs ``extra.serving``;
      scaling efficiency as the 3-vs-1 tokens/s ratio (sub-linear on a
      shared CPU, near-linear across real chips).
    - **kill recovery**: SIGKILL-equivalent on one of 3 replicas under
      load — time from kill to a ``join_replica`` replacement back in
      rotation, with the replacement's ready time reported next to the
      cold first spawn for comparison (process-mode warm-vs-cold is
      asserted by ci/fleet_smoke.py; in-process on one contended CPU
      the compile-cache win can wash out)."""
    import threading

    import numpy as np

    from mxnet_tpu import nd, serving
    from mxnet_tpu.serving import fleet
    from mxnet_tpu.gluon.model_zoo.language.llama import llama_tiny

    net = llama_tiny()
    net.initialize()
    net(nd.zeros((1, 8), dtype="int32"))
    kw = dict(batch_buckets=[1, 2], prefill_buckets=[8, 16],
              kv_pages=32, page_size=8, max_batch=2)

    def factory(rid, donor):
        if donor is not None:
            return serving.ServingEngine.join_replica(
                net, donor, **kw).start()
        return serving.ServingEngine(net, **kw).start()

    max_new = 8

    def percentile(lat, p):
        return lat[min(len(lat) - 1, int(p * len(lat)))]

    def mk_fleet(n):
        mgr = fleet.FleetManager(engine_factory=factory, replicas=n,
                                 probe_interval_ms=100)
        router = fleet.Router(retry_budget=1, hedge_ms=5_000,
                              probe_interval_ms=100, manager=mgr)
        mgr.attach_router(router)
        mgr.ensure(n)
        router.start()
        return mgr, router

    def run_closed(router, conc=4, total=24):
        lat, lock = [], threading.Lock()
        per_client = total // conc

        def client(k):
            rr = np.random.RandomState(500 + k)
            for _ in range(per_client):
                prompt = rr.randint(
                    1, 512, (int(rr.randint(2, 13)),)).tolist()
                t1 = time.perf_counter()
                router.submit(prompt, max_new_tokens=max_new,
                              deadline_ms=300_000).response(timeout=600)
                with lock:
                    lat.append(time.perf_counter() - t1)

        t1 = time.perf_counter()
        threads = [threading.Thread(target=client, args=(k,))
                   for k in range(conc)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t1
        lat.sort()
        return {
            "requests": len(lat),
            "p50_ms": round(percentile(lat, 0.50) * 1e3, 1),
            "p99_ms": round(percentile(lat, 0.99) * 1e3, 1),
            "tokens_per_s": round(len(lat) * max_new / wall, 1),
        }

    out = {}
    for n in (1, 3):
        mgr, router = mk_fleet(n)
        try:
            out[f"replicas_{n}"] = run_closed(router)
        finally:
            router.close()
            mgr.drain_all(timeout=60)

    # -- kill recovery -----------------------------------------------------
    mgr, router = mk_fleet(3)
    try:
        results, errors = {}, []

        def bg_client(k):
            rr = np.random.RandomState(900 + k)
            for _ in range(8):
                prompt = rr.randint(
                    1, 512, (int(rr.randint(2, 13)),)).tolist()
                try:
                    req = router.submit(prompt, max_new_tokens=4,
                                        deadline_ms=300_000)
                    results[req.id] = req.response(timeout=600)
                except Exception as e:
                    errors.append(repr(e)[:120])

        threads = [threading.Thread(target=bg_client, args=(k,))
                   for k in range(3)]
        for t in threads:
            t.start()
        time.sleep(0.3)
        victim = router.replicas()[0]
        t_kill = time.perf_counter()
        victim.kill()
        recovered = None
        while time.perf_counter() - t_kill < 600:
            if len(router.replicas()) >= 3 and any(
                    k == "replacement" for _, k, _ in mgr.spawn_times):
                recovered = time.perf_counter() - t_kill
                break
            time.sleep(0.05)
        for t in threads:
            t.join()
        repl_ready = [dt for _, k, dt in mgr.spawn_times
                      if k == "replacement"]
        cold_ready = mgr.spawn_times[0][2] if mgr.spawn_times else None
        out["kill_recovery"] = {
            "requests_lost": 24 - len(results),
            "errors": errors[:3],
            "kill_to_replacement_s": round(recovered, 2)
            if recovered is not None else None,
            "replacement_ready_s": round(repl_ready[0], 2)
            if repl_ready else None,
            "cold_ready_s": round(cold_ready, 2)
            if cold_ready is not None else None,
        }
    finally:
        mgr.auto_heal = False
        router.close()
        mgr.drain_all(timeout=60)
    return out


def bench_observability():
    """Runtime introspection plane (ISSUE 14): prove the instrumentation
    is free where it must be, and right where it measures.

    - **eager A/B**: the eager dispatch path gains ZERO work from the
      introspection plane; µs/op with request tracing + aggregation
      ticking enabled vs everything off must be within noise.
    - **serving A/B**: engine tokens/s with per-request tracing on vs
      ``MXNET_TRACE_REQUESTS=0`` — host-side stamps only, within noise.
    - **online-vs-offline MFU pin** (llama proxy): the online gauge and
      an offline ``steps × flops / (wall × peak × devices)`` computed
      from the SAME cost_analysis FLOPs source must agree tightly (the
      only divergence is window-edge timing).
    """
    import os

    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import introspection, nd, serving, telemetry

    out = {}
    # -- eager A/B ---------------------------------------------------------
    # process-level warmup first (jit-cache fill, jax internals), then
    # best-of-2 per arm — the instrumentation adds literally zero code
    # to this path, so any residual delta IS scheduler noise and must
    # not flip the verdict
    bench_eager_op_overhead(iters=60, warmup=20)

    def eager_us(trace_env):
        prev = os.environ.get("MXNET_TRACE_REQUESTS")
        os.environ["MXNET_TRACE_REQUESTS"] = trace_env
        try:
            return min(bench_eager_op_overhead(
                iters=150, warmup=20)["us_per_op_jit"]
                for _ in range(2))
        finally:
            if prev is None:
                os.environ.pop("MXNET_TRACE_REQUESTS", None)
            else:
                os.environ["MXNET_TRACE_REQUESTS"] = prev

    us_on = eager_us("1")
    us_off = eager_us("0")
    ratio = us_on / us_off if us_off else 1.0
    out["eager_overhead"] = {
        "us_per_op_introspection_on": us_on,
        "us_per_op_introspection_off": us_off,
        "ratio": round(ratio, 3),
        "within_noise": bool(0.8 <= ratio <= 1.25),
    }

    # -- serving tokens/s A/B ---------------------------------------------
    from mxnet_tpu.gluon.model_zoo.language.llama import llama_tiny

    def serving_tokens_per_s(trace_on):
        net = llama_tiny()
        net.initialize()
        net(nd.zeros((1, 8), dtype="int32"))
        eng = serving.ServingEngine(
            net, batch_buckets=[1, 2, 4], prefill_buckets=[8, 16],
            kv_pages=64, page_size=8, max_batch=4,
            trace_requests=trace_on)
        eng.start()
        R = np.random.RandomState(0)
        # warm every bucket, then measure a fixed closed-loop burst
        for n in (3, 8, 11, 16):
            eng.submit(R.randint(1, 512, (n,)).astype("int32"),
                       max_new_tokens=2).result(timeout=300)
        t0 = time.perf_counter()
        reqs = [eng.submit(R.randint(1, 512, (8,)).astype("int32"),
                           max_new_tokens=8) for _ in range(12)]
        for r in reqs:
            r.result(timeout=300)
        dt = time.perf_counter() - t0
        eng.close()
        return 12 * 8 / dt

    # first engine of the process pays one-time warmup (jax internals,
    # libtpu init) regardless of the arm — throw it away, then
    # ALTERNATE the arms (slow drift hits both equally) and take the
    # best of three per arm so scheduler noise cannot flip the verdict
    serving_tokens_per_s(False)
    on_runs, off_runs = [], []
    for _ in range(3):
        on_runs.append(serving_tokens_per_s(True))
        off_runs.append(serving_tokens_per_s(False))
    tps_on, tps_off = max(on_runs), max(off_runs)
    sratio = tps_on / tps_off if tps_off else 1.0
    out["serving_overhead"] = {
        "tokens_per_s_trace_on": round(tps_on, 1),
        "tokens_per_s_trace_off": round(tps_off, 1),
        "ratio": round(sratio, 3),
        "within_noise": bool(sratio >= 0.8),
    }

    # -- online-vs-offline MFU pin (same FLOPs source) ---------------------
    import jax

    from mxnet_tpu.gluon.model_zoo.language import llama
    from mxnet_tpu.parallel.data_parallel import TrainStep

    cfg = dict(vocab_size=512, hidden_size=128, num_layers=2,
               num_heads=4, num_kv_heads=2, intermediate_size=256,
               max_seq_len=256)
    net = llama.LlamaForCausalLM(llama.LlamaConfig(**cfg))
    net.initialize(ctx=mx.current_context())
    net(mx.nd.zeros((1, 64), dtype="int32"))

    def loss_fn(logits, labels):
        import jax.numpy as jnp

        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.take_along_axis(logp, labels[..., None], axis=-1)

    step = TrainStep(net, loss_fn, optimizer="adam",
                     optimizer_params={"learning_rate": 3e-4})
    ids = np.random.RandomState(1).randint(
        0, cfg["vocab_size"], (2, 64)).astype("int32")
    labels = np.random.RandomState(2).randint(
        0, cfg["vocab_size"], (2, 64)).astype("int32")
    peak = introspection.device_peak_flops() or 1e12
    prev_peak = os.environ.get("MXNET_DEVICE_PEAK_FLOPS")
    os.environ["MXNET_DEVICE_PEAK_FLOPS"] = repr(peak)
    try:
        np.asarray(step(ids, labels))        # warmup: trace + compile
        introspection.reset()
        iters = 8
        t0 = time.perf_counter()
        for _ in range(iters):
            np.asarray(step(ids, labels))    # per-step sync loop
        wall = time.perf_counter() - t0
        online = introspection.utilization()
        # MRU head: multi-axis meshes can hold >1 AOT variant per sig
        _, flops_per_step = step._compiled[
            next(iter(step._compiled))][0]
        ndev = max(1, jax.device_count())
        offline = (iters * (flops_per_step or 0)
                   / (wall * peak * ndev)) if flops_per_step else None
    finally:
        if prev_peak is None:
            os.environ.pop("MXNET_DEVICE_PEAK_FLOPS", None)
        else:
            os.environ["MXNET_DEVICE_PEAK_FLOPS"] = prev_peak
    mfu_ratio = (online / offline) if (online and offline) else None
    out["mfu_pin"] = {
        "flops_per_step": flops_per_step,
        "online_mfu": round(online, 6) if online else None,
        "offline_mfu": round(offline, 6) if offline else None,
        "ratio": round(mfu_ratio, 3) if mfu_ratio else None,
        # same FLOPs source: only window-edge timing can diverge
        "within_tolerance": bool(mfu_ratio and
                                 0.75 <= mfu_ratio <= 1.35),
    }
    out["goodput"] = telemetry.goodput_summary()

    # -- flight-recorder A/B (ISSUE 15): recorder-on vs
    # MXNET_FLIGHT_RECORDER=0 within noise on eager µs/op AND serving
    # tokens/s.  The recorder stamps only Python-level collective issue
    # points + step boundaries — the eager dispatch path and the
    # serving decode loop gain literally zero code — so any residual
    # delta is scheduler noise (same arm-alternating discipline as the
    # tracing A/B above).
    from mxnet_tpu import flight_recorder

    def _flight_env(flag):
        prev = os.environ.get("MXNET_FLIGHT_RECORDER")
        os.environ["MXNET_FLIGHT_RECORDER"] = flag
        flight_recorder.reset()     # re-resolve the cached gate
        return prev

    def _flight_restore(prev):
        if prev is None:
            os.environ.pop("MXNET_FLIGHT_RECORDER", None)
        else:
            os.environ["MXNET_FLIGHT_RECORDER"] = prev
        flight_recorder.reset()

    def flight_eager(flag):
        prev = _flight_env(flag)
        try:
            return min(bench_eager_op_overhead(
                iters=150, warmup=20)["us_per_op_jit"]
                for _ in range(2))
        finally:
            _flight_restore(prev)

    def flight_serving(flag):
        prev = _flight_env(flag)
        try:
            return serving_tokens_per_s(False)
        finally:
            _flight_restore(prev)

    fe_on, fe_off = flight_eager("1"), flight_eager("0")
    fs_on, fs_off = [], []
    for _ in range(2):
        fs_on.append(flight_serving("1"))
        fs_off.append(flight_serving("0"))
    fe_ratio = fe_on / fe_off if fe_off else 1.0
    fs_ratio = max(fs_on) / max(fs_off) if max(fs_off) else 1.0
    out["flight_overhead"] = {
        "eager_us_recorder_on": fe_on,
        "eager_us_recorder_off": fe_off,
        "eager_ratio": round(fe_ratio, 3),
        "serving_tokens_per_s_on": round(max(fs_on), 1),
        "serving_tokens_per_s_off": round(max(fs_off), 1),
        "serving_ratio": round(fs_ratio, 3),
        "within_noise": bool(0.8 <= fe_ratio <= 1.25
                             and fs_ratio >= 0.8),
    }
    return out


def bench_guard(steps=30, warmup=5):
    """Numerical-integrity guard A/B (ISSUE 20).

    Arm-alternating guard-on vs guard-off training steps/s on a small
    MLP — the same discipline as the tracing/flight-recorder A/Bs above
    (interleaved arms, best-of-2, so scheduler drift hits both arms
    equally).  The guard's contract is ONE fused sentinel reduction +
    ONE host sync per step over values the step already computes, so
    the throughput ratio must land within noise AND the compile-event
    counter must stay flat across both measured arms (the sentinel
    introduces no new traced program).
    """
    import time

    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd, telemetry
    from mxnet_tpu import guard as guard_mod

    X = np.random.RandomState(11).randn(32, 16).astype("f")
    Y = (X.sum(1) > 0).astype("f")
    lf = gluon.loss.SoftmaxCrossEntropyLoss()

    def build(guarded):
        np.random.seed(0)
        mx.random.seed(0)
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Dense(64, in_units=16, activation="relu"),
                gluon.nn.Dense(2, in_units=64))
        net.initialize(mx.init.Xavier())
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.05})
        if guarded:
            guard_mod.attach(trainer, guard=guard_mod.Guard(window=32))
        return net, trainer

    def compile_count():
        fam = telemetry.snapshot()["metrics"].get(
            "mxnet_compile_events_total")
        if not fam or not fam["samples"]:
            return 0.0
        return sum(s["value"] for s in fam["samples"])

    def run_arm(guarded):
        net, trainer = build(guarded)
        xs, ys = nd.array(X), nd.array(Y)

        def one_step():
            with autograd.record():
                loss = lf(net(xs), ys)
            loss.backward()
            trainer.step(X.shape[0])
            return loss

        for _ in range(warmup):
            one_step()
        t0 = time.perf_counter()
        last = None
        for _ in range(steps):
            last = one_step()
        np.asarray(last._get())      # settle the tail before stamping
        return steps / (time.perf_counter() - t0)

    # warm every trace in BOTH arms before measuring, so the measured
    # arms read pure steady state and the compile counter can be
    # asserted flat over them
    run_arm(False)
    run_arm(True)
    c0 = compile_count()
    on, off = [], []
    for _ in range(2):
        off.append(run_arm(False))
        on.append(run_arm(True))
    compile_delta = compile_count() - c0
    ratio = max(on) / max(off) if max(off) else 1.0
    return {
        "steps_per_s_guard_on": round(max(on), 2),
        "steps_per_s_guard_off": round(max(off), 2),
        "ratio": round(ratio, 3),
        # one fused sync per step is the design; anything beyond ~20%
        # on this CPU microbench is a regression, not noise
        "within_noise": bool(ratio >= 0.8),
        "compile_events_measured_arms": compile_delta,
        "compile_flat": bool(compile_delta == 0),
    }


def _probe_backend(timeout=90, retries=2):
    """Initialize the backend in a SUBPROCESS first, with a timeout.

    Round-4 postmortem: a wedged axon tunnel made the in-process
    ``jax.default_backend()`` call hang/raise, turning the whole bench into
    an unparseable traceback.  Probing out-of-process bounds the damage; on
    failure the caller emits a parseable ``{"error": ...}`` JSON line and a
    CPU smoke number instead.

    Returns (platform_str or None, error_str or None).
    """
    import subprocess
    import sys

    err = None
    for attempt in range(retries):
        # Popen + SIGTERM-with-grace, NOT subprocess.run(timeout=...):
        # run() SIGKILLs on timeout, and killing a mid-init TPU client is
        # exactly what wedges the single-client axon tunnel
        proc = subprocess.Popen(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].platform)"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        try:
            stdout, stderr = proc.communicate(timeout=timeout)
            out = (stdout or "").strip()
            if proc.returncode == 0 and out:
                return out.splitlines()[-1], None
            err = ((stderr or "") + out)[-300:] or f"rc={proc.returncode}"
        except subprocess.TimeoutExpired:
            proc.terminate()               # graceful client teardown first
            try:
                proc.communicate(timeout=15)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.communicate()
            err = f"backend init timed out after {timeout}s (tunnel wedged?)"
        if attempt + 1 < retries:
            time.sleep(5)
    return None, err


def _effective_knobs():
    """The resolved tuning-knob configuration (value + provenance:
    default/env/tuned/trial) stamped into every ``extra.*`` result
    block — A/B arms can never silently run different configs, and a
    BENCH_*.json trajectory always says which knob values produced
    its numbers."""
    try:
        from mxnet_tpu import tuning

        return tuning.effective_config()
    except Exception as e:
        return {"error": repr(e)[:120]}


def bench_tune(workloads=None, rungs=2, budget0=2, serving=False):
    """Offline knob-space search (``bench.py --tune``, ISSUE 16).

    For each selected knob: run the deterministic grid +
    successive-halving schedule (``mxnet_tpu.tuning.search``), score
    every candidate with the live gauges — the telemetry step timeline
    (step wall seconds) for training arms, tokens/s + p99 TTFT folded
    into one ascending score for serving arms — and persist the winner
    into the tuning DB (``MXNET_TUNE_DB_DIR``) keyed by workload
    signature + device kind + jax fingerprint.  A warm process with
    ``MXNET_TUNE=1`` then replays the winner with ZERO search trials.

    Training workloads:

    - ``allreduce_bucket_mb`` — the ≤32KiB fused-allreduce regime (16
      tensors x 32KiB), the measured win/loss crossover from
      bench_overlap: per-key (cap 0) pays 16 collective launches where
      one fused bucket pays 1.
    - ``graph_fuse_cap`` — the deep elementwise-chain microbench from
      bench_graph, rebuilt per trial so the pass pipeline re-runs
      under the candidate cap.
    - ``prefetch_buffer`` — an input-bound producer/consumer pipeline
      (~1 ms host work per side); depth overlaps them.

    Serving workloads (``--tune-serving``; engine spin-up per trial is
    the budget hog): ``serving_batch_buckets`` and
    ``serving_page_size`` on the tiny llama proxy — score is
    ``1/tokens_per_s + p99_ttft_s`` (ascending: throughput first,
    tail TTFT as the tiebreak).
    """
    import numpy as np

    import jax
    import mxnet_tpu as mx
    from mxnet_tpu import nd, telemetry, tuning
    from mxnet_tpu import graph as G
    from mxnet_tpu.gluon import HybridBlock, nn
    from mxnet_tpu.parallel import bucketing
    from mxnet_tpu.parallel.collectives import allreduce_hosts

    db = tuning.default_db()

    def timed_step(once, budget):
        """min step-wall over ``budget`` timeline steps (the PR 14
        gauge the training arms score with; min = least-noise)."""
        best = None
        for _ in range(budget):
            telemetry.step_begin()
            once()
            rec = telemetry.step_end()
            if best is None or rec["wall_s"] < best:
                best = rec["wall_s"]
        return best

    # -- allreduce_bucket_mb: the <=32KiB fused-allreduce regime ----------
    n_tensors, elems = 16, 8192
    vals = [jax.numpy.asarray(
        np.random.RandomState(i).randn(elems).astype("f"))
        for i in range(n_tensors)]
    entries = [(i, (elems,), "float32") for i in range(n_tensors)]
    bucket_sig = ("allreduce_small", n_tensors, elems, "float32")

    def measure_bucket(value, budget):
        # cap flows trial -> tuning.resolve -> bucket_cap_bytes ->
        # assign_buckets: exactly the path production bucketing takes
        plan = bucketing.assign_buckets(entries)

        def once():
            outs = []
            for b in plan.buckets:
                flat = bucketing.pack([vals[i] for i in b.keys])
                outs.extend(bucketing.unpack(
                    b, allreduce_hosts(flat, _testing_force=True)))
            jax.block_until_ready(outs)

        once()                              # warm every jit path
        return timed_step(once, budget)

    # -- graph_fuse_cap: deep elementwise chain ---------------------------
    class Chain(HybridBlock):
        def __init__(self, depth=24, **kw):
            super().__init__(**kw)
            self.depth = depth
            with self.name_scope():
                self.fc = nn.Dense(128, in_units=64)

        def hybrid_forward(self, F, x):
            h = self.fc(x)
            for _ in range(self.depth):
                h = F.tanh(h * 0.5 + 0.125)
            return h

    chain_seq = [0]

    def measure_fuse(value, budget):
        # a fresh net per trial: the fusion pass reads the cap at
        # pipeline time, and a cached optimized graph would measure
        # the previous trial's cap
        chain_seq[0] += 1
        mx.random.seed(0)
        np.random.seed(0)
        net = Chain(prefix=f"tunechain{chain_seq[0]}_")
        net.initialize()
        net.hybridize()
        x = nd.array(np.random.RandomState(1).randn(16, 64).astype("f"))
        with G.override_enabled(True):
            net(x).asnumpy()                # build under the trial cap
            for _ in range(3):
                net(x).asnumpy()

            def once():
                for _ in range(10):
                    y = net(x)
                y.asnumpy()

            return timed_step(once, budget)

    # -- prefetch_buffer: input-bound producer/consumer pipeline ----------
    def measure_prefetch(value, budget):
        from mxnet_tpu.gluon.data.prefetcher import PrefetchIterator

        n = 8 * budget

        def src():
            for i in range(n):
                time.sleep(0.001)           # host-side input staging
                yield np.full((4, 8), i % 7, "float32")

        telemetry.step_begin()
        it = PrefetchIterator(src())        # depth from the funnel
        for batch in it:
            time.sleep(0.001)               # the "compute" side
            jax.block_until_ready(batch)
        it.close()
        rec = telemetry.step_end()
        return rec["wall_s"] / n

    measures = {
        "allreduce_bucket_mb": (measure_bucket, bucket_sig, "s/step"),
        "graph_fuse_cap": (measure_fuse,
                           ("elemwise_chain", 24, 16, 64), "s/step"),
        "prefetch_buffer": (measure_prefetch,
                            ("prefetch_pipeline", 8), "s/batch"),
    }

    if serving:
        from mxnet_tpu import serving as _serving
        from mxnet_tpu.gluon.model_zoo.language.llama import llama_tiny

        def make_serving_measure():
            def measure(value, budget):
                net = llama_tiny()
                net.initialize()
                net(nd.zeros((1, 8), dtype="int32"))
                # batch buckets + page size resolve through the funnel
                # inside the ctor (the trial override is live here)
                eng = _serving.ServingEngine(
                    net, prefill_buckets=[8, 16], kv_pages=64,
                    max_batch=2)
                try:
                    eng.start()
                    rr = np.random.RandomState(0)
                    warm = eng.submit(rr.randint(1, 64, (3,)).astype(
                        "int32"), max_new_tokens=2)
                    warm.result(timeout=600)
                    # throughput phase: 2-deep closed loop
                    max_new, total = 4, 4 * budget
                    t0 = time.perf_counter()
                    pending = []
                    done = 0
                    for k in range(total):
                        pending.append(eng.submit(
                            rr.randint(1, 64, (1 + k % 8,)).astype(
                                "int32"), max_new_tokens=max_new))
                        while len(pending) >= 2:
                            pending.pop(0).result(timeout=600)
                            done += 1
                    for q in pending:
                        q.result(timeout=600)
                        done += 1
                    wall = time.perf_counter() - t0
                    tps = done * max_new / wall
                    # tail phase: max_new=1 completions ~ TTFT
                    lat = []
                    for k in range(2 * budget):
                        t1 = time.perf_counter()
                        eng.submit(rr.randint(1, 64, (4,)).astype(
                            "int32"), max_new_tokens=1).result(
                            timeout=600)
                        lat.append(time.perf_counter() - t1)
                    lat.sort()
                    p99 = lat[min(len(lat) - 1, int(0.99 * len(lat)))]
                finally:
                    eng.close()
                return 1.0 / max(tps, 1e-9) + p99
            return measure

        measures["serving_batch_buckets"] = (
            make_serving_measure(), ("llama_tiny_serving",),
            "1/tps+p99ttft_s")
        measures["serving_page_size"] = (
            make_serving_measure(), ("llama_tiny_serving",),
            "1/tps+p99ttft_s")

    selected = list(workloads) if workloads else \
        [k for k in measures if tuning.get_knob(k).kind == "training"
         or serving]
    reports = {}
    for name in selected:
        if name not in measures:
            reports[name] = {"error": f"no tune workload for {name!r}"}
            continue
        measure, sig, unit = measures[name]
        reports[name] = tuning.tune_knob(
            name, measure, db=db, signature=sig, rungs=rungs,
            budget0=budget0, unit=unit, log=lambda m: None)
    return reports


def tune_main(argv):
    """``bench.py --tune`` driver: run the search, persist winners,
    print ONE JSON line with best-vs-default deltas per knob + DB
    stats (the ci/tuning_smoke.py contract)."""
    import os

    platform, backend_error = _probe_backend()
    if platform is None:
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass
    workloads = None
    rungs, budget0 = 2, 2
    serving = "--tune-serving" in argv
    for arg in argv:
        if arg.startswith("--tune-workloads="):
            workloads = [w for w in
                         arg.split("=", 1)[1].split(",") if w]
        elif arg.startswith("--tune-rungs="):
            rungs = max(1, int(arg.split("=", 1)[1]))
        elif arg.startswith("--tune-budget="):
            budget0 = max(1, int(arg.split("=", 1)[1]))
    from mxnet_tpu import telemetry, tuning

    reports = bench_tune(workloads=workloads, rungs=rungs,
                         budget0=budget0, serving=serving)
    db = tuning.default_db()
    snap = telemetry.snapshot()["metrics"]

    def total(name):
        return sum(int(s["value"])
                   for s in snap.get(name, {}).get("samples", ()))

    out = {
        "metric": "tuning_search",
        "tune": reports,
        "db": db.stats() if db is not None else
        {"error": "MXNET_TUNE_DB_DIR unset; winners NOT persisted"},
        "trials_total": total("mxnet_tuning_trials_total"),
        "db_stores_total": total("mxnet_tuning_db_stores_total"),
        "knobs": _effective_knobs(),
    }
    if backend_error is not None:
        out["backend"] = "cpu_fallback"
    print(json.dumps(out))


def main():
    import os

    platform, backend_error = _probe_backend()
    if platform is None:
        # TPU unreachable: force CPU before ANY in-process backend touch so
        # we can still emit one parseable JSON line with smoke numbers
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass
        on_tpu = False
    else:
        import jax

        on_tpu = jax.default_backend() == "tpu"
    img_s, resnet_mfu, resnet_cfgs = bench_resnet50(on_tpu)
    extra = {"resnet_configs": resnet_cfgs}
    try:
        bert_s, bert_mfu = bench_bert(on_tpu)
        extra["bert_base_pretrain"] = {
            "value": round(bert_s, 2), "unit": "samples/s/chip",
            "mfu": round(bert_mfu, 4)}
    except Exception as e:  # keep the headline alive
        extra["bert_base_pretrain"] = {"error": repr(e)[:200]}
    try:
        llama_s, llama_mfu, llama_cfgs = bench_llama(on_tpu)
        extra["llama_proxy_train"] = {
            "value": round(llama_s, 2), "unit": "tokens/s/chip",
            "mfu": round(llama_mfu, 4), **llama_cfgs}
    except Exception as e:
        extra["llama_proxy_train"] = {"error": repr(e)[:200]}
    try:
        # tentpole observability (ISSUE 1): the eager dispatch fast path's
        # µs/op win, measured on whatever backend this run has
        extra["eager_op_overhead"] = bench_eager_op_overhead()
    except Exception as e:
        extra["eager_op_overhead"] = {"error": repr(e)[:200]}
    try:
        # overlap engine (ISSUE 4): input-bound prefetch A/B + fused
        # allreduce curve, so the next TPU driver run captures the win
        # unattended
        extra["overlap"] = bench_overlap()
    except Exception as e:
        extra["overlap"] = {"error": repr(e)[:200]}
    try:
        # serving engine (ISSUE 8): closed/open-loop load generation
        # against the AOT-compiled continuous-batching server — p50/p99
        # + tokens/s/chip per concurrency, with the zero-fresh-trace
        # steady-state contract measured, not assumed
        extra["serving"] = bench_serving()
    except Exception as e:
        extra["serving"] = {"error": repr(e)[:200]}
    try:
        # sharding planner (ISSUE 10): one-time plan cost, the
        # zero-per-step-cost pin, and the HBM model's estimated-vs-
        # actual bytes under two mesh shapes
        extra["planner"] = bench_planner()
    except Exception as e:
        extra["planner"] = {"error": repr(e)[:200]}
    try:
        # graph compiler (ISSUE 11): pass-pipeline one-time cost,
        # measured fused-op count, and optimized-vs-raw step time on
        # the llama proxy + a deep elementwise-chain microbench
        extra["graph"] = bench_graph()
    except Exception as e:
        extra["graph"] = {"error": repr(e)[:200]}
    try:
        # zero-downtime elasticity (ISSUE 13): restart-to-first-step
        # cold vs warm (compile cache), live ZeRO reshard vs checkpoint
        # round trip, serving replica handoff join-to-first-token
        extra["elastic"] = bench_elastic()
    except Exception as e:
        extra["elastic"] = {"error": repr(e)[:200]}
    try:
        # runtime introspection plane (ISSUE 14): A/B instrumentation
        # overhead (eager µs/op + serving tokens/s, tracing on vs off)
        # and the online-vs-offline MFU pin on the llama proxy (same
        # cost_analysis FLOPs source => tight tolerance)
        extra["observability"] = bench_observability()
    except Exception as e:
        extra["observability"] = {"error": repr(e)[:200]}
    try:
        # serving fleet router (ISSUE 17): closed-loop p50/p99 +
        # tokens/s at 1 vs 3 replicas (router overhead + scaling), and
        # kill-to-warm-replacement recovery time under load
        extra["fleet"] = bench_fleet()
    except Exception as e:
        extra["fleet"] = {"error": repr(e)[:200]}
    try:
        # numerical-integrity guard (ISSUE 20): arm-alternating A/B —
        # guard-on vs guard-off steps/s within noise (one fused
        # sentinel sync per step) with the compile counter flat over
        # the measured arms
        extra["guard"] = bench_guard()
    except Exception as e:
        extra["guard"] = {"error": repr(e)[:200]}
    try:
        # BASELINE binding metric: allreduce bandwidth (tools/bandwidth_
        # measure.py ≙ reference tools/bandwidth/measure.py).  The bus
        # formula is zero at one device, so the metric only reports on a
        # real multi-device mesh (pod / virtual mesh).
        import jax as _jax

        if len(_jax.devices()) > 1:
            import os as _os
            import sys as _sys

            _sys.path.insert(0, _os.path.join(
                _os.path.dirname(_os.path.abspath(__file__)), "tools"))
            import bandwidth_measure as _bwm

            dt, bw = _bwm.measure_allreduce(64 << 20, iters=5)
            extra["allreduce_bw_64mb"] = {"value": round(bw, 2),
                                          "unit": "GB/s"}
        else:
            extra["allreduce_bw_64mb"] = {
                "skipped": "single device (bus formula is 0 at n=1)"}
    except Exception as e:
        extra["allreduce_bw_64mb"] = {"error": repr(e)[:200]}
    try:
        # runtime telemetry (ISSUE 3): attach diagnosis context — cache
        # efficiency, compile pressure, and the step-phase breakdown — so
        # BENCH_*.json trajectories explain their throughput, not just
        # report it
        import mxnet_tpu as _mx
        from mxnet_tpu import telemetry as _telemetry

        snap = _telemetry.snapshot()
        ds = _mx.nd.dispatch_stats()
        looked = ds["hits"] + ds["misses"]
        # by_cause from the COUNTER family, not the bounded event ring —
        # a >512-compile retrace storm would otherwise undercount exactly
        # when the breakdown matters most
        by_cause = {}
        for s in snap["metrics"]["mxnet_compile_events_total"]["samples"]:
            cause = s["labels"].get("cause", "?")
            by_cause[cause] = by_cause.get(cause, 0) + int(s["value"])
        extra["telemetry"] = {
            "dispatch_cache": {
                "hit_rate": round(ds["hits"] / looked, 4) if looked else None,
                "hits": ds["hits"], "misses": ds["misses"],
                "evictions": ds["evictions"], "bypasses": ds["bypasses"]},
            "compile": {"count": snap["compile"]["count"],
                        "total_s": round(snap["compile"]["total_s"], 3),
                        "by_cause": by_cause},
            "step_phase_totals_s": {
                k: round(v, 4)
                for k, v in snap["step_phase_totals"].items()},
        }
    except Exception as e:
        extra["telemetry"] = {"error": repr(e)[:200]}

    # effective knob configuration (value + default/env/tuned source) in
    # EVERY result block: a number without its knob config is not
    # reproducible (ISSUE 16 satellite)
    knobs = _effective_knobs()
    for block in extra.values():
        if isinstance(block, dict):
            block["knobs"] = knobs

    out = {
        "metric": "resnet50_train_throughput",
        "value": round(img_s, 2),
        "unit": "img/s/chip",
        "vs_baseline": round(img_s / BASELINE_IMG_S_PER_CHIP, 4),
        "mfu": round(resnet_mfu, 4),
        "precision": "bf16_amp",
        "extra": extra,
    }
    if backend_error is not None:
        out["error"] = ("TPU backend unavailable; values are CPU smoke "
                        "numbers: " + backend_error)
        out["backend"] = "cpu_fallback"
    print(json.dumps(out))


if __name__ == "__main__":
    import sys as _sys

    if "--tune" in _sys.argv:
        try:
            tune_main(_sys.argv[1:])
        except Exception as e:  # the driver must ALWAYS get one JSON line
            print(json.dumps({"metric": "tuning_search", "tune": {},
                              "error": repr(e)[:300]}))
    else:
        try:
            main()
        except Exception as e:  # the driver must ALWAYS get one JSON line
            print(json.dumps({"metric": "resnet50_train_throughput",
                              "value": 0.0, "unit": "img/s/chip",
                              "vs_baseline": 0.0, "error": repr(e)[:300]}))
