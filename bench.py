"""Headline benchmark: ResNet-50 fused training-step throughput (img/s).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline: the reference's recalled ResNet-50 fp32 per-accelerator training
throughput on V100 (~350 img/s/GPU mid-range of BASELINE.md's 310–390) —
the north-star target is per-chip parity within 10%.
"""
from __future__ import annotations

import json
import time

import numpy as np

BASELINE_IMG_S_PER_CHIP = 350.0


def main():
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu.parallel.data_parallel import TrainStep

    on_tpu = jax.default_backend() == "tpu"
    batch = 128 if on_tpu else 16
    size = 224 if on_tpu else 64

    net = vision.resnet50_v1()
    net.initialize(ctx=mx.current_context())
    net(mx.nd.zeros((1, 3, size, size)))  # settle deferred param shapes

    def loss_fn(logits, labels):
        import jax.numpy as jnp

        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.take_along_axis(logp, labels[:, None], axis=-1)

    step = TrainStep(net, loss_fn, optimizer="sgd",
                     optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
                     train_mode=True)

    x = np.random.uniform(-1, 1, (batch, 3, size, size)).astype("float32")
    y = np.random.randint(0, 1000, (batch,)).astype("int32")

    # warmup/compile
    for _ in range(2):
        step(x, y).block_until_ready()

    iters = 10 if on_tpu else 3
    t0 = time.perf_counter()
    for _ in range(iters):
        loss = step(x, y)
    loss.block_until_ready()
    dt = time.perf_counter() - t0

    img_s = batch * iters / dt
    # scale CPU-smoke result is not comparable; report raw value regardless
    print(json.dumps({
        "metric": "resnet50_train_throughput",
        "value": round(img_s, 2),
        "unit": "img/s/chip",
        "vs_baseline": round(img_s / BASELINE_IMG_S_PER_CHIP, 4),
    }))


if __name__ == "__main__":
    main()
